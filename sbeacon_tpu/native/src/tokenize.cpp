// VCF record tokenizer: one native pass replacing the per-line Python
// parse (genomics/vcf.parse_record) for the columnar fast path.
//
// Native-component parity (SURVEY.md §2.1): this is the record-header
// walk of the reference's summariseSlice hot loop (reference:
// lambda/summariseSlice/source/main.cpp:230-237 recordHeader + addCounts,
// vcf_chunk_reader.h readPastChars/skipPast byte scanning) generalised to
// emit every field the index build needs as flat arrays: positions, field
// spans (offsets into the caller's text buffer), per-alt spans, INFO
// AC/AN/VT, genotype-derived allele/token tallies (the effective_ac/an
// fallback of genomics/vcf.VcfRecord), and NORMALISED per-sample GT cells
// for the genotype-plane builder (gt_planes.cpp).
//
// Semantics mirror parse_record exactly: lines starting '#' or empty are
// skipped, lines with <8 tab-separated fields are skipped, only '\n' is
// treated as a line terminator (a '\r' stays inside the last field), the
// LAST AC=/AN=/VT= occurrence in INFO wins, and an unparseable AC/AN
// value yields "absent" (python int() -> ValueError -> None).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

template <typename T>
T* CopyOut(const std::vector<T>& v) {
  T* p = static_cast<T*>(std::malloc(v.empty() ? sizeof(T) : v.size() * sizeof(T)));
  if (p && !v.empty()) std::memcpy(p, v.data(), v.size() * sizeof(T));
  return p;
}

// python int(): optional sign then digits, nothing else. Returns false on
// any deviation (caller treats the field as absent).
inline bool ParseInt(const char* p, const char* end, int64_t* out) {
  if (p >= end) return false;
  bool neg = false;
  if (*p == '+' || *p == '-') {
    neg = (*p == '-');
    ++p;
    if (p >= end) return false;
  }
  int64_t v = 0;
  for (; p < end; ++p) {
    if (*p < '0' || *p > '9') return false;
    if (v > (INT64_MAX - 9) / 10) return false;  // overflow -> "absent"
    v = v * 10 + (*p - '0');
  }
  *out = neg ? -v : v;
  return true;
}

}  // namespace

extern "C" {

int sbn_tokenize(
    const uint8_t* text, uint64_t len, uint64_t n_samples,
    // per-record (n_rec)
    int64_t** pos_out,
    uint32_t** chrom_off_out, uint32_t** chrom_len_out,
    uint32_t** ref_off_out, uint32_t** ref_len_out,
    uint32_t** vt_off_out, uint32_t** vt_len_out,
    int64_t** an_out, uint8_t** has_an_out, uint8_t** has_ac_out,
    int64_t** tok_total_out,
    // flat per-alt (n_alt) + starts (n_rec+1)
    uint32_t** alt_off_out, uint32_t** alt_len_out, uint64_t** alt_start_out,
    int64_t** ac_gt_out,  // genotype tally per alt, aligned with alt_start
    // INFO AC values (n_ac) + starts (n_rec+1)
    int64_t** ac_out, uint64_t** ac_start_out,
    // normalised GT cells: blob + offsets [n_rec*n_samples+1]
    uint8_t** gt_blob_out, uint64_t** gt_off_out,
    uint64_t* n_rec_out, uint64_t* n_alt_out, uint64_t* n_ac_out,
    uint64_t* gt_blob_len_out) {
  const char* base = reinterpret_cast<const char*>(text);
  const char* p = base;
  const char* end = p + len;

  std::vector<int64_t> pos, an, tok_total, ac, ac_gt;
  std::vector<uint32_t> chrom_off, chrom_len, ref_off, ref_len;
  std::vector<uint32_t> vt_off, vt_len, alt_off, alt_len;
  std::vector<uint64_t> alt_start{0}, ac_start{0}, gt_off{0};
  std::vector<uint8_t> has_an, has_ac, gt_blob;
  std::vector<std::pair<uint32_t, uint32_t>> fields;  // reused per line

  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    const char* le = nl ? nl : end;
    if (p < le && *p != '#') {
      // split the line on tabs
      fields.clear();
      const char* f = p;
      while (true) {
        const char* t = static_cast<const char*>(
            std::memchr(f, '\t', size_t(le - f)));
        const char* fe = t ? t : le;
        fields.emplace_back(uint32_t(f - base), uint32_t(fe - f));
        if (!t) break;
        f = t + 1;
      }
      if (fields.size() < 8) {
        if (!nl) break;
        p = nl + 1;
        continue;
      }
      int64_t pv;
      const char* ps = base + fields[1].first;
      if (!ParseInt(ps, ps + fields[1].second, &pv)) {
        if (!nl) break;  // malformed POS: skip line (python would raise)
        p = nl + 1;
        continue;
      }
      pos.push_back(pv);
      chrom_off.push_back(fields[0].first);
      chrom_len.push_back(fields[0].second);
      ref_off.push_back(fields[3].first);
      ref_len.push_back(fields[3].second);

      // ALT column -> per-alt spans (split on ',')
      {
        const char* a = base + fields[4].first;
        const char* ae = a + fields[4].second;
        const char* s = a;
        while (true) {
          const char* c = static_cast<const char*>(
              std::memchr(s, ',', size_t(ae - s)));
          const char* se = c ? c : ae;
          alt_off.push_back(uint32_t(s - base));
          alt_len.push_back(uint32_t(se - s));
          if (!c) break;
          s = c + 1;
        }
      }
      const uint64_t rec_alt_begin = alt_start.back();
      alt_start.push_back(alt_len.size());
      const uint64_t rec_n_alts = alt_len.size() - rec_alt_begin;

      // INFO: AC= / AN= / VT=, LAST occurrence wins
      uint8_t h_ac = 0, h_an = 0;
      int64_t an_v = 0;
      uint32_t vt_o = 0, vt_l = 0;
      const uint64_t rec_ac_begin = ac.size();
      {
        const char* q = base + fields[7].first;
        const char* qe = q + fields[7].second;
        while (q < qe) {
          const char* sc = static_cast<const char*>(
              std::memchr(q, ';', size_t(qe - q)));
          const char* fe2 = sc ? sc : qe;
          if (fe2 - q >= 3 && q[2] == '=') {
            if (q[0] == 'A' && q[1] == 'C') {
              ac.resize(rec_ac_begin);  // last AC= wins
              h_ac = 1;
              const char* v = q + 3;
              while (v <= fe2) {
                const char* cm = static_cast<const char*>(
                    std::memchr(v, ',', size_t(fe2 - v)));
                const char* ve = cm ? cm : fe2;
                int64_t cv;
                if (!ParseInt(v, ve, &cv)) {
                  h_ac = 0;  // python: any bad entry -> ac = None
                  ac.resize(rec_ac_begin);
                  break;
                }
                ac.push_back(cv);
                if (!cm) break;
                v = cm + 1;
              }
            } else if (q[0] == 'A' && q[1] == 'N') {
              h_an = ParseInt(q + 3, fe2, &an_v) ? 1 : 0;
            } else if (q[0] == 'V' && q[1] == 'T') {
              vt_o = uint32_t(q + 3 - base);
              vt_l = uint32_t(fe2 - (q + 3));
            }
          }
          if (!sc) break;
          q = sc + 1;
        }
      }
      has_ac.push_back(h_ac);
      has_an.push_back(h_an);
      an.push_back(h_an ? an_v : 0);
      vt_off.push_back(vt_o);
      vt_len.push_back(vt_l);
      ac_start.push_back(ac.size());

      // FORMAT + samples: genotypes only when >9 fields (parse_record)
      int gt_idx = -1;
      if (fields.size() > 9) {
        const char* fm = base + fields[8].first;
        const char* fme = fm + fields[8].second;
        int idx = 0;
        const char* s = fm;
        while (true) {
          const char* c = static_cast<const char*>(
              std::memchr(s, ':', size_t(fme - s)));
          const char* se = c ? c : fme;
          if (se - s == 2 && s[0] == 'G' && s[1] == 'T') {
            gt_idx = idx;
            break;
          }
          if (!c) break;
          s = c + 1;
          ++idx;
        }
      }
      ac_gt.resize(ac_gt.size() + rec_n_alts, 0);
      int64_t* rec_ac_gt = ac_gt.data() + (ac_gt.size() - rec_n_alts);
      int64_t toks = 0;
      uint64_t cells_emitted = 0;
      if (gt_idx >= 0) {
        for (size_t col = 9; col < fields.size(); ++col) {
          // the gt_idx-th ':'-separated piece of this sample column
          const char* s = base + fields[col].first;
          const char* se = s + fields[col].second;
          const char* gs = s;
          int idx = 0;
          const char* ge = nullptr;
          while (idx <= gt_idx) {
            const char* c = static_cast<const char*>(
                std::memchr(gs, ':', size_t(se - gs)));
            if (idx == gt_idx) {
              ge = c ? c : se;
              break;
            }
            if (!c) break;  // fewer pieces than gt_idx: python yields '.'
            gs = c + 1;
            ++idx;
          }
          // token scan over the GT piece (absent piece = '.', tokenless)
          if (ge != nullptr) {
            for (const char* c = gs; c < ge;) {
              if (*c >= '0' && *c <= '9') {
                int64_t v = 0;
                while (c < ge && *c >= '0' && *c <= '9') {
                  if (v < (int64_t(1) << 40))
                    v = v * 10 + (*c - '0');
                  ++c;
                }
                ++toks;
                if (v >= 1 && uint64_t(v) <= rec_n_alts)
                  ++rec_ac_gt[v - 1];
              } else {
                ++c;
              }
            }
          }
          // normalised cell (first n_samples columns only)
          if (cells_emitted < n_samples) {
            if (ge != nullptr) {
              gt_blob.insert(gt_blob.end(),
                             reinterpret_cast<const uint8_t*>(gs),
                             reinterpret_cast<const uint8_t*>(ge));
            }
            gt_off.push_back(gt_blob.size());
            ++cells_emitted;
          }
        }
      }
      while (cells_emitted < n_samples) {  // pad missing cells empty
        gt_off.push_back(gt_blob.size());
        ++cells_emitted;
      }
      tok_total.push_back(toks);
    }
    if (!nl) break;
    p = nl + 1;
  }

  *pos_out = CopyOut(pos);
  *chrom_off_out = CopyOut(chrom_off);
  *chrom_len_out = CopyOut(chrom_len);
  *ref_off_out = CopyOut(ref_off);
  *ref_len_out = CopyOut(ref_len);
  *vt_off_out = CopyOut(vt_off);
  *vt_len_out = CopyOut(vt_len);
  *an_out = CopyOut(an);
  *has_an_out = CopyOut(has_an);
  *has_ac_out = CopyOut(has_ac);
  *tok_total_out = CopyOut(tok_total);
  *alt_off_out = CopyOut(alt_off);
  *alt_len_out = CopyOut(alt_len);
  *alt_start_out = CopyOut(alt_start);
  *ac_gt_out = CopyOut(ac_gt);
  *ac_out = CopyOut(ac);
  *ac_start_out = CopyOut(ac_start);
  *gt_blob_out = CopyOut(gt_blob);
  *gt_off_out = CopyOut(gt_off);
  *n_rec_out = pos.size();
  *n_alt_out = alt_len.size();
  *n_ac_out = ac.size();
  *gt_blob_len_out = gt_blob.size();
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused tokenizer + genotype-plane builder (round-4 ingest hot path).
//
// sbn_tokenize walked every sample column to build a normalised GT text
// blob that sbn_gt_planes then re-parsed per (row, sample) — two full
// scans of ~90% of the input bytes plus a blob copy. This single pass
// emits the same record/field arrays AND the four bit planes directly:
// per GT cell the tokens are parsed once into a small buffer, tallied
// against every alt of the record, and the bits written to text-order
// plane rows (the caller reorders rows with one numpy gather, and maps
// the overflow triples the same way). Cell semantics are identical to
// the blob path: digit-run tokens (get_all_calls regex), absent/short
// GT piece = tokenless, columns beyond n_samples still count toward
// tok_total/ac_gt but carry no plane bits.

extern "C" int sbn_tokenize_planes(
    const uint8_t* text, uint64_t len, uint64_t n_samples, uint64_t words,
    int64_t** pos_out,
    uint32_t** chrom_off_out, uint32_t** chrom_len_out,
    uint32_t** ref_off_out, uint32_t** ref_len_out,
    uint32_t** vt_off_out, uint32_t** vt_len_out,
    int64_t** an_out, uint8_t** has_an_out, uint8_t** has_ac_out,
    int64_t** tok_total_out,
    uint32_t** alt_off_out, uint32_t** alt_len_out, uint64_t** alt_start_out,
    int64_t** ac_gt_out,
    int64_t** ac_out, uint64_t** ac_start_out,
    // planes: per flat-alt row (text order) and per record
    uint32_t** g1_out, uint32_t** g2_out,      // [n_alt * words]
    uint32_t** t1_out, uint32_t** t2_out,      // [n_rec * words]
    // overflow triples: (flat_alt_row, sample, copies) / (rec, sample, ntok)
    int64_t** gt_over_out, uint64_t* n_gt_over,
    int64_t** tok_over_out, uint64_t* n_tok_over,
    uint64_t* n_rec_out, uint64_t* n_alt_out, uint64_t* n_ac_out) {
  const char* base = reinterpret_cast<const char*>(text);
  const char* p = base;
  const char* end = p + len;

  std::vector<int64_t> pos, an, tok_total, ac, ac_gt;
  std::vector<uint32_t> chrom_off, chrom_len, ref_off, ref_len;
  std::vector<uint32_t> vt_off, vt_len, alt_off, alt_len;
  std::vector<uint64_t> alt_start{0}, ac_start{0};
  std::vector<uint8_t> has_an, has_ac;
  std::vector<uint32_t> g1, g2, t1, t2;
  std::vector<int64_t> gt_over, tok_over;
  std::vector<int32_t> spill;  // token values beyond the stack buffer

  // reserve from a cheap line estimate (sample-heavy lines are ~10 kB)
  const uint64_t est_rec = len / 512 + 16;
  pos.reserve(est_rec);

  uint32_t fixed_off[9];
  uint32_t fixed_len[9];

  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    const char* le = nl ? nl : end;
    if (p < le && *p != '#') {
      // first 9 fields only; the rest are streamed in place
      int nf = 0;
      const char* f = p;
      const char* rest = nullptr;  // first sample column (field 9)
      while (nf < 9) {
        const char* t = static_cast<const char*>(
            std::memchr(f, '\t', size_t(le - f)));
        const char* fe = t ? t : le;
        fixed_off[nf] = uint32_t(f - base);
        fixed_len[nf] = uint32_t(fe - f);
        ++nf;
        if (!t) break;
        f = t + 1;
        if (nf == 9) rest = f;
      }
      if (nf < 8) {
        if (!nl) break;
        p = nl + 1;
        continue;
      }
      int64_t pv;
      const char* ps = base + fixed_off[1];
      if (!ParseInt(ps, ps + fixed_len[1], &pv)) {
        if (!nl) break;
        p = nl + 1;
        continue;
      }
      pos.push_back(pv);
      chrom_off.push_back(fixed_off[0]);
      chrom_len.push_back(fixed_len[0]);
      ref_off.push_back(fixed_off[3]);
      ref_len.push_back(fixed_len[3]);

      // ALT -> per-alt spans
      {
        const char* a = base + fixed_off[4];
        const char* ae = a + fixed_len[4];
        const char* s = a;
        while (true) {
          const char* c = static_cast<const char*>(
              std::memchr(s, ',', size_t(ae - s)));
          const char* se = c ? c : ae;
          alt_off.push_back(uint32_t(s - base));
          alt_len.push_back(uint32_t(se - s));
          if (!c) break;
          s = c + 1;
        }
      }
      const uint64_t rec_alt_begin = alt_start.back();
      alt_start.push_back(alt_len.size());
      const uint64_t rec_n_alts = alt_len.size() - rec_alt_begin;
      const uint64_t rec_index = pos.size() - 1;

      // grow plane rows for this record (zero-filled)
      g1.resize(alt_len.size() * words, 0u);
      g2.resize(alt_len.size() * words, 0u);
      t1.resize(pos.size() * words, 0u);
      t2.resize(pos.size() * words, 0u);
      uint32_t* g1r = g1.data() + rec_alt_begin * words;
      uint32_t* g2r = g2.data() + rec_alt_begin * words;
      uint32_t* t1r = t1.data() + rec_index * words;
      uint32_t* t2r = t2.data() + rec_index * words;

      // INFO: AC= / AN= / VT= (last occurrence wins)
      uint8_t h_ac = 0, h_an = 0;
      int64_t an_v = 0;
      uint32_t vt_o = 0, vt_l = 0;
      const uint64_t rec_ac_begin = ac.size();
      {
        const char* q = base + fixed_off[7];
        const char* qe = q + fixed_len[7];
        while (q < qe) {
          const char* sc = static_cast<const char*>(
              std::memchr(q, ';', size_t(qe - q)));
          const char* fe2 = sc ? sc : qe;
          if (fe2 - q >= 3 && q[2] == '=') {
            if (q[0] == 'A' && q[1] == 'C') {
              ac.resize(rec_ac_begin);
              h_ac = 1;
              const char* v = q + 3;
              while (v <= fe2) {
                const char* cm = static_cast<const char*>(
                    std::memchr(v, ',', size_t(fe2 - v)));
                const char* ve = cm ? cm : fe2;
                int64_t cv;
                if (!ParseInt(v, ve, &cv)) {
                  h_ac = 0;
                  ac.resize(rec_ac_begin);
                  break;
                }
                ac.push_back(cv);
                if (!cm) break;
                v = cm + 1;
              }
            } else if (q[0] == 'A' && q[1] == 'N') {
              h_an = ParseInt(q + 3, fe2, &an_v) ? 1 : 0;
            } else if (q[0] == 'V' && q[1] == 'T') {
              vt_o = uint32_t(q + 3 - base);
              vt_l = uint32_t(fe2 - (q + 3));
            }
          }
          if (!sc) break;
          q = sc + 1;
        }
      }
      has_ac.push_back(h_ac);
      has_an.push_back(h_an);
      an.push_back(h_an ? an_v : 0);
      vt_off.push_back(vt_o);
      vt_len.push_back(vt_l);
      ac_start.push_back(ac.size());

      // FORMAT: locate GT piece index
      int gt_idx = -1;
      if (rest != nullptr) {
        const char* fm = base + fixed_off[8];
        const char* fme = fm + fixed_len[8];
        int idx = 0;
        const char* s = fm;
        while (true) {
          const char* c = static_cast<const char*>(
              std::memchr(s, ':', size_t(fme - s)));
          const char* se = c ? c : fme;
          if (se - s == 2 && s[0] == 'G' && s[1] == 'T') {
            gt_idx = idx;
            break;
          }
          if (!c) break;
          s = c + 1;
          ++idx;
        }
      }

      ac_gt.resize(ac_gt.size() + rec_n_alts, 0);
      int64_t* rec_ac_gt = ac_gt.data() + (ac_gt.size() - rec_n_alts);
      int64_t toks = 0;

      if (gt_idx >= 0 && rest != nullptr) {
        uint64_t col = 0;  // sample index
        const char* s = rest;
        while (s <= le) {
          const char* t = static_cast<const char*>(
              std::memchr(s, '\t', size_t(le - s)));
          const char* ce = t ? t : le;  // this sample column
          // GT piece: the gt_idx-th ':'-separated slice
          const char* gs = s;
          const char* ge = nullptr;
          if (gt_idx == 0) {
            const char* c = static_cast<const char*>(
                std::memchr(gs, ':', size_t(ce - gs)));
            ge = c ? c : ce;
          } else {
            int idx = 0;
            while (idx <= gt_idx) {
              const char* c = static_cast<const char*>(
                  std::memchr(gs, ':', size_t(ce - gs)));
              if (idx == gt_idx) {
                ge = c ? c : ce;
                break;
              }
              if (!c) break;
              gs = c + 1;
              ++idx;
            }
          }
          int32_t tv_stack[16];
          int ntv = 0;
          spill.clear();
          int64_t cell_toks = 0;
          if (ge != nullptr) {
            // fast path: the overwhelming diploid shape d[|/]d
            if (ge - gs == 3 && gs[0] >= '0' && gs[0] <= '9' &&
                (gs[1] == '|' || gs[1] == '/') && gs[2] >= '0' &&
                gs[2] <= '9') {
              tv_stack[0] = gs[0] - '0';
              tv_stack[1] = gs[2] - '0';
              ntv = 2;
              cell_toks = 2;
            } else {
              for (const char* c = gs; c < ge;) {
                if (*c >= '0' && *c <= '9') {
                  int64_t v = 0;
                  while (c < ge && *c >= '0' && *c <= '9') {
                    if (v <= INT32_MAX) v = v * 10 + (*c - '0');
                    if (v > INT32_MAX) v = INT32_MAX;
                    ++c;
                  }
                  ++cell_toks;
                  if (ntv < 16) {
                    tv_stack[ntv++] = int32_t(v);
                  } else {
                    spill.push_back(int32_t(v));
                  }
                } else {
                  ++c;
                }
              }
            }
          }
          toks += cell_toks;
          // per-alt tally (all columns, like the unfused tokenizer)
          for (int k = 0; k < ntv; ++k) {
            int32_t v = tv_stack[k];
            if (v >= 1 && uint64_t(v) <= rec_n_alts) ++rec_ac_gt[v - 1];
          }
          for (int32_t v : spill) {
            if (v >= 1 && uint64_t(v) <= rec_n_alts) ++rec_ac_gt[v - 1];
          }
          // plane bits for the first n_samples columns
          if (col < n_samples) {
            const uint32_t bit = 1u << (col % 32);
            const uint64_t w = col / 32;
            if (cell_toks >= 1) t1r[w] |= bit;
            if (cell_toks >= 2) t2r[w] |= bit;
            if (cell_toks > 2) {
              tok_over.push_back(int64_t(rec_index));
              tok_over.push_back(int64_t(col));
              tok_over.push_back(cell_toks);
            }
            for (uint64_t a = 1; a <= rec_n_alts; ++a) {
              int copies = 0;
              for (int k = 0; k < ntv; ++k)
                copies += (tv_stack[k] == int32_t(a));
              for (int32_t v : spill) copies += (v == int32_t(a));
              if (copies >= 1) {
                uint32_t* row = g1r + (a - 1) * words;
                row[w] |= bit;
                if (copies >= 2) g2r[(a - 1) * words + w] |= bit;
                if (copies > 2) {
                  gt_over.push_back(int64_t(rec_alt_begin + a - 1));
                  gt_over.push_back(int64_t(col));
                  gt_over.push_back(copies);
                }
              }
            }
          }
          ++col;
          if (!t) break;
          s = t + 1;
        }
      }
      tok_total.push_back(toks);
    }
    if (!nl) break;
    p = nl + 1;
  }

  *pos_out = CopyOut(pos);
  *chrom_off_out = CopyOut(chrom_off);
  *chrom_len_out = CopyOut(chrom_len);
  *ref_off_out = CopyOut(ref_off);
  *ref_len_out = CopyOut(ref_len);
  *vt_off_out = CopyOut(vt_off);
  *vt_len_out = CopyOut(vt_len);
  *an_out = CopyOut(an);
  *has_an_out = CopyOut(has_an);
  *has_ac_out = CopyOut(has_ac);
  *tok_total_out = CopyOut(tok_total);
  *alt_off_out = CopyOut(alt_off);
  *alt_len_out = CopyOut(alt_len);
  *alt_start_out = CopyOut(alt_start);
  *ac_gt_out = CopyOut(ac_gt);
  *ac_out = CopyOut(ac);
  *ac_start_out = CopyOut(ac_start);
  *g1_out = CopyOut(g1);
  *g2_out = CopyOut(g2);
  *t1_out = CopyOut(t1);
  *t2_out = CopyOut(t2);
  *gt_over_out = CopyOut(gt_over);
  *tok_over_out = CopyOut(tok_over);
  *n_gt_over = gt_over.size() / 3;
  *n_tok_over = tok_over.size() / 3;
  *n_rec_out = pos.size();
  *n_alt_out = alt_len.size();
  *n_ac_out = ac.size();
  return 0;
}
