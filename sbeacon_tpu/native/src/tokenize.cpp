// VCF record tokenizer: one native pass replacing the per-line Python
// parse (genomics/vcf.parse_record) for the columnar fast path.
//
// Native-component parity (SURVEY.md §2.1): this is the record-header
// walk of the reference's summariseSlice hot loop (reference:
// lambda/summariseSlice/source/main.cpp:230-237 recordHeader + addCounts,
// vcf_chunk_reader.h readPastChars/skipPast byte scanning) generalised to
// emit every field the index build needs as flat arrays: positions, field
// spans (offsets into the caller's text buffer), per-alt spans, INFO
// AC/AN/VT, genotype-derived allele/token tallies (the effective_ac/an
// fallback of genomics/vcf.VcfRecord), and NORMALISED per-sample GT cells
// for the genotype-plane builder (gt_planes.cpp).
//
// Semantics mirror parse_record exactly: lines starting '#' or empty are
// skipped, lines with <8 tab-separated fields are skipped, only '\n' is
// treated as a line terminator (a '\r' stays inside the last field), the
// LAST AC=/AN=/VT= occurrence in INFO wins, and an unparseable AC/AN
// value yields "absent" (python int() -> ValueError -> None).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

template <typename T>
T* CopyOut(const std::vector<T>& v) {
  T* p = static_cast<T*>(std::malloc(v.empty() ? sizeof(T) : v.size() * sizeof(T)));
  if (p && !v.empty()) std::memcpy(p, v.data(), v.size() * sizeof(T));
  return p;
}

// python int(): optional sign then digits, nothing else. Returns false on
// any deviation (caller treats the field as absent).
inline bool ParseInt(const char* p, const char* end, int64_t* out) {
  if (p >= end) return false;
  bool neg = false;
  if (*p == '+' || *p == '-') {
    neg = (*p == '-');
    ++p;
    if (p >= end) return false;
  }
  int64_t v = 0;
  for (; p < end; ++p) {
    if (*p < '0' || *p > '9') return false;
    if (v > (INT64_MAX - 9) / 10) return false;  // overflow -> "absent"
    v = v * 10 + (*p - '0');
  }
  *out = neg ? -v : v;
  return true;
}

}  // namespace

extern "C" {

int sbn_tokenize(
    const uint8_t* text, uint64_t len, uint64_t n_samples,
    // per-record (n_rec)
    int64_t** pos_out,
    uint32_t** chrom_off_out, uint32_t** chrom_len_out,
    uint32_t** ref_off_out, uint32_t** ref_len_out,
    uint32_t** vt_off_out, uint32_t** vt_len_out,
    int64_t** an_out, uint8_t** has_an_out, uint8_t** has_ac_out,
    int64_t** tok_total_out,
    // flat per-alt (n_alt) + starts (n_rec+1)
    uint32_t** alt_off_out, uint32_t** alt_len_out, uint64_t** alt_start_out,
    int64_t** ac_gt_out,  // genotype tally per alt, aligned with alt_start
    // INFO AC values (n_ac) + starts (n_rec+1)
    int64_t** ac_out, uint64_t** ac_start_out,
    // normalised GT cells: blob + offsets [n_rec*n_samples+1]
    uint8_t** gt_blob_out, uint64_t** gt_off_out,
    uint64_t* n_rec_out, uint64_t* n_alt_out, uint64_t* n_ac_out,
    uint64_t* gt_blob_len_out) {
  const char* base = reinterpret_cast<const char*>(text);
  const char* p = base;
  const char* end = p + len;

  std::vector<int64_t> pos, an, tok_total, ac, ac_gt;
  std::vector<uint32_t> chrom_off, chrom_len, ref_off, ref_len;
  std::vector<uint32_t> vt_off, vt_len, alt_off, alt_len;
  std::vector<uint64_t> alt_start{0}, ac_start{0}, gt_off{0};
  std::vector<uint8_t> has_an, has_ac, gt_blob;
  std::vector<std::pair<uint32_t, uint32_t>> fields;  // reused per line

  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    const char* le = nl ? nl : end;
    if (p < le && *p != '#') {
      // split the line on tabs
      fields.clear();
      const char* f = p;
      while (true) {
        const char* t = static_cast<const char*>(
            std::memchr(f, '\t', size_t(le - f)));
        const char* fe = t ? t : le;
        fields.emplace_back(uint32_t(f - base), uint32_t(fe - f));
        if (!t) break;
        f = t + 1;
      }
      if (fields.size() < 8) {
        if (!nl) break;
        p = nl + 1;
        continue;
      }
      int64_t pv;
      const char* ps = base + fields[1].first;
      if (!ParseInt(ps, ps + fields[1].second, &pv)) {
        if (!nl) break;  // malformed POS: skip line (python would raise)
        p = nl + 1;
        continue;
      }
      pos.push_back(pv);
      chrom_off.push_back(fields[0].first);
      chrom_len.push_back(fields[0].second);
      ref_off.push_back(fields[3].first);
      ref_len.push_back(fields[3].second);

      // ALT column -> per-alt spans (split on ',')
      {
        const char* a = base + fields[4].first;
        const char* ae = a + fields[4].second;
        const char* s = a;
        while (true) {
          const char* c = static_cast<const char*>(
              std::memchr(s, ',', size_t(ae - s)));
          const char* se = c ? c : ae;
          alt_off.push_back(uint32_t(s - base));
          alt_len.push_back(uint32_t(se - s));
          if (!c) break;
          s = c + 1;
        }
      }
      const uint64_t rec_alt_begin = alt_start.back();
      alt_start.push_back(alt_len.size());
      const uint64_t rec_n_alts = alt_len.size() - rec_alt_begin;

      // INFO: AC= / AN= / VT=, LAST occurrence wins
      uint8_t h_ac = 0, h_an = 0;
      int64_t an_v = 0;
      uint32_t vt_o = 0, vt_l = 0;
      const uint64_t rec_ac_begin = ac.size();
      {
        const char* q = base + fields[7].first;
        const char* qe = q + fields[7].second;
        while (q < qe) {
          const char* sc = static_cast<const char*>(
              std::memchr(q, ';', size_t(qe - q)));
          const char* fe2 = sc ? sc : qe;
          if (fe2 - q >= 3 && q[2] == '=') {
            if (q[0] == 'A' && q[1] == 'C') {
              ac.resize(rec_ac_begin);  // last AC= wins
              h_ac = 1;
              const char* v = q + 3;
              while (v <= fe2) {
                const char* cm = static_cast<const char*>(
                    std::memchr(v, ',', size_t(fe2 - v)));
                const char* ve = cm ? cm : fe2;
                int64_t cv;
                if (!ParseInt(v, ve, &cv)) {
                  h_ac = 0;  // python: any bad entry -> ac = None
                  ac.resize(rec_ac_begin);
                  break;
                }
                ac.push_back(cv);
                if (!cm) break;
                v = cm + 1;
              }
            } else if (q[0] == 'A' && q[1] == 'N') {
              h_an = ParseInt(q + 3, fe2, &an_v) ? 1 : 0;
            } else if (q[0] == 'V' && q[1] == 'T') {
              vt_o = uint32_t(q + 3 - base);
              vt_l = uint32_t(fe2 - (q + 3));
            }
          }
          if (!sc) break;
          q = sc + 1;
        }
      }
      has_ac.push_back(h_ac);
      has_an.push_back(h_an);
      an.push_back(h_an ? an_v : 0);
      vt_off.push_back(vt_o);
      vt_len.push_back(vt_l);
      ac_start.push_back(ac.size());

      // FORMAT + samples: genotypes only when >9 fields (parse_record)
      int gt_idx = -1;
      if (fields.size() > 9) {
        const char* fm = base + fields[8].first;
        const char* fme = fm + fields[8].second;
        int idx = 0;
        const char* s = fm;
        while (true) {
          const char* c = static_cast<const char*>(
              std::memchr(s, ':', size_t(fme - s)));
          const char* se = c ? c : fme;
          if (se - s == 2 && s[0] == 'G' && s[1] == 'T') {
            gt_idx = idx;
            break;
          }
          if (!c) break;
          s = c + 1;
          ++idx;
        }
      }
      ac_gt.resize(ac_gt.size() + rec_n_alts, 0);
      int64_t* rec_ac_gt = ac_gt.data() + (ac_gt.size() - rec_n_alts);
      int64_t toks = 0;
      uint64_t cells_emitted = 0;
      if (gt_idx >= 0) {
        for (size_t col = 9; col < fields.size(); ++col) {
          // the gt_idx-th ':'-separated piece of this sample column
          const char* s = base + fields[col].first;
          const char* se = s + fields[col].second;
          const char* gs = s;
          int idx = 0;
          const char* ge = nullptr;
          while (idx <= gt_idx) {
            const char* c = static_cast<const char*>(
                std::memchr(gs, ':', size_t(se - gs)));
            if (idx == gt_idx) {
              ge = c ? c : se;
              break;
            }
            if (!c) break;  // fewer pieces than gt_idx: python yields '.'
            gs = c + 1;
            ++idx;
          }
          // token scan over the GT piece (absent piece = '.', tokenless)
          if (ge != nullptr) {
            for (const char* c = gs; c < ge;) {
              if (*c >= '0' && *c <= '9') {
                int64_t v = 0;
                while (c < ge && *c >= '0' && *c <= '9') {
                  if (v < (int64_t(1) << 40))
                    v = v * 10 + (*c - '0');
                  ++c;
                }
                ++toks;
                if (v >= 1 && uint64_t(v) <= rec_n_alts)
                  ++rec_ac_gt[v - 1];
              } else {
                ++c;
              }
            }
          }
          // normalised cell (first n_samples columns only)
          if (cells_emitted < n_samples) {
            if (ge != nullptr) {
              gt_blob.insert(gt_blob.end(),
                             reinterpret_cast<const uint8_t*>(gs),
                             reinterpret_cast<const uint8_t*>(ge));
            }
            gt_off.push_back(gt_blob.size());
            ++cells_emitted;
          }
        }
      }
      while (cells_emitted < n_samples) {  // pad missing cells empty
        gt_off.push_back(gt_blob.size());
        ++cells_emitted;
      }
      tok_total.push_back(toks);
    }
    if (!nl) break;
    p = nl + 1;
  }

  *pos_out = CopyOut(pos);
  *chrom_off_out = CopyOut(chrom_off);
  *chrom_len_out = CopyOut(chrom_len);
  *ref_off_out = CopyOut(ref_off);
  *ref_len_out = CopyOut(ref_len);
  *vt_off_out = CopyOut(vt_off);
  *vt_len_out = CopyOut(vt_len);
  *an_out = CopyOut(an);
  *has_an_out = CopyOut(has_an);
  *has_ac_out = CopyOut(has_ac);
  *tok_total_out = CopyOut(tok_total);
  *alt_off_out = CopyOut(alt_off);
  *alt_len_out = CopyOut(alt_len);
  *alt_start_out = CopyOut(alt_start);
  *ac_gt_out = CopyOut(ac_gt);
  *ac_out = CopyOut(ac);
  *ac_start_out = CopyOut(ac_start);
  *gt_blob_out = CopyOut(gt_blob);
  *gt_off_out = CopyOut(gt_off);
  *n_rec_out = pos.size();
  *n_alt_out = alt_len.size();
  *n_ac_out = ac.size();
  *gt_blob_len_out = gt_blob.size();
  return 0;
}

}  // extern "C"
