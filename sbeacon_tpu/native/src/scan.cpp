// VCF slice scanning: the summariseSlice hot loop, natively.
//
// Native-component parity (SURVEY.md §2.1): re-implements the reference's
// per-record INFO scan (reference: lambda/summariseSlice/source/main.cpp
// addCounts :52-109 — numVariants = 1 + commas of the AC= value, numCalls
// += AN= value, fields walked until both found or the column ends) and the
// branchless ascii->int of shared/generalutils fast_atoi. Operates on
// already-inflated text (sbn_inflate_range's output), so the scan is pure
// byte work with no I/O stalls.

#include <cstdint>
#include <cstring>

namespace {

inline uint64_t FastAtoU64(const char* p, const char* end) {
  uint64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + uint64_t(*p - '0');
    ++p;
  }
  return v;
}

// INFO column begins after the 7th tab of a record line.
inline const char* SeekInfo(const char* p, const char* end) {
  int tabs = 0;
  while (p < end && tabs < 7) {
    if (*p == '\t') ++tabs;
    ++p;
  }
  return tabs == 7 ? p : nullptr;
}

}  // namespace

extern "C" {

// Scan VCF body text: counts via the reference addCounts semantics.
// Header lines ('#') are skipped. Returns 0 on success.
int sbn_count_slice(const uint8_t* text, uint64_t len,
                    int64_t* num_variants, int64_t* num_calls,
                    int64_t* num_records) {
  const char* p = reinterpret_cast<const char*>(text);
  const char* end = p + len;
  int64_t variants = 0, calls = 0, records = 0;
  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    const char* line_end = nl ? nl : end;
    if (p < line_end && *p != '#') {
      ++records;
      const char* q = SeekInfo(p, line_end);
      if (q) {
        bool found_ac = false, found_an = false;
        while (q < line_end && !(found_ac && found_an)) {
          const char* fe = q;
          while (fe < line_end && *fe != ';' && *fe != '\t') ++fe;
          if (fe - q >= 4) {
            if (std::memcmp(q, "AC=", 3) == 0) {
              found_ac = true;
              ++variants;
              for (const char* c = q + 3; c < fe; ++c) {
                if (*c == ',') ++variants;
              }
            } else if (std::memcmp(q, "AN=", 3) == 0) {
              found_an = true;
              calls += int64_t(FastAtoU64(q + 3, fe));
            }
          }
          if (fe >= line_end || *fe == '\t') break;
          q = fe + 1;
        }
      }
    }
    if (!nl) break;
    p = nl + 1;
  }
  *num_variants = variants;
  *num_calls = calls;
  *num_records = records;
  return 0;
}

// Newline offsets of non-header lines (record starts), for host-side
// record slicing without re-scanning in Python. out must hold up to
// max_out entries; returns the number written (negative on overflow).
int64_t sbn_line_offsets(const uint8_t* text, uint64_t len, uint64_t* out,
                         uint64_t max_out) {
  const char* base = reinterpret_cast<const char*>(text);
  const char* p = base;
  const char* end = p + len;
  uint64_t n = 0;
  while (p < end) {
    if (*p != '#' && *p != '\n') {
      if (n == max_out) return -1;
      out[n++] = uint64_t(p - base);
    }
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    if (!nl) break;
    p = nl + 1;
  }
  return int64_t(n);
}

}  // extern "C"
