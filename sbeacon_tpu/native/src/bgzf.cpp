// BGZF codec: parallel block inflate over a virtual-offset range, and
// whole-stream BGZF compression.
//
// Native-component parity (SURVEY.md §2.1): this is the coherent rebuild of
// the reference's VcfChunkReader (reference: lambda/summariseSlice/source/
// vcf_chunk_reader.h — getBlockDetails header parse :143-174, per-block
// zlib inflate :233-260, window rotation) and shared/gzip streaming
// (lambda/shared/gzip/gzip.cpp deflateFile/inflateFile). The reference
// overlaps 4 S3 download threads with decompression; local files make the
// read cheap, so parallelism moves to where the time actually goes —
// per-block inflate across a thread pool (blocks are independent deflate
// streams, so decode order is free and output offsets are prefix-summed
// from each block's ISIZE footer before any inflation starts).

#include <zlib.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "thread_pool.hpp"

namespace {

struct Block {
  uint64_t coffset;  // compressed offset of block start
  uint32_t bsize;    // total block size (BSIZE+1)
  uint32_t isize;    // uncompressed payload size
  uint64_t uoffset;  // prefix-summed uncompressed offset
};

// Parse the BGZF/gzip header at buf (len bytes available); returns the
// total block size via the BC extra subfield, or 0 on error/EOF-short.
uint32_t BlockSize(const uint8_t* buf, size_t len) {
  if (len < 18) return 0;
  if (buf[0] != 0x1f || buf[1] != 0x8b || buf[2] != 8) return 0;
  if (!(buf[3] & 4)) return 0;  // FEXTRA required for BGZF
  uint16_t xlen = uint16_t(buf[10]) | (uint16_t(buf[11]) << 8);
  size_t pos = 12, end = 12 + xlen;
  if (end > len) return 0;
  while (pos + 4 <= end) {
    uint8_t si1 = buf[pos], si2 = buf[pos + 1];
    uint16_t slen = uint16_t(buf[pos + 2]) | (uint16_t(buf[pos + 3]) << 8);
    if (si1 == 66 && si2 == 67 && slen == 2) {
      if (pos + 6 > end) return 0;
      uint16_t bsize =
          uint16_t(buf[pos + 4]) | (uint16_t(buf[pos + 5]) << 8);
      return uint32_t(bsize) + 1;
    }
    pos += 4 + slen;
  }
  return 0;
}

// Inflate one raw-deflate payload into out (exactly isize bytes).
bool InflateBlock(const uint8_t* comp, size_t comp_len, uint8_t* out,
                  uint32_t isize) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<uint8_t*>(comp);
  zs.avail_in = static_cast<uInt>(comp_len);
  zs.next_out = out;
  zs.avail_out = isize;
  int rc = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  return rc == Z_STREAM_END && zs.total_out == isize;
}

std::vector<uint8_t>* ReadFile(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  auto* data = new std::vector<uint8_t>(size_t(size));
  if (size && std::fread(data->data(), 1, size_t(size), f) != size_t(size)) {
    std::fclose(f);
    delete data;
    return nullptr;
  }
  std::fclose(f);
  return data;
}

// Shared core of sbn_inflate_range / sbn_inflate_buffer: decompress the
// virtual-offset range [vstart, vend) of a BGZF stream already resident
// in memory (compressed offsets are relative to `data`, which must begin
// at a block boundary). Same return codes as the extern entry points.
int InflateRangeCore(const uint8_t* data, size_t fsize, uint64_t vstart,
                     uint64_t vend, int n_threads, uint8_t** out,
                     uint64_t* out_len) {
  uint64_t cstart = vstart >> 16;
  uint32_t ustart = uint32_t(vstart & 0xffff);
  uint64_t cend = vend >> 16;
  uint32_t uend_within = uint32_t(vend & 0xffff);
  bool to_eof = vend == UINT64_MAX;

  // walk block headers from cstart, prefix-sum uncompressed offsets
  std::vector<Block> blocks;
  uint64_t coff = cstart, uoff = 0;
  while (coff < fsize) {
    if (!to_eof && coff > cend) break;
    uint32_t bsize = BlockSize(data + coff, fsize - coff);
    if (bsize == 0 || coff + bsize > fsize) {
      if (blocks.empty()) return 2;
      break;  // trailing garbage: stop at last good block
    }
    uint32_t isize;
    std::memcpy(&isize, data + coff + bsize - 4, 4);
    bool is_last_wanted = !to_eof && coff == cend;
    blocks.push_back({coff, bsize, isize, uoff});
    uoff += isize;
    coff += bsize;
    if (is_last_wanted) break;
    if (!to_eof && coff > cend && uend_within == 0) break;
  }
  if (blocks.empty()) {
    *out = nullptr;
    *out_len = 0;
    return 0;
  }

  uint64_t total = uoff;
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(total ? total : 1));
  if (!buf) return 3;

  std::atomic<int> failed{0};
  auto payload_of = [&](const Block& b, size_t* hdr_out) {
    // deflate payload sits between the header (12 + xlen bytes) and the
    // 8-byte CRC/ISIZE footer
    uint16_t xlen = uint16_t(data[b.coffset + 10]) |
                    (uint16_t(data[b.coffset + 11]) << 8);
    *hdr_out = 12 + size_t(xlen);
    return data + b.coffset + 12 + xlen;
  };
  if (n_threads <= 1) {
    // single-core path: one reusable z_stream, no pool overhead
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, -15) != Z_OK) failed.store(1);
    for (const Block& b : blocks) {
      if (failed.load() || b.isize == 0) continue;
      size_t hdr;
      const uint8_t* comp = payload_of(b, &hdr);
      zs.next_in = const_cast<uint8_t*>(comp);
      zs.avail_in = static_cast<uInt>(b.bsize - hdr - 8);
      zs.next_out = buf + b.uoffset;
      zs.avail_out = b.isize;
      int rc = inflate(&zs, Z_FINISH);
      if (rc != Z_STREAM_END || zs.total_out != b.isize) failed.store(1);
      inflateReset(&zs);
    }
    inflateEnd(&zs);
  } else {
    sbn::ThreadPool pool{size_t(n_threads)};
    std::mutex mu;
    std::condition_variable cv;
    size_t remaining = blocks.size();
    for (const Block& b : blocks) {
      pool.Submit([&, b] {
        size_t hdr;
        const uint8_t* comp = payload_of(b, &hdr);
        if (b.isize > 0 &&
            !InflateBlock(comp, b.bsize - hdr - 8, buf + b.uoffset,
                          b.isize)) {
          failed.store(1);
        }
        std::unique_lock<std::mutex> lk(mu);
        if (--remaining == 0) cv.notify_one();
      });
    }
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return remaining == 0; });
  }
  if (failed.load()) {
    std::free(buf);
    return 4;
  }

  // trim to the within-block offsets of the virtual range; a start
  // offset past the first block's payload contributes nothing from
  // THAT block (the reference reader slices payload[uoff:] per block
  // — it never bleeds into the next block's bytes)
  uint64_t begin = ustart;
  if (begin > blocks.front().isize) begin = blocks.front().isize;
  uint64_t end = total;
  if (!to_eof) {
    // find the block at cend; its uoffset + uend_within bounds the range
    for (const Block& b : blocks) {
      if (b.coffset == cend) {
        end = b.uoffset + uend_within;
        break;
      }
    }
    if (end > total) end = total;
  }
  if (begin > end) begin = end;
  uint64_t n = end - begin;
  if (begin > 0) std::memmove(buf, buf + begin, n);
  *out = buf;
  *out_len = n;
  return 0;
}

}  // namespace

extern "C" {

// Decompress the virtual-offset range [vstart, vend) of a BGZF file.
// vend == UINT64_MAX means "to EOF". The caller owns *out (sbn_free).
// Returns 0 on success.
int sbn_inflate_range(const char* path, uint64_t vstart, uint64_t vend,
                      int n_threads, uint8_t** out, uint64_t* out_len) {
  std::vector<uint8_t>* file = ReadFile(path);
  if (!file) return 1;
  int rc = InflateRangeCore(file->data(), file->size(), vstart, vend,
                            n_threads, out, out_len);
  delete file;
  return rc;
}

// Decompress the virtual-offset range [vstart, vend) of a BGZF blob
// already in memory — the remote scan-blob leg, where the compressed
// span arrives by ranged GET. Offsets are relative to the blob (its
// first byte must be a block boundary); vend == UINT64_MAX means "to
// the end of the blob". The caller owns *out (sbn_free).
int sbn_inflate_buffer(const uint8_t* data, uint64_t len, uint64_t vstart,
                       uint64_t vend, int n_threads, uint8_t** out,
                       uint64_t* out_len) {
  return InflateRangeCore(data, size_t(len), vstart, vend, n_threads, out,
                          out_len);
}

// Compress data into a full BGZF stream (64KB blocks + EOF marker).
// Returns 0 on success; caller owns *out.
int sbn_compress_bgzf(const uint8_t* data, uint64_t len, int level,
                      uint8_t** out, uint64_t* out_len) {
  static const uint8_t kEof[28] = {
      0x1f, 0x8b, 0x08, 0x04, 0,    0,    0,    0,    0,    0xff,
      0x06, 0x00, 0x42, 0x43, 0x02, 0x00, 0x1b, 0x00, 0x03, 0x00,
      0,    0,    0,    0,    0,    0,    0,    0};
  const size_t kChunk = 0xff00;  // uncompressed bytes per block
  std::vector<uint8_t> result;
  result.reserve(len / 2 + 64);
  std::vector<uint8_t> comp(kChunk + 1024);
  for (uint64_t off = 0; off < len || (len == 0 && off == 0);
       off += kChunk) {
    size_t n = size_t(len - off < kChunk ? len - off : kChunk);
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8,
                     Z_DEFAULT_STRATEGY) != Z_OK)
      return 1;
    zs.next_in = const_cast<uint8_t*>(data + off);
    zs.avail_in = static_cast<uInt>(n);
    zs.next_out = comp.data();
    zs.avail_out = static_cast<uInt>(comp.size());
    if (deflate(&zs, Z_FINISH) != Z_STREAM_END) {
      deflateEnd(&zs);
      return 1;
    }
    uint32_t csize = uint32_t(zs.total_out);
    deflateEnd(&zs);
    uint32_t crc = crc32(0, data + off, uInt(n));
    uint32_t bsize = csize + 25 + 1;  // header(18) + payload + footer(8)
    uint8_t hdr[18] = {0x1f, 0x8b, 0x08, 0x04, 0, 0,    0,    0,   0,
                       0xff, 0x06, 0x00, 0x42, 0x43, 0x02, 0x00, 0,   0};
    hdr[16] = uint8_t((bsize - 1) & 0xff);
    hdr[17] = uint8_t(((bsize - 1) >> 8) & 0xff);
    result.insert(result.end(), hdr, hdr + 18);
    result.insert(result.end(), comp.data(), comp.data() + csize);
    uint8_t footer[8];
    std::memcpy(footer, &crc, 4);
    uint32_t isize = uint32_t(n);
    std::memcpy(footer + 4, &isize, 4);
    result.insert(result.end(), footer, footer + 8);
    if (len == 0) break;
  }
  result.insert(result.end(), kEof, kEof + 28);
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(result.size()));
  if (!buf) return 3;
  std::memcpy(buf, result.data(), result.size());
  *out = buf;
  *out_len = result.size();
  return 0;
}

void sbn_free(uint8_t* p) { std::free(p); }

}  // extern "C"
