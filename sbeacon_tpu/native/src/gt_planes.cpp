// Genotype bit-plane builder: the per-(row, sample) hot loop of index
// construction (the summariseSlice-scan-loop role, reference:
// lambda/summariseSlice/source/main.cpp:230-237 — there the native loop
// counts AC/AN per slice; here it builds the per-row sample-genotype
// planes the selected-samples query path consumes).
//
// Inputs: every used record's GT strings concatenated ('\0'-free runs
// addressed by offsets, record-major then sample), plus per-output-row
// (record index, allele number). Token semantics match the reference's
// get_all_calls regex `[0-9]+` findall (performQuery/search_variants.py:
// 28-29): every digit run in a GT contributes one call.
//
// Outputs (caller-allocated): four uint32 planes [n_rows, words] — bit s
// of word w set when sample s*... has >=1 / >=2 copies of the row's
// allele, >=1 / >=2 GT tokens — plus malloc'd (row, sample, value)
// overflow triples where copies or tokens exceed 2 (ploidy > 2).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

int64_t sbn_gt_planes(
    const uint8_t* gt_blob, const uint64_t* gt_off,  // [n_rec*n_samples+1]
    uint64_t n_rec, uint64_t n_samples,
    const int32_t* row_rec,     // [n_rows] record index per row
    const int32_t* row_allele,  // [n_rows] allele number (alt_ord + 1)
    uint64_t n_rows, uint64_t words,
    uint32_t* gt1, uint32_t* gt2, uint32_t* tok1, uint32_t* tok2,
    int64_t** gt_over_out, uint64_t* n_gt_over,
    int64_t** tok_over_out, uint64_t* n_tok_over) {
  // 1. parse every (record, sample) GT once: digit runs -> tokens, in a
  // flat token array + offsets (two allocations total — a vector per
  // (record, sample) would cost a heap block each at cohort scale)
  const uint64_t n_cells = n_rec * n_samples;
  std::vector<int32_t> tokens;
  tokens.reserve(n_cells * 2);  // diploid common case
  std::vector<uint64_t> tok_off(n_cells + 1, 0);
  for (uint64_t k = 0; k < n_cells; ++k) {
    const uint8_t* s = gt_blob + gt_off[k];
    const uint8_t* e = gt_blob + gt_off[k + 1];
    while (s < e) {
      if (*s >= '0' && *s <= '9') {
        int64_t v = 0;
        while (s < e && *s >= '0' && *s <= '9') {
          v = v * 10 + (*s - '0');
          if (v > INT32_MAX) v = INT32_MAX;  // clamp absurd allele ids
          ++s;
        }
        tokens.push_back(static_cast<int32_t>(v));
      } else {
        ++s;
      }
    }
    tok_off[k + 1] = tokens.size();
  }

  // per-record token-count planes are identical across that record's
  // rows; precompute them (and the token overflow list) once
  std::vector<uint32_t> rec_tok1(n_rec * words, 0);
  std::vector<uint32_t> rec_tok2(n_rec * words, 0);
  std::vector<std::vector<std::pair<int32_t, int32_t>>> rec_tok_over(n_rec);
  for (uint64_t r = 0; r < n_rec; ++r) {
    for (uint64_t s = 0; s < n_samples; ++s) {
      uint64_t k = r * n_samples + s;
      uint64_t nt = tok_off[k + 1] - tok_off[k];
      uint32_t bit = 1u << (s % 32);
      if (nt >= 1) rec_tok1[r * words + s / 32] |= bit;
      if (nt >= 2) rec_tok2[r * words + s / 32] |= bit;
      if (nt > 2) {
        rec_tok_over[r].emplace_back(static_cast<int32_t>(s),
                                     static_cast<int32_t>(nt));
      }
    }
  }

  // 2. fill rows
  std::vector<int64_t> gt_over;
  std::vector<int64_t> tok_over;
  for (uint64_t i = 0; i < n_rows; ++i) {
    int32_t r = row_rec[i];
    int32_t allele = row_allele[i];
    if (r < 0 || static_cast<uint64_t>(r) >= n_rec) return -1;
    std::memcpy(tok1 + i * words, rec_tok1.data() + r * words,
                words * sizeof(uint32_t));
    std::memcpy(tok2 + i * words, rec_tok2.data() + r * words,
                words * sizeof(uint32_t));
    for (const auto& so : rec_tok_over[r]) {
      tok_over.push_back(static_cast<int64_t>(i));
      tok_over.push_back(so.first);
      tok_over.push_back(so.second);
    }
    for (uint64_t s = 0; s < n_samples; ++s) {
      uint64_t k = static_cast<uint64_t>(r) * n_samples + s;
      int32_t copies = 0;
      for (uint64_t t = tok_off[k]; t < tok_off[k + 1]; ++t)
        copies += (tokens[t] == allele);
      if (copies >= 1) {
        uint32_t bit = 1u << (s % 32);
        gt1[i * words + s / 32] |= bit;
        if (copies >= 2) gt2[i * words + s / 32] |= bit;
        if (copies > 2) {
          gt_over.push_back(static_cast<int64_t>(i));
          gt_over.push_back(static_cast<int64_t>(s));
          gt_over.push_back(copies);
        }
      }
    }
  }

  auto take = [](const std::vector<int64_t>& v) -> int64_t* {
    auto* p = static_cast<int64_t*>(
        std::malloc(v.empty() ? 8 : v.size() * sizeof(int64_t)));
    if (p && !v.empty()) {
      std::memcpy(p, v.data(), v.size() * sizeof(int64_t));
    }
    return p;
  };
  *gt_over_out = take(gt_over);
  *tok_over_out = take(tok_over);
  if (!*gt_over_out || !*tok_over_out) return -2;
  *n_gt_over = gt_over.size() / 3;
  *n_tok_over = tok_over.size() / 3;
  return static_cast<int64_t>(n_rows);
}

}  // extern "C"
