// Minimal fixed-size thread pool.
//
// Native-component parity: the reference vendors a generic pool for its
// parallel S3 downloads (reference: lambda/duplicateVariantSearch/source/
// thread.hpp, 226 LoC of work-stealing queue) and hand-rolls 4 download
// threads in the BGZF reader (summariseSlice/source/vcf_chunk_reader.h:
// 69-105). Here one pool serves both roles: parallel block inflation and
// any future ranged-read prefetch.

#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace sbn {

class ThreadPool {
 public:
  explicit ThreadPool(std::size_t n) {
    if (n == 0) n = 1;
    for (std::size_t i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Run(); });
    }
  }

  ~ThreadPool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      done_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  void Submit(std::function<void()> fn) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  void Run() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return done_ || !q_.empty(); });
        if (q_.empty()) {
          if (done_) return;
          continue;
        }
        fn = std::move(q_.front());
        q_.pop();
      }
      fn();
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool done_ = false;
};

}  // namespace sbn
