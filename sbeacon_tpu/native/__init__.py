"""Native (C++) hot-path library: BGZF codec, VCF slice scanner,
record tokenizer, index record codec, genotype-plane builder.

One coherent C++17 library replacing the reference's scattered native
components (SURVEY.md §2.1 ledger: VcfChunkReader, Downloader, shared/gzip,
thread_pool, fast_atoi, the summariseSlice scan loop). Built on demand with
g++ (no external build system), loaded via ctypes — per the environment
contract there is no pybind11; the ABI is a flat C surface over malloc'd
buffers.

Every entry point has a pure-Python fallback in ``genomics/``; callers use
``available()`` or just call the wrappers, which raise ``NativeUnavailable``
when the toolchain/library is missing so the Python path can take over.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

log = logging.getLogger(__name__)

_SRC = Path(__file__).parent / "src"
_LIB_PATH = Path(__file__).parent / "_sbnative.so"
_SOURCES = [
    "bgzf.cpp",
    "scan.cpp",
    "index_codec.cpp",
    "gt_planes.cpp",
    "tokenize.cpp",
]

_lock = threading.Lock()
_lib = None
_build_failed = False


class NativeUnavailable(RuntimeError):
    pass


def _newest_source_mtime() -> float:
    return max((_SRC / s).stat().st_mtime for s in _SOURCES)


def build(force: bool = False) -> Path:
    """Compile the shared library (cached by mtime)."""
    if (
        not force
        and _LIB_PATH.exists()
        and _LIB_PATH.stat().st_mtime >= _newest_source_mtime()
    ):
        return _LIB_PATH
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        *[str(_SRC / s) for s in _SOURCES],
        "-lz",
        "-o",
        str(_LIB_PATH),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return _LIB_PATH


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        try:
            path = build()
            lib = ctypes.CDLL(str(path))
        except Exception as e:
            _build_failed = True
            log.warning("native library unavailable: %s", e)
            return None
        lib.sbn_inflate_range.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sbn_inflate_range.restype = ctypes.c_int
        if hasattr(lib, "sbn_inflate_buffer"):
            lib.sbn_inflate_buffer.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_uint64,
                ctypes.c_uint64,
                ctypes.c_uint64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.sbn_inflate_buffer.restype = ctypes.c_int
        lib.sbn_compress_bgzf.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sbn_compress_bgzf.restype = ctypes.c_int
        lib.sbn_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.sbn_count_slice.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.sbn_count_slice.restype = ctypes.c_int
        u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
        u32pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32))
        u64pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64))
        i64pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))
        lib.sbn_tokenize.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.c_uint64,
            i64pp,              # pos
            u32pp, u32pp,       # chrom off/len
            u32pp, u32pp,       # ref off/len
            u32pp, u32pp,       # vt off/len
            i64pp, u8pp, u8pp,  # an, has_an, has_ac
            i64pp,              # tok_total
            u32pp, u32pp, u64pp,  # alt off/len/start
            i64pp,              # ac_gt
            i64pp, u64pp,       # ac, ac_start
            u8pp, u64pp,        # gt_blob, gt_off
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sbn_tokenize.restype = ctypes.c_int
        lib.sbn_line_offsets.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
        ]
        lib.sbn_line_offsets.restype = ctypes.c_int64
        lib.sbn_pack_records.argtypes = [
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sbn_pack_records.restype = ctypes.c_int
        lib.sbn_unpack_records.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32)),
        ]
        lib.sbn_unpack_records.restype = ctypes.c_int64
        lib.sbn_unpack_seq.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
        ]
        lib.sbn_unpack_seq.restype = ctypes.c_int64
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.sbn_gt_planes.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.c_uint64,
            i32p,
            i32p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            u32p,
            u32p,
            u32p,
            u32p,
            ctypes.POINTER(i64p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(i64p),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sbn_gt_planes.restype = ctypes.c_int64
        if hasattr(lib, "sbn_tokenize_planes"):
            # uint64 params MUST be declared: the ctypes default of
            # c_int silently truncates len/n_samples/words >= 2^32
            # (a >=2 GiB decompressed slice would mis-parse with no
            # error on the fused hot path)
            u8pp_ = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
            u32pp_ = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32))
            u64pp_ = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64))
            i64pp_ = ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))
            u64p_ = ctypes.POINTER(ctypes.c_uint64)
            lib.sbn_tokenize_planes.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_uint64,      # len
                ctypes.c_uint64,      # n_samples
                ctypes.c_uint64,      # words
                i64pp_,               # pos
                u32pp_, u32pp_,       # chrom off/len
                u32pp_, u32pp_,       # ref off/len
                u32pp_, u32pp_,       # vt off/len
                i64pp_, u8pp_, u8pp_,  # an, has_an, has_ac
                i64pp_,               # tok_total
                u32pp_, u32pp_, u64pp_,  # alt off/len/start
                i64pp_,               # ac_gt
                i64pp_, u64pp_,       # ac, ac_start
                u32pp_, u32pp_,       # g1, g2
                u32pp_, u32pp_,       # t1, t2
                i64pp_, u64p_,        # gt_over, n_gt_over
                i64pp_, u64p_,        # tok_over, n_tok_over
                u64p_, u64p_, u64p_,  # n_rec, n_alt, n_ac
            ]
            lib.sbn_tokenize_planes.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def prefer_native_io() -> bool:
    """Whether the native BGZF codec should take over I/O paths: it wins
    via block-parallel inflate, so a single-core host keeps python's
    one-shot zlib (both are C underneath; the pool only adds overhead).
    ``BEACON_NATIVE_IO=0`` is the operator kill switch — every call site
    behind this gate has a pure-Python fallback, so flipping it degrades
    throughput, never correctness."""
    import os

    if os.environ.get("BEACON_NATIVE_IO", "").strip().lower() in (
        "0",
        "off",
        "false",
        "no",
    ):
        return False
    return (os.cpu_count() or 1) >= 2 and available()


def _take_buffer(lib, out_p, out_len) -> bytes:
    try:
        if not out_p or out_len.value == 0:
            return b""
        return ctypes.string_at(out_p, out_len.value)
    finally:
        if out_p:
            lib.sbn_free(out_p)


def inflate_range(
    path: str | Path,
    vstart: int = 0,
    vend: int | None = None,
    *,
    n_threads: int | None = None,
) -> bytes:
    """Decompress the BGZF virtual-offset range [vstart, vend) — the
    native VcfChunkReader role, blocks inflated in parallel (adaptive:
    single-core machines take a pool-free reused-z_stream path)."""
    if n_threads is None:
        import os

        n_threads = min(8, os.cpu_count() or 1)
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    out_p = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_uint64()
    rc = lib.sbn_inflate_range(
        str(path).encode(),
        vstart,
        2**64 - 1 if vend is None else vend,
        n_threads,
        ctypes.byref(out_p),
        ctypes.byref(out_len),
    )
    if rc != 0:
        raise NativeUnavailable(f"sbn_inflate_range failed rc={rc}")
    return _take_buffer(lib, out_p, out_len)


def inflate_buffer(
    data: bytes,
    vstart: int = 0,
    vend: int | None = None,
    *,
    n_threads: int | None = None,
) -> bytes:
    """Decompress the BGZF virtual-offset range [vstart, vend) of a
    compressed blob already in memory — the remote scan-blob leg, where
    the span arrives by ranged GET and never touches local disk. Offsets
    are relative to the blob, whose first byte must be a block boundary
    (fetch from the compressed half of the slice's start voffset). The
    ctypes call releases the GIL, so scan workers inflate in parallel."""
    if n_threads is None:
        import os

        n_threads = min(8, os.cpu_count() or 1)
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    if not hasattr(lib, "sbn_inflate_buffer"):
        raise NativeUnavailable("sbn_inflate_buffer missing (stale library)")
    import numpy as np

    # zero-copy in: the C side only reads the blob
    view = np.frombuffer(data or b"\0", dtype=np.uint8)
    out_p = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_uint64()
    rc = lib.sbn_inflate_buffer(
        view.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(data),
        vstart,
        2**64 - 1 if vend is None else vend,
        n_threads,
        ctypes.byref(out_p),
        ctypes.byref(out_len),
    )
    if rc != 0:
        raise NativeUnavailable(f"sbn_inflate_buffer failed rc={rc}")
    return _take_buffer(lib, out_p, out_len)


def compress_bgzf(data: bytes, level: int = 6) -> bytes:
    """Full BGZF stream (blocks + EOF marker) for the given payload."""
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    out_p = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_uint64()
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else None
    rc = lib.sbn_compress_bgzf(
        buf, len(data), level, ctypes.byref(out_p), ctypes.byref(out_len)
    )
    if rc != 0:
        raise NativeUnavailable(f"sbn_compress_bgzf failed rc={rc}")
    return _take_buffer(lib, out_p, out_len)


def pack_records(
    pos, refs: list[bytes], alts: list[bytes], *, level: int = 9
) -> bytes:
    """Gzip blob of (pos, packed ref'_'alt) records — the reference
    writeDataToS3 on-S3 index format (write_data_to_s3.h:30-228).

    List form: joins the per-row bytes and delegates to the columnar
    ``pack_records_arrays`` (one FFI call site)."""
    import numpy as np

    n = len(refs)
    pos_a = np.ascontiguousarray(pos, dtype=np.uint64)
    if pos_a.shape != (n,) or len(alts) != n:
        raise ValueError("pos/refs/alts length mismatch")

    def runs(items):
        cum = np.cumsum([len(b) for b in items], dtype=np.uint64)
        offs = np.zeros(n + 1, dtype=np.uint64)
        offs[1:] = cum
        return np.frombuffer(b"".join(items), dtype=np.uint8), offs

    ref_blob, ref_offs = runs(refs)
    alt_blob, alt_offs = runs(alts)
    return pack_records_arrays(
        pos_a, ref_blob, ref_offs, alt_blob, alt_offs, level=level
    )


def unpack_records(
    blob: bytes,
    range_start: int = 0,
    range_end: int = 2**63 - 1,
):
    """(pos: uint64 ndarray, payloads: list[bytes]) for records in
    [range_start, range_end] — the ReadVcfData range-filtered read
    (readVcfData.cpp:3-38). Payloads are the packed ref'_'alt keys the
    reference dedupes on."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    out_pos = ctypes.POINTER(ctypes.c_uint64)()
    out_payload = ctypes.POINTER(ctypes.c_uint8)()
    out_offs = ctypes.POINTER(ctypes.c_uint32)()
    buf = (
        (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
        if blob
        else (ctypes.c_uint8 * 1)()
    )
    n = lib.sbn_unpack_records(
        buf,
        len(blob),
        range_start,
        range_end,
        ctypes.byref(out_pos),
        ctypes.byref(out_payload),
        ctypes.byref(out_offs),
    )
    if n < 0:
        raise NativeUnavailable(f"sbn_unpack_records failed rc={n}")
    try:
        pos = np.ctypeslib.as_array(out_pos, shape=(n,)).copy()
        offs = np.ctypeslib.as_array(out_offs, shape=(n + 1,)).copy()
        payload = (
            ctypes.string_at(out_payload, int(offs[-1])) if n else b""
        )
    finally:
        lib.sbn_free(ctypes.cast(out_pos, ctypes.POINTER(ctypes.c_uint8)))
        lib.sbn_free(out_payload)
        lib.sbn_free(ctypes.cast(out_offs, ctypes.POINTER(ctypes.c_uint8)))
    return pos, [
        payload[offs[i] : offs[i + 1]] for i in range(n)
    ]


def unpack_seq(packed: bytes) -> bytes | None:
    """Sequence text for a packed payload half; None when it was stored
    raw (symbolic allele passthrough)."""
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    cap = max(2 * len(packed), 1)
    out = (ctypes.c_uint8 * cap)()
    buf = (
        (ctypes.c_uint8 * len(packed)).from_buffer_copy(packed)
        if packed
        else (ctypes.c_uint8 * 1)()
    )
    n = lib.sbn_unpack_seq(buf, len(packed), out, cap)
    if n == -1:
        return None
    if n < 0:
        raise NativeUnavailable(f"sbn_unpack_seq failed rc={n}")
    return bytes(out[:n])


def gt_planes(
    gt_blob: bytes,
    gt_off,
    n_rec: int,
    n_samples: int,
    row_rec,
    row_allele,
    words: int,
):
    """(gt1, gt2, tok1, tok2, gt_overflow, tok_overflow) — the genotype
    bit planes for all index rows in one native pass (the per-(row,
    sample) hot loop of build_index). Arrays are uint32[n_rows, words];
    overflows are int64[k, 3] (row, sample, exact value)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    gt_off = np.ascontiguousarray(gt_off, dtype=np.uint64)
    row_rec = np.ascontiguousarray(row_rec, dtype=np.int32)
    row_allele = np.ascontiguousarray(row_allele, dtype=np.int32)
    n_rows = len(row_rec)
    planes = [
        np.zeros((n_rows, words), dtype=np.uint32) for _ in range(4)
    ]
    # zero-copy: the C side only reads the blob; keep the buffer object
    # referenced (blob_view) for the duration of the call. Accepts bytes
    # or a uint8 ndarray (the tokenizer's gt_blob output) without copying.
    if isinstance(gt_blob, np.ndarray):
        blob_view = (
            np.ascontiguousarray(gt_blob, dtype=np.uint8)
            if len(gt_blob)
            else np.zeros(1, np.uint8)
        )
    else:
        blob_view = np.frombuffer(gt_blob or b"\0", dtype=np.uint8)
    u32 = ctypes.POINTER(ctypes.c_uint32)
    u64 = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    gt_over_p = i64p()
    tok_over_p = i64p()
    n_gt = ctypes.c_uint64()
    n_tok = ctypes.c_uint64()
    rc = lib.sbn_gt_planes(
        blob_view.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        gt_off.ctypes.data_as(u64),
        n_rec,
        n_samples,
        row_rec.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        row_allele.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_rows,
        words,
        *[p.ctypes.data_as(u32) for p in planes],
        ctypes.byref(gt_over_p),
        ctypes.byref(n_gt),
        ctypes.byref(tok_over_p),
        ctypes.byref(n_tok),
    )
    if rc < 0:
        raise NativeUnavailable(f"sbn_gt_planes failed rc={rc}")
    try:
        gt_over = (
            np.ctypeslib.as_array(gt_over_p, shape=(int(n_gt.value), 3))
            .copy()
            .astype(np.int64)
            if n_gt.value
            else np.zeros((0, 3), np.int64)
        )
        tok_over = (
            np.ctypeslib.as_array(tok_over_p, shape=(int(n_tok.value), 3))
            .copy()
            .astype(np.int64)
            if n_tok.value
            else np.zeros((0, 3), np.int64)
        )
    finally:
        lib.sbn_free(ctypes.cast(gt_over_p, ctypes.POINTER(ctypes.c_uint8)))
        lib.sbn_free(ctypes.cast(tok_over_p, ctypes.POINTER(ctypes.c_uint8)))
    return planes[0], planes[1], planes[2], planes[3], gt_over, tok_over


def count_slice(text: bytes) -> tuple[int, int, int]:
    """(num_variants, num_calls, num_records) over VCF body text — the
    reference addCounts semantics (AC= commas / AN= value)."""
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    buf = (ctypes.c_uint8 * len(text)).from_buffer_copy(text) if text else None
    nv = ctypes.c_int64()
    nc = ctypes.c_int64()
    nr = ctypes.c_int64()
    rc = lib.sbn_count_slice(
        buf, len(text), ctypes.byref(nv), ctypes.byref(nc), ctypes.byref(nr)
    )
    if rc != 0:
        raise NativeUnavailable(f"sbn_count_slice failed rc={rc}")
    return nv.value, nc.value, nr.value


def tokenize(text: bytes, n_samples: int) -> dict:
    """One native pass over VCF body text -> flat record/field arrays.

    The columnar fast path's front end (tokenize.cpp): per-record
    positions and field spans (byte offsets into ``text``), per-alt
    spans, INFO AC/AN/VT, genotype-derived allele/token tallies, and
    normalised per-sample GT cells ready for ``gt_planes``. Dict keys
    mirror the C out-params; span arrays index into the ``text`` the
    caller passed (keep it alive)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    if not hasattr(lib, "sbn_tokenize"):
        raise NativeUnavailable("sbn_tokenize missing (stale library)")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    outs = {
        "pos": i64p(),
        "chrom_off": u32p(), "chrom_len": u32p(),
        "ref_off": u32p(), "ref_len": u32p(),
        "vt_off": u32p(), "vt_len": u32p(),
        "an": i64p(), "has_an": u8p(), "has_ac": u8p(),
        "tok_total": i64p(),
        "alt_off": u32p(), "alt_len": u32p(), "alt_start": u64p(),
        "ac_gt": i64p(),
        "ac": i64p(), "ac_start": u64p(),
        "gt_blob": u8p(), "gt_off": u64p(),
    }
    n_rec = ctypes.c_uint64()
    n_alt = ctypes.c_uint64()
    n_ac = ctypes.c_uint64()
    gt_blob_len = ctypes.c_uint64()
    text_view = np.frombuffer(text or b"\0", dtype=np.uint8)
    rc = lib.sbn_tokenize(
        text_view.ctypes.data_as(u8p),
        len(text),
        n_samples,
        *[ctypes.byref(v) for v in outs.values()],
        ctypes.byref(n_rec),
        ctypes.byref(n_alt),
        ctypes.byref(n_ac),
        ctypes.byref(gt_blob_len),
    )
    if rc != 0:
        raise NativeUnavailable(f"sbn_tokenize failed rc={rc}")
    nr, na, nac = n_rec.value, n_alt.value, n_ac.value
    shapes = {
        "pos": nr, "chrom_off": nr, "chrom_len": nr,
        "ref_off": nr, "ref_len": nr, "vt_off": nr, "vt_len": nr,
        "an": nr, "has_an": nr, "has_ac": nr, "tok_total": nr,
        "alt_off": na, "alt_len": na, "alt_start": nr + 1,
        "ac_gt": na, "ac": nac, "ac_start": nr + 1,
        "gt_blob": gt_blob_len.value,
        "gt_off": nr * n_samples + 1,
    }
    try:
        result = {
            k: (
                np.ctypeslib.as_array(v, shape=(shapes[k],)).copy()
                if shapes[k]
                else np.zeros(0, dtype=np.ctypeslib.as_array(v, shape=(1,)).dtype)
            )
            for k, v in outs.items()
        }
    finally:
        for v in outs.values():
            lib.sbn_free(ctypes.cast(v, u8p))
    result["n_rec"] = nr
    result["n_alt"] = na
    return result


def tokenize_planes(text: bytes, n_samples: int, words: int) -> dict:
    """Fused single native pass: tokenizer arrays + genotype bit planes.

    Same record/field outputs as :func:`tokenize` (minus the normalised
    GT blob, which no longer exists) plus ``g1``/``g2`` uint32
    [n_alt, words] planes in TEXT alt order, ``t1``/``t2`` uint32
    [n_rec, words] per-record token planes, and overflow triples
    ``gt_over`` (flat_alt, sample, copies) / ``tok_over`` (rec, sample,
    ntok). One scan of the input instead of tokenize + gt_planes' two —
    the per-core ingest hot path (VERDICT r3 #5)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    if not hasattr(lib, "sbn_tokenize_planes"):
        raise NativeUnavailable("sbn_tokenize_planes missing (stale library)")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    outs = {
        "pos": i64p(),
        "chrom_off": u32p(), "chrom_len": u32p(),
        "ref_off": u32p(), "ref_len": u32p(),
        "vt_off": u32p(), "vt_len": u32p(),
        "an": i64p(), "has_an": u8p(), "has_ac": u8p(),
        "tok_total": i64p(),
        "alt_off": u32p(), "alt_len": u32p(), "alt_start": u64p(),
        "ac_gt": i64p(),
        "ac": i64p(), "ac_start": u64p(),
        "g1": u32p(), "g2": u32p(), "t1": u32p(), "t2": u32p(),
        "gt_over": i64p(),
    }
    n_gt_over = ctypes.c_uint64()
    tok_over_p = i64p()
    n_tok_over = ctypes.c_uint64()
    n_rec = ctypes.c_uint64()
    n_alt = ctypes.c_uint64()
    n_ac = ctypes.c_uint64()
    text_view = np.frombuffer(text or b"\0", dtype=np.uint8)
    vals = list(outs.values())
    rc = lib.sbn_tokenize_planes(
        text_view.ctypes.data_as(u8p),
        len(text),
        n_samples,
        words,
        *[ctypes.byref(v) for v in vals[:-1]],
        ctypes.byref(vals[-1]),
        ctypes.byref(n_gt_over),
        ctypes.byref(tok_over_p),
        ctypes.byref(n_tok_over),
        ctypes.byref(n_rec),
        ctypes.byref(n_alt),
        ctypes.byref(n_ac),
    )
    if rc != 0:
        raise NativeUnavailable(f"sbn_tokenize_planes failed rc={rc}")
    nr, na, nac = n_rec.value, n_alt.value, n_ac.value
    shapes = {
        "pos": nr, "chrom_off": nr, "chrom_len": nr,
        "ref_off": nr, "ref_len": nr, "vt_off": nr, "vt_len": nr,
        "an": nr, "has_an": nr, "has_ac": nr, "tok_total": nr,
        "alt_off": na, "alt_len": na, "alt_start": nr + 1,
        "ac_gt": na, "ac": nac, "ac_start": nr + 1,
        "g1": na * words, "g2": na * words,
        "t1": nr * words, "t2": nr * words,
        "gt_over": n_gt_over.value * 3,
    }
    import weakref

    planes = {"g1", "g2", "t1", "t2"}
    result = {}
    finalized = set()  # plane keys whose buffer a finalizer now owns
    try:
        for k, v in outs.items():
            if not shapes[k]:
                result[k] = np.zeros(
                    0, dtype=np.ctypeslib.as_array(v, shape=(1,)).dtype
                )
                continue
            arr = np.ctypeslib.as_array(v, shape=(shapes[k],))
            if k in planes:
                # the planes are the bulk of the output: wrap the C
                # buffer zero-copy and free it when the LAST view dies
                # (views keep the base array — and thus the finalizer —
                # alive); everything else is small enough to copy out
                weakref.finalize(
                    arr, lib.sbn_free, ctypes.cast(v, u8p)
                )
                finalized.add(k)
                result[k] = arr
            else:
                result[k] = arr.copy()
        nt = n_tok_over.value * 3
        result["tok_over"] = (
            np.ctypeslib.as_array(tok_over_p, shape=(nt,)).copy()
            if nt
            else np.zeros(0, np.int64)
        )
    finally:
        for k, v in outs.items():
            if k in finalized:
                continue  # freed by the finalizer above
            lib.sbn_free(ctypes.cast(v, u8p))
        lib.sbn_free(ctypes.cast(tok_over_p, u8p))
    for k in ("g1", "g2"):
        result[k] = result[k].view(np.uint32).reshape(na, words)
    for k in ("t1", "t2"):
        result[k] = result[k].view(np.uint32).reshape(nr, words)
    result["gt_over"] = result["gt_over"].reshape(-1, 3)
    result["tok_over"] = result["tok_over"].reshape(-1, 3)
    result["n_rec"] = nr
    result["n_alt"] = na
    return result


def pack_records_arrays(
    pos, ref_blob, ref_offs, alt_blob, alt_offs, *, level: int = 6
) -> bytes:
    """pack_records over columnar inputs (uint8 blobs + uint32 offsets) —
    the export path's zero-copy form: shard blobs slice straight in, no
    per-row python bytes objects."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        raise NativeUnavailable("native library not built")
    pos_a = np.ascontiguousarray(pos, dtype=np.uint64)
    ref_b = np.ascontiguousarray(ref_blob, dtype=np.uint8)
    alt_b = np.ascontiguousarray(alt_blob, dtype=np.uint8)
    n = len(pos_a)
    # validate BEFORE the uint32 cast: silent modular wrap of >=2^32
    # offsets (or offsets outside the blob) would hand the C side an
    # out-of-bounds read and a silently corrupt blob
    for name, offs, blob in (
        ("ref", ref_offs, ref_b),
        ("alt", alt_offs, alt_b),
    ):
        offs = np.asarray(offs)
        if len(offs) != n + 1:
            raise ValueError(f"{name} offsets must have n+1 entries")
        if len(offs) and int(offs[-1]) >= 2**32:
            raise ValueError("total allele bytes exceed u32 offset space")
        if len(offs) and (
            int(offs[0]) != 0
            or int(offs[-1]) != len(blob)
            or (np.diff(offs) < 0).any()
        ):
            raise ValueError(f"{name} offsets malformed for blob")
    ref_o = np.ascontiguousarray(ref_offs, dtype=np.uint32)
    alt_o = np.ascontiguousarray(alt_offs, dtype=np.uint32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    out_p = u8p()
    out_len = ctypes.c_uint64()
    # keep 1-byte dummies for empty blobs (NULL data pointers otherwise)
    ref_mem = ref_b if len(ref_b) else np.zeros(1, np.uint8)
    alt_mem = alt_b if len(alt_b) else np.zeros(1, np.uint8)
    rc = lib.sbn_pack_records(
        n,
        pos_a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ref_mem.ctypes.data_as(u8p),
        ref_o.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        alt_mem.ctypes.data_as(u8p),
        alt_o.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        level,
        ctypes.byref(out_p),
        ctypes.byref(out_len),
    )
    if rc == 3:
        raise ValueError("allele too long for u16 record length")
    if rc != 0:
        raise NativeUnavailable(f"sbn_pack_records failed rc={rc}")
    return _take_buffer(lib, out_p, out_len)
