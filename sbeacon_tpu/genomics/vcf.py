"""VCF record parsing and synthetic-VCF generation.

The reference never parses VCF itself on the query path — it shells out to
``bcftools query`` per region (reference: lambda/performQuery/
search_variants.py:42-50) — and its C++ ingest scans raw bytes for the
handful of columns it needs (reference: lambda/summariseSlice/source/
main.cpp:52-109). Here the parse is an explicit, tested layer: records come
out with exactly the fields the matching semantics consume (POS, REF, ALTs,
INFO AC/AN/VT, genotypes), feeding both the CPU oracle and the columnar
index builder.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from pathlib import Path

from .bgzf import BgzfWriter

_CALLS = re.compile(r"[0-9]+")

#: GT-string -> call tuple memo (cohorts use a handful of GT spellings;
#: bounded against pathological cardinality)
_CALLS_MEMO: dict[str, tuple[int, ...]] = {}


def _calls_for(gt: str) -> tuple[int, ...]:
    r = _CALLS_MEMO.get(gt)
    if r is None:
        r = tuple(int(m) for m in _CALLS.findall(gt))
        if len(_CALLS_MEMO) < 1 << 16:
            _CALLS_MEMO[gt] = r
    return r


@dataclass
class VcfRecord:
    chrom: str
    pos: int  # 1-based, as in the file
    ref: str
    alts: list[str]
    # INFO-derived; None when absent from the file
    ac: list[int] | None  # per-alt allele counts (INFO AC)
    an: int | None  # total allele number (INFO AN)
    vt: str  # INFO VT, 'N/A' when absent (reference main default)
    genotypes: list[str]  # raw GT strings per sample, e.g. '0|1'

    def genotype_calls(self) -> list[int]:
        """All haplotype allele indices, reference-style.

        Matches ``get_all_calls`` = ``re.compile('[0-9]+').findall`` over the
        joined genotype column (reference: performQuery/search_variants.py:
        28-29,219) — every integer in every GT contributes one call; '.'
        (missing) contributes none.
        """
        calls: list[int] = []
        for gt in self.genotypes:
            calls.extend(_calls_for(gt))
        return calls

    def effective_ac(self) -> list[int]:
        """Per-alt allele count: INFO AC when present, else genotype tally."""
        if self.ac is not None:
            return self.ac
        calls = self.genotype_calls()
        return [sum(1 for c in calls if c == i + 1) for i in range(len(self.alts))]

    def effective_an(self) -> int:
        """Allele number: INFO AN when present, else number of calls."""
        if self.an is not None:
            return self.an
        return len(self.genotype_calls())


def parse_info(info_str: str) -> tuple[list[int] | None, int | None, str]:
    """Extract (AC list, AN, VT) from an INFO column string.

    Mirrors the INFO scan in the reference hot loop (performQuery/
    search_variants.py:195-201): only ``AC=``, ``AN=``, ``VT=`` matter.
    """
    ac = None
    an = None
    vt = "N/A"
    for info in info_str.split(";"):
        if info.startswith("AC="):
            try:
                ac = [int(c) for c in info[3:].split(",")]
            except ValueError:
                ac = None
        elif info.startswith("AN="):
            try:
                an = int(info[3:])
            except ValueError:
                an = None
        elif info.startswith("VT="):
            vt = info[3:]
    return ac, an, vt


def parse_record(line: str | bytes) -> VcfRecord | None:
    """Parse one VCF body line; None for headers/empty lines."""
    if isinstance(line, bytes):
        line = line.decode()
    if not line or line.startswith("#"):
        return None
    fields = line.rstrip("\n").split("\t")
    if len(fields) < 8:
        return None
    chrom, pos, _id, ref, alt_str, _qual, _filt, info = fields[:8]
    genotypes: list[str] = []
    if len(fields) > 9:
        fmt = fields[8].split(":")
        try:
            gt_idx = fmt.index("GT")
        except ValueError:
            gt_idx = -1
        if gt_idx == 0:
            # GT-first is the overwhelmingly common FORMAT layout;
            # partition beats a full split across every sample column
            genotypes = [s.partition(":")[0] for s in fields[9:]]
        elif gt_idx > 0:
            for sample in fields[9:]:
                parts = sample.split(":")
                genotypes.append(parts[gt_idx] if gt_idx < len(parts) else ".")
    ac, an, vt = parse_info(info)
    return VcfRecord(
        chrom=chrom,
        pos=int(pos),
        ref=ref,
        alts=alt_str.split(","),
        ac=ac,
        an=an,
        vt=vt,
        genotypes=genotypes,
    )


def iter_vcf_records(
    path: str | Path,
    region: tuple[str, int, int] | None = None,
    index=None,
):
    """Yield VcfRecords from a bgzipped VCF, optionally region-filtered.

    ``region`` is (chrom, start, end) 1-based inclusive, bcftools
    ``--regions`` style: records whose REF span overlaps the region are
    yielded (htslib overlap semantics, which is why the reference re-checks
    ``first_bp <= pos <= last_bp`` afterwards — performQuery/
    search_variants.py:83-85). When a .tbi/.csi sits next to the file (or
    via ``index=``), the region path seeks straight to the candidate chunks
    instead of inflating the whole file.
    """
    from .bgzf import BgzfReader
    from .tabix import find_index_for

    reader = BgzfReader(path)
    if region is None:
        for _, line in reader.iter_lines():
            rec = parse_record(line)
            if rec is not None:
                yield rec
        return

    chrom, start, end = region
    if index is None:
        index = find_index_for(path)
    if index is not None and index.ref_id(chrom) is not None:
        spans = [
            (c.beg, c.end) for c in index.chunks_for_region(chrom, start - 1, end)
        ]
    else:
        spans = [(0, None)]
    for beg, stop in spans:
        for _, line in reader.iter_lines(beg, stop):
            rec = parse_record(line)
            if rec is None:
                continue
            if rec.chrom != chrom:
                if index is not None:
                    # sorted file + indexed seek: past this contig means done
                    break
                continue
            if rec.pos > end:
                break
            if rec.pos + len(rec.ref) - 1 < start:
                continue
            yield rec


def read_sample_names(path: str | Path) -> list[str]:
    """Sample names from the #CHROM header line (reference:
    summariseVcf/lambda_function.py:128-141 reads the same to count samples).
    """
    from .bgzf import BgzfReader

    reader = BgzfReader(path)
    for _, line in reader.iter_lines():
        if line.startswith(b"#CHROM"):
            cols = line.decode().rstrip("\n").split("\t")
            return cols[9:] if len(cols) > 9 else []
        if not line.startswith(b"#"):
            break
    return []


# ---------------------------------------------------------------------------
# Synthetic VCF writing (fixtures + simulation harness)
# ---------------------------------------------------------------------------

VCF_HEADER_LINES = [
    "##fileformat=VCFv4.2",
    '##INFO=<ID=AC,Number=A,Type=Integer,Description="Allele count">',
    '##INFO=<ID=AN,Number=1,Type=Integer,Description="Allele number">',
    '##INFO=<ID=VT,Number=.,Type=String,Description="Variant type">',
    '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">',
]


def write_vcf(
    path: str | Path,
    records: list[VcfRecord],
    sample_names: list[str] | None = None,
    contigs: list[str] | None = None,
) -> None:
    """Write a bgzipped VCF from records (sorted by (chrom order, pos))."""
    if sample_names is None:
        n = max((len(r.genotypes) for r in records), default=0)
        sample_names = [f"S{i:04d}" for i in range(n)]
    header = list(VCF_HEADER_LINES)
    if contigs is None:
        contigs = []
        for r in records:
            if r.chrom not in contigs:
                contigs.append(r.chrom)
    for c in contigs:
        header.append(f"##contig=<ID={c}>")
    cols = ["#CHROM", "POS", "ID", "REF", "ALT", "QUAL", "FILTER", "INFO"]
    if sample_names:
        cols += ["FORMAT"] + sample_names
    header.append("\t".join(cols))
    with BgzfWriter(path) as w:
        for line in header:
            w.write(line + "\n")
        for r in records:
            info_parts = []
            if r.ac is not None:
                info_parts.append("AC=" + ",".join(str(a) for a in r.ac))
            if r.an is not None:
                info_parts.append(f"AN={r.an}")
            if r.vt and r.vt != "N/A":
                info_parts.append(f"VT={r.vt}")
            info = ";".join(info_parts) if info_parts else "."
            fields = [
                r.chrom,
                str(r.pos),
                ".",
                r.ref,
                ",".join(r.alts),
                ".",
                "PASS",
                info,
            ]
            if sample_names:
                fields.append("GT")
                gts = list(r.genotypes) + ["0|0"] * (
                    len(sample_names) - len(r.genotypes)
                )
                fields.extend(gts)
            w.write("\t".join(fields) + "\n")
