"""Tabix (.tbi) and CSI (.csi) index parsing.

The reference parses these with small pure-python binary readers to plan its
ingest fan-out (reference: lambda/summariseVcf/index_reader.py — Csi :4-61,
Tbi :64-125) and shells out to ``tabix --list-chroms`` to discover a VCF's
contigs (reference: shared_resources/utils/chrom_matching.py:43-61). This
module provides both capabilities natively: full bin/linear index parsing
(R-tree chunk lookup for region slicing) and contig listing, with no
external binary.

Binary layouts follow the SAM/tabix specification (htslib). Both index
flavours are BGZF/gzip-compressed on disk.
"""

from __future__ import annotations

import gzip
import struct
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class Chunk:
    beg: int  # virtual offset
    end: int  # virtual offset


@dataclass
class RefIndex:
    bins: dict[int, list[Chunk]] = field(default_factory=dict)
    # loff per bin (CSI) or 16kb linear index (TBI)
    linear: list[int] = field(default_factory=list)
    bin_loff: dict[int, int] = field(default_factory=dict)


@dataclass
class TabixIndex:
    names: list[str]
    refs: list[RefIndex]
    min_shift: int
    depth: int
    # tabix header config (column layout for generic files; VCF: 1,2,0)
    fmt: int = 2
    col_seq: int = 1
    col_beg: int = 2
    col_end: int = 0
    meta_char: int = ord("#")
    skip: int = 0

    @property
    def chromosomes(self) -> list[str]:
        return list(self.names)

    def ref_id(self, name: str) -> int | None:
        try:
            return self.names.index(name)
        except ValueError:
            return None

    def reg2bins(self, beg: int, end: int) -> list[int]:
        """All bins overlapping [beg, end) (0-based, half-open)."""
        bins = []
        if end <= beg:
            end = beg + 1
        end -= 1
        t = 0
        s = self.min_shift + self.depth * 3
        for level in range(self.depth + 1):
            b = t + (beg >> s)
            e = t + (end >> s)
            bins.extend(range(b, e + 1))
            s -= 3
            t += 1 << (level * 3)
        return bins

    def chunks_for_region(self, ref_name: str, beg: int, end: int) -> list[Chunk]:
        """Candidate virtual-offset chunks overlapping [beg, end) 0-based."""
        rid = self.ref_id(ref_name)
        if rid is None:
            return []
        ref = self.refs[rid]
        min_voff = 0
        if ref.linear:
            # TBI linear index: 16kb windows give a lower bound voffset;
            # windows past the end of the index use the last entry.
            win = beg >> 14
            if win < len(ref.linear):
                min_voff = ref.linear[win]
            else:
                min_voff = ref.linear[-1]
        chunks = []
        for b in self.reg2bins(beg, end):
            for ck in ref.bins.get(b, ()):
                if ck.end > min_voff:
                    chunks.append(Chunk(max(ck.beg, min_voff), ck.end))
        chunks.sort(key=lambda c: c.beg)
        # merge adjacent/overlapping
        merged: list[Chunk] = []
        for ck in chunks:
            if merged and ck.beg <= merged[-1].end:
                merged[-1].end = max(merged[-1].end, ck.end)
            else:
                merged.append(Chunk(ck.beg, ck.end))
        return merged

    def first_voffset(self, ref_name: str) -> int | None:
        rid = self.ref_id(ref_name)
        if rid is None:
            return None
        ref = self.refs[rid]
        candidates = [c.beg for chunks in ref.bins.values() for c in chunks]
        return min(candidates) if candidates else None


def _parse_tabix_aux(aux: bytes) -> tuple[dict, list[str]]:
    fmt, col_seq, col_beg, col_end, meta, skip, l_nm = struct.unpack_from(
        "<7i", aux, 0
    )
    names_blob = aux[28 : 28 + l_nm]
    names = [n.decode() for n in names_blob.split(b"\x00") if n]
    cfg = dict(
        fmt=fmt,
        col_seq=col_seq,
        col_beg=col_beg,
        col_end=col_end,
        meta_char=meta,
        skip=skip,
    )
    return cfg, names


def parse_tbi(path: str | Path) -> TabixIndex:
    from ..io import read_bytes

    data = gzip.decompress(read_bytes(path))
    if data[:4] != b"TBI\x01":
        raise ValueError("bad .tbi magic")
    (n_ref,) = struct.unpack_from("<i", data, 4)
    cfg, names = _parse_tabix_aux(data[8:])
    (l_nm,) = struct.unpack_from("<i", data, 8 + 24)
    pos = 8 + 28 + l_nm
    refs = []
    for _ in range(n_ref):
        (n_bin,) = struct.unpack_from("<i", data, pos)
        pos += 4
        ref = RefIndex()
        for _ in range(n_bin):
            bin_no, n_chunk = struct.unpack_from("<Ii", data, pos)
            pos += 8
            chunks = []
            for _ in range(n_chunk):
                beg, end = struct.unpack_from("<QQ", data, pos)
                pos += 16
                chunks.append(Chunk(beg, end))
            ref.bins[bin_no] = chunks
        (n_intv,) = struct.unpack_from("<i", data, pos)
        pos += 4
        ref.linear = list(struct.unpack_from(f"<{n_intv}Q", data, pos))
        pos += 8 * n_intv
        refs.append(ref)
    return TabixIndex(names=names, refs=refs, min_shift=14, depth=5, **cfg)


def parse_csi(path: str | Path) -> TabixIndex:
    from ..io import read_bytes

    data = gzip.decompress(read_bytes(path))
    if data[:4] != b"CSI\x01":
        raise ValueError("bad .csi magic")
    min_shift, depth, l_aux = struct.unpack_from("<3i", data, 4)
    aux = data[16 : 16 + l_aux]
    cfg: dict = {}
    names: list[str] = []
    if l_aux >= 28:
        cfg, names = _parse_tabix_aux(aux)
    pos = 16 + l_aux
    (n_ref,) = struct.unpack_from("<i", data, pos)
    pos += 4
    refs = []
    for _ in range(n_ref):
        (n_bin,) = struct.unpack_from("<i", data, pos)
        pos += 4
        ref = RefIndex()
        for _ in range(n_bin):
            bin_no, loff, n_chunk = struct.unpack_from("<IQi", data, pos)
            pos += 16
            chunks = []
            for _ in range(n_chunk):
                beg, end = struct.unpack_from("<QQ", data, pos)
                pos += 16
                chunks.append(Chunk(beg, end))
            ref.bins[bin_no] = chunks
            ref.bin_loff[bin_no] = loff
        refs.append(ref)
    return TabixIndex(names=names, refs=refs, min_shift=min_shift, depth=depth, **cfg)


def parse_index(path: str | Path) -> TabixIndex:
    p = str(path)
    if p.endswith(".csi"):
        return parse_csi(path)
    return parse_tbi(path)


# parsed-index cache for REMOTE locations only: one submission touches the
# index from the reachability probe, the chromosome map, and the slice
# planner — without a cache that is 3 full .tbi transfers through an
# object store per VCF. Local paths stay uncached (tests and re-indexing
# rewrite them in place). Entries expire so a re-uploaded index is seen.
_REMOTE_IDX_CACHE: dict[str, tuple[float, "TabixIndex | None"]] = {}
_REMOTE_IDX_TTL_S = 60.0
_REMOTE_IDX_MAX = 256


def find_index_for(vcf_path: str | Path) -> TabixIndex | None:
    """Locate and parse the .tbi/.csi next to a VCF, if present — local
    path or remote object (the reference's S3 layout keeps the index at
    the same key + extension, summariseVcf/lambda_function.py get_vcf_index).
    """
    import time as _time

    from ..io import is_remote, open_source

    key = str(vcf_path)
    if is_remote(key):
        hit = _REMOTE_IDX_CACHE.get(key)
        if hit is not None and _time.monotonic() - hit[0] < _REMOTE_IDX_TTL_S:
            return hit[1]
        idx = None
        for ext in (".tbi", ".csi"):
            cand = key + ext
            if open_source(cand).exists():
                idx = parse_index(cand)
                break
        if len(_REMOTE_IDX_CACHE) >= _REMOTE_IDX_MAX:
            _REMOTE_IDX_CACHE.clear()
        _REMOTE_IDX_CACHE[key] = (_time.monotonic(), idx)
        return idx
    for ext in (".tbi", ".csi"):
        cand = key + ext
        if Path(cand).exists():
            return parse_index(cand)
    return None


def list_chromosomes(vcf_path: str | Path) -> list[str]:
    """Contig names for a bgzipped VCF.

    Replaces the reference's ``tabix --list-chroms`` subprocess
    (chrom_matching.py:43-61): uses the .tbi/.csi when present, else scans
    the VCF body.
    """
    idx = find_index_for(vcf_path)
    if idx is not None and idx.names:
        return idx.chromosomes
    from .bgzf import BgzfReader

    seen: list[str] = []
    reader = BgzfReader(vcf_path)
    for _, line in reader.iter_lines():
        if line.startswith(b"#"):
            continue
        chrom = line.split(b"\t", 1)[0].decode()
        if not seen or seen[-1] != chrom:
            if chrom not in seen:
                seen.append(chrom)
    return seen


def write_tbi(idx: TabixIndex, path: str | Path) -> None:
    """Serialise a TabixIndex to the on-disk .tbi format (BGZF-wrapped,
    SAM/tabix spec layout — the inverse of ``parse_tbi``)."""
    out = bytearray()
    out += b"TBI\x01"
    out += struct.pack("<i", len(idx.names))
    out += struct.pack(
        "<6i",
        idx.fmt,
        idx.col_seq,
        idx.col_beg,
        idx.col_end,
        idx.meta_char,
        idx.skip,
    )
    names_blob = b"".join(n.encode() + b"\x00" for n in idx.names)
    out += struct.pack("<i", len(names_blob))
    out += names_blob
    for ref in idx.refs:
        out += struct.pack("<i", len(ref.bins))
        for bin_no in sorted(ref.bins):
            chunks = ref.bins[bin_no]
            out += struct.pack("<Ii", bin_no, len(chunks))
            for ck in chunks:
                out += struct.pack("<QQ", ck.beg, ck.end)
        out += struct.pack("<i", len(ref.linear))
        out += struct.pack(f"<{len(ref.linear)}Q", *ref.linear)
    from .bgzf import BgzfWriter

    with BgzfWriter(path) as w:
        w.write(bytes(out))


def ensure_index(vcf_path: str | Path) -> TabixIndex:
    """Parse the existing .tbi/.csi, or self-index the VCF and persist the
    result (the framework's replacement for requiring external ``tabix``
    runs before submission). Remote objects cannot be self-indexed in
    place — like the reference, they must ship with their index."""
    from ..io import is_remote

    idx = find_index_for(vcf_path)
    if idx is not None:
        return idx
    if is_remote(vcf_path):
        raise ValueError(
            f"remote VCF {vcf_path} has no .tbi/.csi alongside it; "
            "remote submissions must be pre-indexed"
        )
    idx = build_tbi(vcf_path)
    write_tbi(idx, str(vcf_path) + ".tbi")
    return idx


def build_tbi(vcf_path: str | Path) -> TabixIndex:
    """Build a tabix-equivalent index in memory by scanning the VCF.

    The reference assumes indexes are produced externally by ``tabix``; the
    framework can self-index. Only the linear (16kb window -> first voffset)
    and per-contig single-bin chunk lists are populated — enough for
    region slicing and contig listing.
    """
    from .bgzf import BgzfReader, make_virtual_offset

    reader = BgzfReader(vcf_path)
    names: list[str] = []
    refs: list[RefIndex] = []
    cur_ref: RefIndex | None = None
    first_voff = None
    for voff, line in reader.iter_lines():
        if line.startswith(b"#") or not line:
            continue
        fields = line.split(b"\t", 3)
        chrom = fields[0].decode()
        pos0 = int(fields[1]) - 1
        if not names or names[-1] != chrom:
            if chrom in names:
                raise ValueError(
                    f"VCF contigs out of order: revisited {chrom!r}"
                )
            if cur_ref is not None and first_voff is not None:
                # previous contig's chunk ends where this line begins
                cur_ref.bins[0] = [Chunk(first_voff, voff)]
            names.append(chrom)
            cur_ref = RefIndex()
            refs.append(cur_ref)
            first_voff = voff
        win = pos0 >> 14
        while len(cur_ref.linear) <= win:
            cur_ref.linear.append(voff)
    if cur_ref is not None and first_voff is not None:
        eof_voff = make_virtual_offset(len(reader._data), 0)
        cur_ref.bins[0] = [Chunk(first_voff, eof_voff)]
    return TabixIndex(names=names, refs=refs, min_shift=14, depth=5)
