"""BGZF (blocked gzip) reading and writing.

BGZF is the framing used by bgzipped VCFs: a sequence of independent gzip
members, each at most 64 KiB uncompressed, whose total compressed size is
recorded in a BSIZE extra field so readers can hop block-to-block without
inflating. Positions inside the stream are "virtual offsets":
``(compressed_block_offset << 16) | offset_within_uncompressed_block``.

The reference consumes this format with a C++ streaming reader that splits a
VCF at block boundaries for Lambda fan-out (reference:
lambda/summariseSlice/source/vcf_chunk_reader.h:24-32 for the virtual-offset
split, :143-174 for block header parsing). This module provides the same
capabilities as a clean library: block scanning, random access by virtual
offset, region slicing for parallel ingest, and a writer for producing
bgzipped fixtures/outputs (the reference relies on the external ``bgzip``
binary for that).
"""

from __future__ import annotations

import io
import struct
import zlib
from pathlib import Path

# 18-byte BGZF member header: gzip magic, deflate, FEXTRA, mtime 0, XFL 0,
# OS unknown, XLEN=6, extra subfield BC(2) len 2, BSIZE u16.
_HEADER = struct.Struct("<BBBBIBBHBBHH")
_HEADER_SIZE = 18
_MAX_UNCOMPRESSED = 65280  # bgzip's per-block payload cap

# The canonical 28-byte BGZF EOF marker block.
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)


class BgzfError(ValueError):
    pass


def make_virtual_offset(block_offset: int, within_offset: int) -> int:
    return (block_offset << 16) | within_offset


def split_virtual_offset(voffset: int) -> tuple[int, int]:
    return voffset >> 16, voffset & 0xFFFF


def read_block_header(buf: bytes, pos: int = 0) -> int:
    """Parse one BGZF member header at ``pos``; return total block size."""
    if len(buf) - pos < _HEADER_SIZE:
        raise BgzfError("truncated BGZF header")
    (id1, id2, cm, flg, _mtime, _xfl, _os, xlen, si1, si2, slen, bsize) = (
        _HEADER.unpack_from(buf, pos)
    )
    if id1 != 0x1F or id2 != 0x8B or cm != 8:
        raise BgzfError("not a gzip member")
    if not flg & 4:
        raise BgzfError("gzip member without FEXTRA — not BGZF")
    if si1 != 66 or si2 != 67 or slen != 2 or xlen < 6:
        # Extra field may hold more subfields; scan for BC.
        end = pos + 12 + xlen
        p = pos + 12
        while p + 4 <= end:
            s1, s2, sl = buf[p], buf[p + 1], struct.unpack_from("<H", buf, p + 2)[0]
            if s1 == 66 and s2 == 67 and sl == 2:
                bsize = struct.unpack_from("<H", buf, p + 4)[0]
                break
            p += 4 + sl
        else:
            raise BgzfError("no BGZF BC subfield")
    return bsize + 1


def decompress_block(buf: bytes, pos: int = 0) -> tuple[bytes, int]:
    """Inflate the BGZF block at ``pos``; return (payload, total_block_size)."""
    size = read_block_header(buf, pos)
    # Deflate data sits between the 18-byte header and the 8-byte trailer
    # (CRC32 + ISIZE). zlib with wbits=-15 consumes raw deflate.
    xlen = struct.unpack_from("<H", buf, pos + 10)[0]
    data_start = pos + 12 + xlen
    comp = buf[data_start : pos + size - 8]
    payload = zlib.decompress(comp, wbits=-15)
    (crc, isize) = struct.unpack_from("<II", buf, pos + size - 8)
    if isize != len(payload):
        raise BgzfError("BGZF ISIZE mismatch")
    if crc != (zlib.crc32(payload) & 0xFFFFFFFF):
        raise BgzfError("BGZF CRC mismatch")
    return payload, size


def compress_block(payload: bytes, level: int = 6) -> bytes:
    """Produce one complete BGZF member for <=65280 payload bytes."""
    if len(payload) > _MAX_UNCOMPRESSED:
        raise BgzfError("payload too large for one BGZF block")
    compressor = zlib.compressobj(level, zlib.DEFLATED, -15)
    comp = compressor.compress(payload) + compressor.flush()
    bsize = _HEADER_SIZE + len(comp) + 8 - 1
    if bsize >= 1 << 16:
        # Incompressible payload: retry with stored blocks via level 0.
        compressor = zlib.compressobj(0, zlib.DEFLATED, -15)
        comp = compressor.compress(payload) + compressor.flush()
        bsize = _HEADER_SIZE + len(comp) + 8 - 1
        if bsize >= 1 << 16:
            raise BgzfError("block does not fit even stored")
    header = _HEADER.pack(
        0x1F, 0x8B, 8, 4, 0, 0, 0xFF, 6, 66, 67, 2, bsize
    )
    trailer = struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    return header + comp + trailer


class BgzfWriter:
    """Streaming BGZF writer (the role bgzip plays for the reference)."""

    def __init__(self, path: str | Path, level: int = 6):
        self._fh = open(path, "wb")
        self._level = level
        self._buf = bytearray()

    def write(self, data: bytes | str) -> None:
        if isinstance(data, str):
            data = data.encode()
        self._buf.extend(data)
        while len(self._buf) >= _MAX_UNCOMPRESSED:
            chunk = bytes(self._buf[:_MAX_UNCOMPRESSED])
            del self._buf[:_MAX_UNCOMPRESSED]
            self._fh.write(compress_block(chunk, self._level))

    def close(self) -> None:
        if self._buf:
            self._fh.write(compress_block(bytes(self._buf), self._level))
            self._buf.clear()
        self._fh.write(BGZF_EOF)
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def scan_blocks(path: str | Path) -> list[tuple[int, int, int]]:
    """Hop through a BGZF file reading only headers.

    Returns [(compressed_offset, compressed_size, uncompressed_size)] per
    block, excluding the EOF block. This gives the ingest planner its slice
    boundaries without any .tbi/.csi (the reference needs the tabix index
    for this, lambda/summariseVcf/index_reader.py).
    """
    out = []
    with open(path, "rb") as fh:
        data = fh.read()
    pos = 0
    n = len(data)
    while pos < n:
        size = read_block_header(data, pos)
        isize = struct.unpack_from("<I", data, pos + size - 4)[0]
        if isize > 0:
            out.append((pos, size, isize))
        pos += size
    return out


class BgzfReader:
    """Random-access BGZF reader with virtual-offset seeks.

    Local files are held in memory (framework files are block-sliced
    before they get here; the C++ path streams). A small block cache makes
    sequential line iteration cheap.

    Remote objects (``http(s)://`` / ``s3://`` — sbeacon_tpu.io sources)
    are read by RANGED GETs: a bounded read prefetches its compressed
    span in one concurrent chunked fetch (the reference's 4-thread
    download ring, vcf_chunk_reader.h:69-105 + downloader.h), and
    unbounded iteration streams segment-sized fetches — the whole object
    is never required to be local.
    """

    #: remote segment fetch size for unbounded iteration
    SEG_BYTES = 2 * 1024 * 1024
    #: max size of one compressed BGZF block (BSIZE is u16)
    _BLOCK_MAX = 1 << 16

    def __init__(self, path: str | Path):
        from ..io import is_remote, open_source

        self._path = str(path)
        self._remote = is_remote(self._path)
        self._source = open_source(self._path) if self._remote else None
        self._data_loaded: bytes | None = None  # lazy: native paths never
        self._block_cache_off = -1              # touch the python copy
        self._block_cache: bytes = b""
        self._block_cache_size = 0
        self._seg_start = 0                     # remote segment buffer
        self._seg: bytes = b""

    @property
    def _data(self) -> bytes:
        if self._data_loaded is None:
            if self._remote:
                self._data_loaded = self._source.read_range(
                    0, self._source.size(), workers=4
                )
            else:
                with open(self._path, "rb") as fh:
                    self._data_loaded = fh.read()
        return self._data_loaded

    @property
    def _csize(self) -> int:
        """Compressed object size without forcing a full download."""
        if self._remote and self._data_loaded is None:
            return self._source.size()
        return len(self._data)

    def _native(self):
        """The C++ codec when built (parallel block inflate); None keeps
        the pure-Python path (also on single-core hosts, where the pool
        cannot beat python's one-shot zlib — see native.prefer_native_io).
        Remote objects always use the python path (the native codec reads
        local files).
        """
        if self._remote:
            return None
        try:
            from .. import native

            return native if native.prefer_native_io() else None
        except Exception:
            return None

    def _block_buf(self, coffset: int) -> tuple[bytes, int]:
        """(buffer, position) with the whole block at ``coffset`` present."""
        if not self._remote or self._data_loaded is not None:
            return self._data, coffset
        need_end = min(coffset + self._BLOCK_MAX, self._csize)
        covered = (
            self._seg_start <= coffset
            and need_end <= self._seg_start + len(self._seg)
        )
        if not covered:
            seg_end = min(
                max(coffset + self.SEG_BYTES, need_end), self._csize
            )
            self._seg = self._source.read_range(coffset, seg_end, workers=4)
            self._seg_start = coffset
        return self._seg, coffset - self._seg_start

    def prefetch(self, voffset_start: int, voffset_end: int) -> None:
        """One concurrent ranged fetch covering a virtual-offset span —
        block loads inside the span then hit the local segment."""
        if not self._remote or self._data_loaded is not None:
            return
        c0, _ = split_virtual_offset(voffset_start)
        c1, _ = split_virtual_offset(voffset_end)
        end = min(c1 + self._BLOCK_MAX, self._csize)
        if (
            self._seg_start <= c0
            and end <= self._seg_start + len(self._seg)
        ):
            return
        self._seg = self._source.read_range(c0, end, workers=4)
        self._seg_start = c0

    def _load_block(self, coffset: int) -> bytes:
        if coffset != self._block_cache_off:
            buf, pos = self._block_buf(coffset)
            payload, size = decompress_block(buf, pos)
            self._block_cache = payload
            self._block_cache_off = coffset
            self._block_cache_size = size
        return self._block_cache

    def read_all(self) -> bytes:
        nat = self._native()
        if nat is not None:
            try:
                return nat.inflate_range(self._path)
            except Exception:
                pass
        out = io.BytesIO()
        pos = 0
        while pos < len(self._data):
            payload, size = decompress_block(self._data, pos)
            out.write(payload)
            pos += size
        return out.getvalue()

    def read_range(self, voffset_start: int, voffset_end: int) -> bytes:
        """Uncompressed bytes in [voffset_start, voffset_end)."""
        nat = self._native()
        if nat is not None:
            try:
                return nat.inflate_range(
                    self._path, voffset_start, voffset_end
                )
            except Exception:
                pass
        self.prefetch(voffset_start, voffset_end)
        out = io.BytesIO()
        coff, uoff = split_virtual_offset(voffset_start)
        end_coff, end_uoff = split_virtual_offset(voffset_end)
        while True:
            payload = self._load_block(coff)
            size = self._block_cache_size
            if coff == end_coff:
                out.write(payload[uoff:end_uoff])
                break
            out.write(payload[uoff:])
            coff += size
            uoff = 0
            if coff >= self._csize or not payload:
                break
            if coff > end_coff:
                break
        return out.getvalue()

    def iter_lines(self, voffset_start: int = 0, voffset_end: int | None = None):
        """Yield (voffset_of_line_start, line_bytes_without_newline).

        Lines starting at or after ``voffset_end`` (when given) are not
        yielded; the final partial line (no trailing newline) is yielded.
        """
        if voffset_end is not None:
            self.prefetch(voffset_start, voffset_end)
        coff, uoff = split_virtual_offset(voffset_start)
        end = voffset_end
        carry = b""
        carry_voff = voffset_start
        while coff < self._csize:
            if end is not None and make_virtual_offset(coff, uoff) >= end:
                break
            payload = self._load_block(coff)
            size = self._block_cache_size
            chunk = payload[uoff:]
            base_coff, base_uoff = coff, uoff
            start = 0
            while True:
                nl = chunk.find(b"\n", start)
                if nl < 0:
                    carry += chunk[start:]
                    break
                line_voff = (
                    carry_voff
                    if carry
                    else make_virtual_offset(base_coff, base_uoff + start)
                )
                if end is not None and line_voff >= end:
                    return
                yield line_voff, carry + chunk[start:nl]
                carry = b""
                start = nl + 1
                carry_voff = make_virtual_offset(base_coff, base_uoff + start)
            if not carry:
                carry_voff = make_virtual_offset(coff + size, 0)
            coff += size
            uoff = 0
            if not payload:
                break
        if carry:
            if end is None or carry_voff < end:
                yield carry_voff, carry
