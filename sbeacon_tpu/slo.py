"""SLO burn-rate engine: multi-window error-budget burn per route.

The reference answers "is the service healthy" with AWS-provided
observability — CloudWatch metric alarms over API Gateway 5xx counts
and Lambda duration percentiles. A TPU-native deployment has no such
platform tier, so this module provides the layer itself, implementing
the multi-window burn-rate methodology (Google SRE Workbook ch. 5,
"Alerting on SLOs"): each route carries two objectives —

- **availability**: at most ``1 - availability_target`` of requests may
  answer 5xx (e.g. target 0.999 -> 0.1% error budget);
- **latency**: at least ``latency_target`` of non-5xx requests must
  finish under ``latency_ms`` (e.g. ``boolean p99 < 50ms`` declares
  latency_ms=50, latency_target=0.99).

Good/bad counts land in ring-buffered per-bucket counters spanning the
longest window, and the **burn rate** over a window is ``observed bad
ratio / error budget`` — 1.0 means the route is consuming its budget
exactly at the sustainable rate, 14.4 (the classic fast-page factor)
means a 30-day budget would be gone in 2 days. A route is **breached**
when BOTH the fast (5m) and slow (1h) windows burn above the alert
factor — the two-window AND is what makes the signal precise (the slow
window proves it's real, the fast window proves it's still happening).

Objectives are declared in :class:`~sbeacon_tpu.config.
ObservabilityConfig` (``BEACON_SLO_*`` env): one default objective plus
per-route overrides. Everything is stdlib-only with an injectable clock
(tests drive window rollover without sleeping); ``record`` is O(1) —
one lock, two ring-bucket increments — and sits on the request path.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time

#: (name, seconds) — fast and slow burn windows, in rendering order
WINDOWS: tuple[tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))

#: THE single literal source of the probe/diagnostic route surface
#: (ISSUE 12 satellite). Three request-path lists used to hand-maintain
#: their own copies of "what is a probe" — the SLO budget exclusion
#: here, the API layer's auth/admission bypass set, and the
#: request-latency histogram's named diagnostic labels — and drift
#: between them silently folded probe traffic into error budgets.
#: Everything now DERIVES from this set (``tools/check_probe_routes.py``
#: enforces it statically, tier-1 via tests/test_telemetry.py):
#: single-segment entries are route labels AND paths; dotted entries
#: are the two-segment diagnostic surfaces (``ops.events`` =
#: ``/ops/events``); ``canary`` is the prober's synthetic in-process
#: route (sbeacon_tpu/canary.py) — excluded from budgets and cost
#: tables like every probe, though it never arrives over HTTP.
PROBE_ROUTE_LABELS = frozenset({
    "health",
    "ready",
    "metrics",
    "slo",
    "_trace",
    "canary",
    "ops.events",
    "ops.costs",
    "ops.plans",
    "debug.status",
    "device.status",
    "fleet.status",
    "fleet.migrations",
})

#: probe labels that are NOT auth/admission-bypass transport paths:
#: ``/_trace`` can render large span trees so it stays behind the
#: admission gate, and ``canary`` is never an HTTP path at all
NON_PATH_PROBE_LABELS = frozenset({"_trace", "canary"})

#: probe labels with no HTTP path at all (the prober's synthetic
#: in-process route) — everything else appears in the API route table
NON_HTTP_PROBE_LABELS = frozenset({"canary"})

#: the API layer's bypass set (served before auth/admission/deadlines)
PROBE_BYPASS_PATHS = frozenset(
    label.replace(".", "/")
    for label in PROBE_ROUTE_LABELS - NON_PATH_PROBE_LABELS
)

#: single-segment probe labels that ARE HTTP route heads (the latency
#: histogram's bounded head set derives its probe members from this)
PROBE_HEAD_LABELS = frozenset(
    label
    for label in PROBE_ROUTE_LABELS - NON_HTTP_PROBE_LABELS
    if "." not in label
)

#: the two-segment diagnostic surfaces the latency histogram may mint
#: named route labels for (anything else under their heads collapses
#: to "other" so a URL scanner cannot mint series)
DIAGNOSTIC_ROUTE_LABELS = frozenset(
    label for label in PROBE_ROUTE_LABELS if "." in label
)

#: probe/diagnostic routes never carry objectives: scrapes and status
#: queries must not consume (or fabricate) anyone's error budget
EXCLUDED_ROUTES = frozenset(
    label for label in PROBE_ROUTE_LABELS if "." not in label
)
_EXCLUDED_HEADS = tuple(
    sorted({label.split(".", 1)[0] for label in DIAGNOSTIC_ROUTE_LABELS})
)


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One route's objectives (availability + latency threshold)."""

    availability_target: float = 0.999
    latency_ms: float = 250.0
    latency_target: float = 0.99

    def __post_init__(self):
        for f in ("availability_target", "latency_target"):
            v = getattr(self, f)
            if not (0.0 < v < 1.0):
                raise ValueError(f"{f} must be in (0, 1), got {v}")
        if self.latency_ms <= 0:
            raise ValueError("latency_ms must be > 0")


def parse_route_objectives(
    spec: str, default: SloObjective
) -> dict[str, SloObjective]:
    """Per-route overrides from the compact ``BEACON_SLO_ROUTES`` form:
    comma-separated ``route:field=value[:field=value...]`` entries, e.g.
    ``g_variants:latency_ms=50:latency_target=0.99,info:availability=0.99``.
    Unknown fields or malformed entries raise at wiring time — a typo'd
    objective silently falling back to the default is exactly the kind
    of drift an SLO declaration exists to prevent."""
    out: dict[str, SloObjective] = {}
    field_of = {
        "availability": "availability_target",
        "availability_target": "availability_target",
        "latency_ms": "latency_ms",
        "latency_target": "latency_target",
    }
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        parts = entry.split(":")
        route, overrides = parts[0].strip(), {}
        if not route:
            raise ValueError(f"BEACON_SLO_ROUTES entry missing route: {entry!r}")
        for kv in parts[1:]:
            key, sep, val = kv.partition("=")
            if not sep or key.strip() not in field_of:
                raise ValueError(
                    f"BEACON_SLO_ROUTES: bad field {kv!r} in {entry!r} "
                    "(want availability=/latency_ms=/latency_target=)"
                )
            overrides[field_of[key.strip()]] = float(val)
        out[route] = dataclasses.replace(default, **overrides)
    return out


class _BucketRing:
    """Per-``bucket_s`` (good, bad) counters covering ``horizon_s``.

    A slot is lazily reset when its epoch index changes, so no sweeper
    thread exists and an idle route costs nothing. Thread-safety is the
    caller's (SloEngine holds one lock across both rings)."""

    __slots__ = ("_bucket_s", "_n", "_good", "_bad", "_epoch", "_clock")

    def __init__(self, horizon_s: float, bucket_s: float, clock):
        self._bucket_s = float(bucket_s)
        # +1: the partially-filled current bucket rides alongside a
        # full horizon of closed ones
        self._n = int(horizon_s / bucket_s) + 1
        self._good = [0] * self._n
        self._bad = [0] * self._n
        self._epoch = [-1] * self._n
        self._clock = clock

    def record(self, ok: bool) -> None:
        idx = int(self._clock() / self._bucket_s)
        slot = idx % self._n
        if self._epoch[slot] != idx:
            self._epoch[slot] = idx
            self._good[slot] = 0
            self._bad[slot] = 0
        if ok:
            self._good[slot] += 1
        else:
            self._bad[slot] += 1

    def totals(self, window_s: float) -> tuple[int, int]:
        """(good, bad) over the trailing ``window_s``."""
        now_idx = int(self._clock() / self._bucket_s)
        lo = now_idx - int(window_s / self._bucket_s)
        good = bad = 0
        for slot in range(self._n):
            e = self._epoch[slot]
            if lo < e <= now_idx:
                good += self._good[slot]
                bad += self._bad[slot]
        return good, bad


class _RouteState:
    __slots__ = ("objective", "avail", "latency")

    def __init__(self, objective: SloObjective, horizon_s, bucket_s, clock):
        self.objective = objective
        self.avail = _BucketRing(horizon_s, bucket_s, clock)
        self.latency = _BucketRing(horizon_s, bucket_s, clock)


def _burn(bad: int, total: int, budget: float) -> float:
    if total <= 0:
        return 0.0
    return round((bad / total) / max(budget, 1e-9), 3)


class SloEngine:
    """Per-route multi-window burn-rate evaluation over request
    outcomes. ``record`` is called by the API layer once per request;
    ``snapshot`` renders the ``/slo`` document; ``register_metrics``
    exposes ``slo.burn_rate{route,window}`` (availability),
    ``slo.latency_burn_rate{route,window}`` and ``slo.breached{route}``
    gauges in the app registry. Breach *listeners*
    (:meth:`add_breach_listener`) get the current breached-route list
    at most once per ``NOTIFY_INTERVAL_S``, evaluated on the request
    path after recording — the brownout ladder (shaping.py) subscribes
    here, so degradation reacts to the same signal that pages."""

    #: min seconds between breach-listener evaluations: the breach set
    #: is O(routes x windows) to compute and must not run per request
    NOTIFY_INTERVAL_S = 1.0

    #: distinct tenants carrying their own burn rings before new ids
    #: share the overflow bucket (shaping's 64-tenant cap, reused)
    MAX_TENANTS = 64
    #: the shared bucket once MAX_TENANTS tenants are tracked
    OVERFLOW_TENANT = "overflow"
    #: tenant-scoped rings use coarser buckets than the global ones:
    #: 64 tenants x routes x 5s buckets would be real memory for a
    #: per-tenant VIEW, and 30s resolution is plenty for attribution
    TENANT_BUCKET_S = 30.0

    def __init__(
        self,
        *,
        default: SloObjective | None = None,
        routes: dict[str, SloObjective] | None = None,
        windows: tuple = WINDOWS,
        alert_burn_rate: float = 14.4,
        bucket_s: float = 5.0,
        max_tenants: int | None = None,
        clock=time.monotonic,
    ):
        self.default = default or SloObjective()
        self.overrides = dict(routes or {})
        self.windows = tuple(windows)
        self.alert_burn_rate = float(alert_burn_rate)
        self._bucket_s = float(bucket_s)
        self._horizon_s = max(s for _n, s in self.windows)
        self._clock = clock
        self._lock = threading.Lock()
        self._route_states: dict[str, _RouteState] = {}
        # tenant -> route -> _RouteState: the per-tenant SLO view
        # (/slo?tenant=...), recorded alongside the global rings so a
        # tenant's 5xx storm is attributable without moving any other
        # tenant's burn. Cardinality-bounded like shaping's classifier.
        self.max_tenants = int(
            max_tenants if max_tenants is not None else self.MAX_TENANTS
        )
        self._tenant_states: dict[str, dict[str, _RouteState]] = {}
        self._listeners: list = []
        self._last_notify = -math.inf
        # routes with declared overrides exist from the start, so /slo
        # shows the objective (at zero traffic) instead of nothing
        for route, obj in self.overrides.items():
            self._route_states[route] = _RouteState(
                obj, self._horizon_s, self._bucket_s, clock
            )

    @classmethod
    def from_config(
        cls, obs, *, max_tenants: int | None = None
    ) -> "SloEngine":
        """Build from an ObservabilityConfig (the ``BEACON_SLO_*``
        tier). ``max_tenants`` threads shaping's tenant cap through so
        every tenant-bounded plane (shaping, accounting, SLO views)
        collapses to overflow at the SAME count."""
        default = SloObjective(
            availability_target=getattr(
                obs, "slo_availability_target", 0.999
            ),
            latency_ms=getattr(obs, "slo_latency_ms", 250.0),
            latency_target=getattr(obs, "slo_latency_target", 0.99),
        )
        return cls(
            default=default,
            routes=parse_route_objectives(
                getattr(obs, "slo_routes", "") or "", default
            ),
            alert_burn_rate=getattr(obs, "slo_alert_burn_rate", 14.4),
            max_tenants=max_tenants,
        )

    @staticmethod
    def tracked(route: str) -> bool:
        return (
            route not in EXCLUDED_ROUTES
            and route.split(".", 1)[0] not in _EXCLUDED_HEADS
        )

    # -- the request-path entry ---------------------------------------------

    def record(
        self,
        route: str,
        status: int,
        elapsed_ms: float,
        tenant: str | None = None,
    ) -> None:
        """One request outcome. Availability: 5xx is bad. Latency: only
        non-5xx requests count (a failed request's latency is noise),
        bad when over the route's threshold. Route cardinality is
        bounded upstream by the API layer's route labeling; ``tenant``
        (when classified) additionally lands the outcome in that
        tenant's own rings — isolated, so one tenant's storm never
        moves another's view — bounded by ``max_tenants`` with
        overflow sharing one bucket."""
        if self.tracked(route):
            ok = status < 500
            good_latency = elapsed_ms  # compared per-objective below
            with self._lock:
                st = self._route_states.get(route)
                if st is None:
                    st = self._route_states[route] = _RouteState(
                        self.overrides.get(route, self.default),
                        self._horizon_s,
                        self._bucket_s,
                        self._clock,
                    )
                st.avail.record(ok)
                if ok:
                    st.latency.record(
                        good_latency <= st.objective.latency_ms
                    )
                if tenant:
                    by_route = self._tenant_states.get(tenant)
                    if by_route is None:
                        if (
                            len(self._tenant_states) >= self.max_tenants
                            and tenant != self.OVERFLOW_TENANT
                        ):
                            tenant = self.OVERFLOW_TENANT
                            by_route = self._tenant_states.get(tenant)
                        if by_route is None:
                            by_route = self._tenant_states[tenant] = {}
                    tst = by_route.get(route)
                    if tst is None:
                        tst = by_route[route] = _RouteState(
                            self.overrides.get(route, self.default),
                            self._horizon_s,
                            self.TENANT_BUCKET_S,
                            self._clock,
                        )
                    tst.avail.record(ok)
                    if ok:
                        tst.latency.record(
                            good_latency <= tst.objective.latency_ms
                        )
        # untracked routes still drive notification: health probes must
        # keep the brownout ladder's recovery clock ticking even when
        # shed 429s are the only tracked traffic
        self._maybe_notify()

    # -- breach listeners ----------------------------------------------------

    def add_breach_listener(self, fn) -> None:
        """``fn(breached_routes: list[str])`` called from the request
        path, rate-limited to one evaluation per ``NOTIFY_INTERVAL_S``.
        Listeners must be fast and must not raise (failures are logged
        and swallowed — degradation control must never fail requests)."""
        self._listeners.append(fn)

    def _maybe_notify(self) -> None:
        if not self._listeners:
            return
        with self._lock:
            now = self._clock()
            if now - self._last_notify < self.NOTIFY_INTERVAL_S:
                return
            self._last_notify = now
        breached = self.breached_routes()
        for fn in self._listeners:
            try:
                fn(breached)
            except Exception:  # pragma: no cover - defensive
                logging.getLogger(__name__).exception(
                    "SLO breach listener failed"
                )

    # -- evaluation ----------------------------------------------------------

    def _route_doc(self, route: str, st: _RouteState) -> dict:
        obj = st.objective
        doc: dict = {}
        breached_any = False
        for kind, ring, budget, extra in (
            (
                "availability",
                st.avail,
                1.0 - obj.availability_target,
                {"target": obj.availability_target},
            ),
            (
                "latency",
                st.latency,
                1.0 - obj.latency_target,
                {
                    "target": obj.latency_target,
                    "thresholdMs": obj.latency_ms,
                },
            ),
        ):
            windows = {}
            burning_all = True
            for wname, wsec in self.windows:
                good, bad = ring.totals(wsec)
                total = good + bad
                rate = _burn(bad, total, budget)
                windows[wname] = {
                    "good": good,
                    "bad": bad,
                    "total": total,
                    "badRatio": round(bad / total, 5) if total else 0.0,
                    "burnRate": rate,
                }
                if rate < self.alert_burn_rate:
                    burning_all = False
            breached = burning_all
            breached_any = breached_any or breached
            kdoc = {"windows": windows, "breached": breached}
            kdoc.update(extra)
            doc[kind] = kdoc
        doc["breached"] = breached_any
        return doc

    def snapshot(self, tenant: str | None = None) -> dict:
        """The ``/slo`` document: every tracked route's objectives,
        per-window good/bad/burn, and breach verdicts. With ``tenant``
        (the ``/slo?tenant=...`` view) the SAME document shape is
        rendered from that tenant's isolated rings — routes the tenant
        never touched are absent, and a ``tenant`` field names the
        scope (the overflow bucket, when the id overflowed the cap)."""
        # evaluated under the engine lock: _BucketRing's lazy-reset
        # slots are only coherent when reads exclude record()'s
        # stamp-then-zero mutation (a horizon-old bucket's counts must
        # never surface under a fresh epoch)
        with self._lock:
            if tenant is None:
                states = self._route_states
            else:
                if (
                    tenant not in self._tenant_states
                    and len(self._tenant_states) >= self.max_tenants
                ):
                    tenant = self.OVERFLOW_TENANT
                states = self._tenant_states.get(tenant, {})
            doc = {
                "alertBurnRate": self.alert_burn_rate,
                "windows": {n: s for n, s in self.windows},
                "routes": {
                    route: self._route_doc(route, st)
                    for route, st in sorted(states.items())
                },
            }
            if tenant is not None:
                doc["tenant"] = tenant
            return doc

    def tenants(self) -> list[str]:
        """Tenants with per-tenant burn rings (``/slo`` discovery)."""
        with self._lock:
            return sorted(self._tenant_states)

    def burn_rates(self, kind: str = "availability") -> dict:
        """{(route, window): burn rate} for the gauge callbacks."""
        out = {}
        with self._lock:
            for route, st in self._route_states.items():
                obj = st.objective
                if kind == "availability":
                    ring, budget = st.avail, 1.0 - obj.availability_target
                else:
                    ring, budget = st.latency, 1.0 - obj.latency_target
                for wname, wsec in self.windows:
                    good, bad = ring.totals(wsec)
                    out[(route, wname)] = _burn(bad, good + bad, budget)
        return out

    def breached(self) -> dict[str, int]:
        """{route: 0/1} — 1 when either objective burns above the
        alert factor on BOTH windows (the page condition)."""
        with self._lock:
            return {
                route: int(self._route_doc(route, st)["breached"])
                for route, st in self._route_states.items()
            }

    def breached_routes(self) -> list[str]:
        return sorted(r for r, b in self.breached().items() if b)

    def register_metrics(self, registry) -> None:
        registry.gauge(
            "slo.burn_rate",
            "availability error-budget burn rate per route and window",
            label=("route", "window"),
            fn=lambda: self.burn_rates("availability"),
        )
        registry.gauge(
            "slo.latency_burn_rate",
            "latency error-budget burn rate per route and window",
            label=("route", "window"),
            fn=lambda: self.burn_rates("latency"),
        )
        registry.gauge(
            "slo.breached",
            "1 when a route burns over the alert factor on both windows",
            label="route",
            fn=self.breached,
        )
