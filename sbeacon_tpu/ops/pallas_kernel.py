"""Pallas TPU kernel for the variant-query hot op.

The XLA kernel (``ops/kernel.py``) answers each query by a fixed-depth
bisection followed by a **gather** of ``window_cap`` rows per column —
XLA lowers that arbitrary-index gather row-by-row. The candidate window
is *contiguous* in the sorted index, so this module exploits it with
Pallas: the index columns are stacked into one int32 matrix ``[16, L]``
(rows = columns of the columnar index, lanes = variant rows).

Bandwidth design (round-2 rework): the round-1 kernel DMA'd a private
2W-wide tile pair per query — ~256 KB of HBM traffic for point queries
whose real windows are a handful of rows (single-digit % of HBM peak,
VERDICT r1 weak #1). Now queries are **sorted by window start and packed
G per grid step**: each step DMAs ONE tile pair shared by all G queries
(amortising both the copy and the per-step pipeline overhead G-fold) and
evaluates the full predicate stack for the whole group as ``[G, 2W]``
VPU mask algebra. Window bounds come from a vectorised host-side
searchsorted over the resident column (the tunnel-hostile device bisect
pass is gone entirely). Groups are packed greedily: a query joins the
current group only if its capped window fits the group's tile span, so
results are never silently truncated — a query that cannot fit reports
overflow and takes the uncapped host path, exactly like the XLA kernel.

Record granularity runs in-kernel too (VERDICT r1 weak #2): the ``[G,
2W]`` match mask is bit-packed on the MXU (one f32 dot against a
constant 16-bits-per-word packing matrix — all values are exact powers
of two, so bf16 multiply + f32 accumulate is lossless) into ~2W/16
words per query; the host unpacks matched row ids with one vectorised
``np.unpackbits`` per batch. Output per query: 8 aggregate words + the
packed mask — ~300 B instead of a row-id gather kernel dispatch.

Semantics are identical to ``ops/kernel._query_one`` (itself the exact
spec of the reference's matcher, performQuery/search_variants.py:84-254):
the same predicates, the same '<None' variant-type artifact, and the same
"AN once per matching record" rule — here computed with a segmented
first-match scan built from log-shift cumsum/cummax over the lane axis.
"""

from __future__ import annotations

import time as _time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index.columnar import INT32_MAX, FLAG, VariantIndexShard
from .kernel import _PAD_FILLS, bisect_iters, encode_queries

try:  # pallas import kept lazy-safe: CPU-only builds may lack TPU deps
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

# stacked-matrix row ids (lane axis = index rows, sublane axis = columns)
ROW_POS = 0
ROW_REC_END = 1
ROW_REF_LEN = 2
ROW_ALT_LEN = 3
ROW_REF_HASH = 4
ROW_ALT_HASH = 5
ROW_K = 6
ROW_FLAGS = 7
ROW_AC = 8
ROW_AN = 9
ROW_REC_ID = 10
ROW_AP = 11  # 11..14: alt_prefix words 0..3
N_ROWS = 16  # padded to an int32-friendly sublane count

_ROW_SOURCES = [
    ("pos", ROW_POS),
    ("rec_end", ROW_REC_END),
    ("ref_len", ROW_REF_LEN),
    ("alt_len", ROW_ALT_LEN),
    ("ref_hash", ROW_REF_HASH),
    ("alt_hash", ROW_ALT_HASH),
    ("ref_repeat_k", ROW_K),
    ("flags", ROW_FLAGS),
    ("ac", ROW_AC),
    ("an", ROW_AN),
    ("rec_id", ROW_REC_ID),
]

# query scalar-array field ids (all int32; prefix words bit-cast) —
# legacy 24-word encoding kept for pack_encoded API compatibility
(
    F_CHROM,
    F_START_MIN,
    F_START_MAX,
    F_END_MIN,
    F_END_MAX,
    F_REF_WILD,
    F_REF_HASH,
    F_REF_LEN,
    F_ALT_MODE,
    F_ALT_HASH,
    F_ALT_LEN,
    F_VT_CODE,
    F_VP0,
    F_VP1,
    F_VP2,
    F_VP3,
    F_VM0,
    F_VM1,
    F_VM2,
    F_VM3,
    F_MIN_LEN,
    F_MAX_LEN,
    F_LO,
    F_HI,
) = range(24)
N_FIELDS = 24

# compact 8-word per-query upload, symbolic-prefix staging, window
# bounds, and mask unpacking now live in ops.query_pack (kernel-neutral
# — VERDICT r3 weak #8); re-imported here because this module's legacy
# call sites and tests use the historical names.
from .query_pack import (  # noqa: E402
    N_QWORDS,
    PM_CNV,
    PM_DUPT,
    PM_INS,
    Q_ALT_HASH,
    Q_END_MAX,
    Q_END_MIN,
    Q_HI,
    Q_LENS,
    Q_LO,
    Q_META,
    Q_REF_HASH,
    _rows_from_masks,
    _window_bounds,
    pack_q8,
    stage_symbolic_flags,
)

# alt matching modes / variant-type codes (mirror ops.kernel)
from .kernel import (  # noqa: E402
    MODE_ANY_BASE,
    MODE_EXACT,
    VT_CNV,
    VT_DEL,
    VT_DUP,
    VT_DUP_TANDEM,
    VT_INS,
)


class PallasDeviceIndex:
    """One shard's columns stacked as an int32 ``[16, L]`` device matrix.

    L is a multiple of the tile width W with two tiles of tail padding so
    any window start block and its successor are always in range; padding
    lanes carry pos=INT32_MAX / rec_id=INT32_MAX so they never match.

    Host copies of ``pos`` and ``chrom_offsets`` stay on the object: the
    per-query window bounds are a host-side vectorised searchsorted (the
    round-1 device bisect pass is gone), and group planning needs them.
    """

    def __init__(self, shard: VariantIndexShard, window: int = 512):
        if window % 128:
            raise ValueError("window must be a multiple of 128 lanes")
        self.window = window
        n = shard.n_rows
        L = (n // window + 2) * window
        mat = np.empty((N_ROWS, L), dtype=np.int32)
        for name, row in _ROW_SOURCES:
            mat[row, :n] = shard.cols[name]
            mat[row, n:] = _PAD_FILLS[name]
        ap = shard.cols["alt_prefix"].view(np.int32)  # [n, 4]
        mat[ROW_AP : ROW_AP + 4, :n] = ap.T
        mat[ROW_AP : ROW_AP + 4, n:] = 0
        mat[ROW_AP + 4 :, :] = 0
        # stage the symbolic-prefix bits the grouped kernel needs (the
        # shard's persisted flags are untouched — these live only in the
        # device matrix), via the staging helper shared with the
        # scattered kernel
        mat[ROW_FLAGS, :n] = stage_symbolic_flags(
            mat[ROW_FLAGS, :n], shard.cols["alt_prefix"]
        ).astype(np.int32)
        self.shard = shard
        self.n_rows = n
        self.n_lanes = L
        self.mat = jnp.asarray(mat)
        self.pos_host = shard.cols["pos"]
        self.offsets_host = shard.chrom_offsets.astype(np.int64)
        # constant packing matrix: lane l contributes 2^(l%16) to word
        # l//16 — every entry an exact power of two, so the in-kernel
        # dot packs the match mask losslessly; stored bf16 (powers of two
        # up to 2^15 are exact) to halve its VMEM block at large W
        nw = (2 * window) // 16
        pw = np.zeros((2 * window, nw), dtype=np.float32)
        lanes = np.arange(2 * window)
        pw[lanes, lanes // 16] = (1 << (lanes % 16)).astype(np.float32)
        self.pack_mat = jnp.asarray(pw, dtype=jnp.bfloat16)
        self.n_words = nw
        self.n_iters = bisect_iters(L)  # legacy (XLA-kernel comparisons)
        # max rows per record (= max alt arity): lets the kernel replace
        # the log-depth segmented first-match scan with max_arity-1
        # neighbour shifts — the scan was ~half the per-query VPU work,
        # and real cohorts rarely exceed a handful of alts per record
        rec = shard.cols["rec_id"][:n]
        if n:
            bounds = np.flatnonzero(np.diff(rec) != 0)
            edges = np.concatenate([[-1], bounds, [n - 1]])
            self.max_arity = int(np.diff(edges).max())
        else:
            self.max_arity = 1


def _shift_right(x, k: int, fill):
    """Lane-axis right shift by static k with constant fill.

    Mosaic cannot lower a shifted concatenate (offset mismatch on the
    non-concat dimension), so this is a circular ``pltpu.roll`` with the
    wrapped lanes masked to ``fill``; interpret mode falls back to
    ``jnp.roll`` (same semantics) so the kernel stays CPU-testable.
    """
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    try:
        rolled = pltpu.roll(x, shift=k, axis=1)
    except Exception:
        rolled = jnp.roll(x, k, axis=1)
    return jnp.where(lane < k, fill, rolled)


def _cum(x, op, fill):
    """Inclusive scan along lanes via log-depth shifted combines."""
    n = x.shape[1]
    k = 1
    while k < n:
        x = op(x, _shift_right(x, k, fill))
        k *= 2
    return x


def _pallas_kernel(
    starts_ref,
    qarr_ref,
    t0_ref,
    t1_ref,
    pw_ref,
    out_ref,
    mask_ref,
    *,
    W,
    CAP,
    DUP_SHIFTS=-1,
):
    """One grid step = one shared tile pair × G packed queries.

    ``qarr_ref`` is this group's ``[G, N_FIELDS]`` query block (VMEM);
    every predicate evaluates as ``[G, 2W]`` mask algebra — per-query
    scalars enter as ``[G, 1]`` columns broadcast against ``[1, 2W]``
    window rows.
    """
    i = pl.program_id(0)
    q = lambda fld: qarr_ref[:, fld : fld + 1]  # [G, 1]

    win = jnp.concatenate([t0_ref[:, :], t1_ref[:, :]], axis=1)  # [16, 2W]
    row = lambda r: win[r : r + 1, :]  # [1, 2W]

    base = starts_ref[i] * W
    lo = q(Q_LO)
    hi = q(Q_HI)
    gidx = base + jax.lax.broadcasted_iota(jnp.int32, (1, 2 * W), 1)

    # bit-packed per-query fields (arithmetic >> then mask is exact for
    # the field widths chosen by pack_q8)
    meta = q(Q_META)
    ref_wild = meta & 1
    mode = (meta >> 1) & 3
    vt = (meta >> 3) & 7
    ref_len_q = (meta >> 6) & 0x1FFF
    min_len_q = (meta >> 19) & 0x1FFF
    lens = q(Q_LENS)
    alt_len_q = lens & 0xFFFF
    # 0xFFFF is the unbounded sentinel: row alt_len is an unclamped int32
    # (a 70 kb insertion is a legal row), so an unbounded query must not
    # inherit a 16-bit ceiling; finite bounds above 0xFFFE are
    # host-flagged by pack_q8
    max_len_q = (lens >> 16) & 0xFFFF
    max_len_q = jnp.where(
        max_len_q == 0xFFFF, jnp.int32(INT32_MAX), max_len_q
    )

    # Mosaic dislikes selects over 1-bit vectors, so the whole predicate
    # stack is int32 0/1 mask algebra; booleans appear only as compare
    # results immediately widened via jnp.where(cond, 1, 0).
    b2i = lambda cond: jnp.where(cond, jnp.int32(1), jnp.int32(0))
    valid = b2i(gidx >= lo) & b2i(gidx < jnp.minimum(hi, lo + CAP))

    rec_end = row(ROW_REC_END)
    end_ok = b2i(q(Q_END_MIN) <= rec_end) & b2i(rec_end <= q(Q_END_MAX))

    ref_ok = b2i(ref_wild != 0) | (
        b2i(row(ROW_REF_HASH) == q(Q_REF_HASH))
        & b2i(row(ROW_REF_LEN) == ref_len_q)
    )

    alt_len = row(ROW_ALT_LEN)
    len_ok = b2i(min_len_q <= alt_len) & b2i(alt_len <= max_len_q)

    flags = row(ROW_FLAGS)
    f = lambda bit: b2i((flags & bit) != 0)
    sym = f(FLAG.SYMBOLIC)
    nsym = 1 - sym
    k = row(ROW_K)
    ref_len = row(ROW_REF_LEN)

    # symbolic-prefix matches come from index-side flag bits (PM_* staged
    # by PallasDeviceIndex; '<DEL'/'<DUP' reuse the shard's own bits).
    # VT_OTHER (arbitrary/absent variant_type) is host-resolved — pack_q8
    # flags those queries for the uncapped host path, so other_ok is 0.
    del_ok = (sym & (f(FLAG.DEL_PREFIX) | f(FLAG.CN0))) | (
        nsym & b2i(alt_len < ref_len)
    )
    ins_ok = (sym & f(PM_INS)) | (nsym & b2i(alt_len > ref_len))
    dup_ok = (
        sym
        & (
            f(FLAG.DUP_PREFIX)
            | (f(FLAG.CN_PREFIX) & (1 - f(FLAG.CN0)) & (1 - f(FLAG.CN1)))
        )
    ) | (nsym & b2i(k >= 2))
    dupt_ok = (sym & (f(PM_DUPT) | f(FLAG.CN2))) | (nsym & b2i(k == 2))
    cnv_ok = (
        sym
        & (f(PM_CNV) | f(FLAG.CN_PREFIX) | f(FLAG.DEL_PREFIX) | f(FLAG.DUP_PREFIX))
    ) | (nsym & (f(FLAG.DOT) | b2i(k >= 1)))
    other_ok = jnp.zeros_like(valid)
    type_ok = jnp.where(
        vt == VT_DEL,
        del_ok,
        jnp.where(
            vt == VT_INS,
            ins_ok,
            jnp.where(
                vt == VT_DUP,
                dup_ok,
                jnp.where(
                    vt == VT_DUP_TANDEM,
                    dupt_ok,
                    jnp.where(vt == VT_CNV, cnv_ok, other_ok),
                ),
            ),
        ),
    )
    exact_ok = b2i(row(ROW_ALT_HASH) == q(Q_ALT_HASH)) & b2i(
        alt_len == alt_len_q
    )
    anyb_ok = f(FLAG.SINGLE_BASE)
    alt_ok = jnp.where(
        mode == MODE_EXACT,
        exact_ok,
        jnp.where(mode == MODE_ANY_BASE, anyb_ok, type_ok),
    )

    m_i = valid & end_ok & ref_ok & len_ok & alt_ok  # int32 0/1 [G, 2W]

    ac = row(ROW_AC)
    call_count = jnp.sum(m_i * ac, axis=1, keepdims=True)  # [G, 1]
    n_variants = jnp.sum(m_i & b2i(ac != 0), axis=1, keepdims=True)
    n_matched = jnp.sum(m_i, axis=1, keepdims=True)

    # AN once per record with >= 1 matched row. Records are contiguous
    # lane runs of equal rec_id, so when the index's max alt arity is
    # small (DUP_SHIFTS = max_arity-1 >= 0) a matched lane is the
    # record's first match iff none of its DUP_SHIFTS left neighbours
    # matched with the same rec_id — a handful of shifts instead of the
    # general log-depth segmented scan (which remains the fallback for
    # pathological arity). Lanes left of the query window have m=0, so
    # partially-visible records still count AN exactly once.
    if DUP_SHIFTS == 0:
        first_match = m_i
    elif 0 < DUP_SHIFTS <= _MAX_DUP_SHIFTS:
        rec_raw = row(ROW_REC_ID)
        dup = jnp.zeros_like(m_i)
        for kk in range(1, DUP_SHIFTS + 1):
            # shift the [1, 2W] row, not a [G, 2W] broadcast: the
            # same-record compare broadcasts against prev_m afterwards
            prev_rec = _shift_right(rec_raw, kk, jnp.int32(-1))
            prev_m = _shift_right(m_i, kk, jnp.int32(0))
            dup = dup | (b2i(prev_rec == rec_raw) & prev_m)
        first_match = m_i & (1 - dup)
    else:
        # segmented first-match via cumsum (matched before lane) +
        # cummax (matched-before at segment start)
        rec = jnp.where(valid != 0, row(ROW_REC_ID), INT32_MAX)
        seg_begin = b2i(rec != _shift_right(rec, 1, jnp.int32(-1)))
        cs = _cum(m_i, jnp.add, jnp.int32(0))
        before = cs - m_i
        seg_base = _cum(
            jnp.where(seg_begin != 0, before, jnp.int32(-1)),
            jnp.maximum,
            jnp.int32(-1),
        )
        first_match = m_i & b2i(before == seg_base)
    all_alleles = jnp.sum(
        first_match * row(ROW_AN), axis=1, keepdims=True
    )

    overflow = b2i((hi - lo) > CAP)  # [G, 1]

    zero = jnp.zeros_like(overflow)
    out_ref[:, :] = jnp.concatenate(
        [
            b2i(call_count > 0),
            call_count,
            n_variants,
            all_alleles,
            n_matched,
            overflow,
            zero,
            zero,
        ],
        axis=1,
    )
    # matched-row bit mask, 16 lanes per output word, packed on the MXU:
    # mask and weights are exact powers of two, so bf16 multiply with f32
    # accumulate is lossless (sums < 2^16 per word)
    packed = jnp.dot(
        m_i.astype(jnp.bfloat16),
        pw_ref[:, :],
        preferred_element_type=jnp.float32,
    )
    mask_ref[:, :] = packed.astype(jnp.int32)


def pack_encoded(enc: dict[str, np.ndarray]) -> np.ndarray:
    """Host-side: one int32 ``[B, 22]`` array holding every query field —
    a single H2D transfer instead of 22 (the device may sit behind a
    network tunnel where each transfer costs milliseconds)."""
    b = len(enc["chrom"])
    packed = np.empty((b, N_FIELDS - 2), dtype=np.int32)
    packed[:, F_CHROM] = enc["chrom"]
    packed[:, F_START_MIN] = enc["start_min"]
    packed[:, F_START_MAX] = enc["start_max"]
    packed[:, F_END_MIN] = enc["end_min"]
    packed[:, F_END_MAX] = enc["end_max"]
    packed[:, F_REF_WILD] = enc["ref_wild"]
    packed[:, F_REF_HASH] = enc["ref_hash"]
    packed[:, F_REF_LEN] = enc["ref_len"]
    packed[:, F_ALT_MODE] = enc["alt_mode"]
    packed[:, F_ALT_HASH] = enc["alt_hash"]
    packed[:, F_ALT_LEN] = enc["alt_len"]
    packed[:, F_VT_CODE] = enc["vt_code"]
    packed[:, F_VP0 : F_VP0 + 4] = enc["vprefix"].view(np.int32)
    packed[:, F_VM0 : F_VM0 + 4] = enc["vprefix_mask"].view(np.int32)
    packed[:, F_MIN_LEN] = enc["min_len"]
    packed[:, F_MAX_LEN] = enc["max_len"]
    return packed


# group geometry: G queries share one tile pair per grid step; a
# pallas_call covers a fixed number of query slots so distinct batch
# sizes reuse compiled programs (CHUNK_SMALL for serving-latency
# batches, CHUNK for throughput batches; larger batches lax.map chunks).
# G amortises the fixed per-step cost (pipeline + scalar-prefetch
# control) across the group. Measured on v5e with serialized-chain
# differencing (bench point-query mix, W=512): G=16 -> 0.38 ms/10k
# batch, G=32 -> 0.29, G=64 -> 0.25 (~40M q/s), G=128 -> 0.26 — G=64
# is the knee where per-step overhead is amortised but the [G, 2W]
# VPU mask algebra hasn't yet grown past it.
G = 64
CHUNK = 1024
CHUNK_SMALL = 64

# beyond this many neighbour shifts the log-depth segmented scan is
# cheaper (10 combines at 2W=1024 lanes); also bounds the number of
# compiled kernel variants across shards of different alt arity
_MAX_DUP_SHIFTS = 6


def _dup_shifts(pindex: PallasDeviceIndex) -> int:
    ds = pindex.max_arity - 1
    return ds if ds <= _MAX_DUP_SHIFTS else -1


def _plan_groups(
    lo: np.ndarray, hi: np.ndarray, *, W: int, cap: int, g: int = G
) -> tuple[np.ndarray, np.ndarray]:
    """Greedy pack of start-sorted queries into tile-sharing groups.

    Returns (slots, starts): ``slots[k]`` is the original query index in
    group ``k // g`` (groups padded by repeating their last query),
    ``starts[k//g]`` the group's base tile. A query joins a group only if
    its cap-clamped window fits the group's 2W tile span; since
    ``cap <= W``, any query fits a fresh group, so no result is ever
    silently truncated — oversize windows report overflow instead.
    """
    order = np.argsort(lo, kind="stable")

    # vectorized fast path: fixed G-sized groups in sorted order. Valid
    # whenever every query's capped window fits its group's tile span —
    # true for dense batches (the serving hot path), where the Python
    # greedy loop below would otherwise be ~10 ms of GIL-bound host work
    # per 10k-query batch, throttling pipelined throughput.
    b = len(order)
    pad = (-b) % g
    slots_v = np.concatenate([order, np.repeat(order[-1:], pad)])
    ng = len(slots_v) // g
    lo_s = lo[slots_v].reshape(ng, g)
    need_end = np.minimum(hi, lo + cap)[slots_v].reshape(ng, g)
    t0 = lo_s[:, 0] // W
    if (need_end <= ((t0 + 2) * W)[:, None]).all():
        return slots_v.astype(np.int64), t0.astype(np.int32)

    # sparse/straggler batches: exact greedy packing (splits a group as
    # soon as the next query cannot share its tile span)
    slots: list[int] = []
    starts: list[int] = []
    cur: list[int] = []
    cur_t0 = 0

    def close():
        if cur:
            while len(cur) < g:
                cur.append(cur[-1])
            slots.extend(cur)
            starts.append(cur_t0)
            cur.clear()

    for qi in order:
        qi = int(qi)
        need = min(int(hi[qi]), int(lo[qi]) + cap)
        if cur and (len(cur) == g or need > (cur_t0 + 2) * W):
            close()
        if not cur:
            cur_t0 = int(lo[qi]) // W
        cur.append(qi)
    close()
    return np.asarray(slots, np.int64), np.asarray(starts, np.int32)


@partial(
    jax.jit,
    static_argnames=("W", "CAP", "g", "nslots", "interpret", "dup_shifts"),
)
def _grouped_batch(
    mat, pack_mat, starts, qarr, *, W, CAP, g, nslots, interpret, dup_shifts=-1
):
    """lax.map over fixed-size chunks: one compiled program per
    (W, CAP, nslots, chunk-count) regardless of logical batch size."""
    nw = pack_mat.shape[1]
    per_call = nslots // g
    nc = starts.shape[0] // per_call

    def run_chunk(args):
        starts_c, qarr_c = args
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(per_call,),
            in_specs=[
                pl.BlockSpec((g, N_QWORDS), lambda i, s: (i, 0)),
                pl.BlockSpec((N_ROWS, W), lambda i, s: (0, s[i])),
                pl.BlockSpec((N_ROWS, W), lambda i, s: (0, s[i] + 1)),
                pl.BlockSpec((2 * W, nw), lambda i, s: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((g, 8), lambda i, s: (i, 0)),
                pl.BlockSpec((g, nw), lambda i, s: (i, 0)),
            ],
        )
        return pl.pallas_call(
            partial(_pallas_kernel, W=W, CAP=CAP, DUP_SHIFTS=dup_shifts),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((nslots, 8), jnp.int32),
                jax.ShapeDtypeStruct((nslots, nw), jnp.int32),
            ],
            interpret=interpret,
        )(starts_c, qarr_c, mat, mat, pack_mat)

    agg, masks = jax.lax.map(
        run_chunk,
        (
            starts.reshape(nc, per_call),
            qarr.reshape(nc, nslots, N_QWORDS),
        ),
    )
    return agg.reshape(nc * nslots, 8), masks.reshape(nc * nslots, -1)


def _prepare_slots(
    pindex: PallasDeviceIndex, enc: dict, cap: int, g: int = G
):
    """Plan + pad one batch: (starts, qslot, slots, lo, hi, needs_host,
    nslots). Shared by the serving runner and the bench device probe."""
    w = pindex.window
    lo, hi = _window_bounds(pindex, enc)
    slots, starts = _plan_groups(lo, hi, W=w, cap=cap, g=g)
    nslots = CHUNK_SMALL if len(slots) <= CHUNK_SMALL else CHUNK
    nslots = -(-max(nslots, g) // g) * g  # round up to a multiple of g
    pad_groups = (-len(starts)) % (nslots // g)
    if pad_groups:
        starts = np.concatenate([starts, np.zeros(pad_groups, np.int32)])
        slots = np.concatenate(
            [slots, np.full(pad_groups * g, -1, np.int64)]
        )
    q8, needs_host = pack_q8(enc, lo, hi)
    qslot = np.zeros((len(slots), N_QWORDS), np.int32)
    real = slots >= 0  # dummy slots keep lo=hi=0: no lane is ever valid
    qslot[real] = q8[slots[real]]
    return starts, qslot, slots, lo, hi, needs_host, nslots


def device_time_probe(
    pindex: PallasDeviceIndex,
    queries,
    *,
    window_cap: int | None = None,
    iters: int = 128,
    interpret: bool | None = None,
    group: int = G,
) -> tuple[float, int]:
    """(seconds per batch on-device, HBM bytes scanned per batch).

    Runs serialized kernel executions inside ONE dispatch (a lax.scan
    whose carry feeds each iteration's scalar-prefetch array from the
    previous iteration's output — the added word is always 0 but
    data-dependent, so XLA cannot hoist or overlap the iterations), at
    two chain lengths k1 and k1+``iters``, each timed dispatch-to-
    ``device_get``. The difference of the two timings divided by
    ``iters`` is pure on-device time: the RTT, host dispatch cost, and
    result transfer are identical in both and cancel. (Differencing
    matters doubly behind the tunnel: this backend's
    ``block_until_ready`` returns before execution finishes, so only a
    ``device_get`` observes real completion — VERDICT r1 weak #3.)
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    enc = encode_queries(queries) if isinstance(queries, list) else queries
    w = pindex.window
    cap = min(window_cap or w, w)
    starts, qslot, slots, _lo, _hi, _nh, nslots = _prepare_slots(
        pindex, enc, cap, group
    )
    sd = jnp.asarray(starts)
    qd = jnp.asarray(qslot)
    args = dict(
        W=w,
        CAP=cap,
        g=group,
        nslots=nslots,
        interpret=interpret,
        dup_shifts=_dup_shifts(pindex),
    )
    k1 = 8
    k2 = k1 + iters

    def timed(k, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            np.asarray(
                jax.device_get(
                    _probe_rep(pindex.mat, pindex.pack_mat, sd, qd, k=k, **args)
                )
            )
            best = min(best, _time.perf_counter() - t0)
        return best

    timed(k1, reps=1)  # compile + transfer-path warm-up, per program
    timed(k2, reps=1)
    delta = timed(k2) - timed(k1)
    if delta <= 0:
        # RTT jitter swamped the chain-length signal: refuse to report a
        # garbage rate (callers treat the probe as optional and catch)
        raise RuntimeError(
            f"device_time_probe: unmeasurable — {iters}-batch signal "
            f"below timing jitter ({delta * 1e3:.3f} ms); raise iters"
        )
    per = delta / iters
    scanned = len(starts) * (2 * w) * N_ROWS * 4
    return per, scanned


@partial(
    jax.jit,
    static_argnames=("W", "CAP", "g", "nslots", "interpret", "k", "dup_shifts"),
)
def _probe_rep(
    mat,
    pack_mat,
    starts_d,
    qarr,
    *,
    W,
    CAP,
    g,
    nslots,
    interpret,
    k,
    dup_shifts=-1,
):
    """Module-level (shared jit cache): k serialized kernel executions —
    the carry feeds each iteration's prefetch array from the previous
    output (always +0, but data-dependent, so XLA cannot hoist)."""

    def body(carry, _):
        agg, _masks = _grouped_batch(
            mat,
            pack_mat,
            carry,
            qarr,
            W=W,
            CAP=CAP,
            g=g,
            nslots=nslots,
            interpret=interpret,
            dup_shifts=dup_shifts,
        )
        return carry + agg[0, 6], agg[0, 1]  # agg[:,6] is always 0

    _, outs = jax.lax.scan(body, starts_d, None, length=k)
    # scalar result: both probe chain lengths must transfer IDENTICAL
    # bytes or the difference no longer cancels the transfer cost; the
    # sum still depends on every iteration so none can be elided.
    # NOTE: the scalar is timing ballast only — at large k the int32 sum
    # of call_counts may wrap (int64 is unavailable without x64 mode);
    # never assert on its value.
    return jnp.sum(outs)


def run_queries_grouped(
    pindex: PallasDeviceIndex,
    queries,
    *,
    window_cap: int | None = None,
    record_cap: int = 1024,
    with_rows: bool = True,
    interpret: bool | None = None,
    group: int = G,
):
    """Execute a query batch via the grouped Pallas window-scan kernel.

    Returns ``ops.kernel.QueryResults`` (aggregates + matched row ids),
    the same contract as the XLA ``run_queries`` — the serving engine and
    micro-batcher dispatch on index type. ``interpret`` defaults to True
    off-TPU so the same kernel is testable on the CPU mesh; on TPU it
    compiles through Mosaic. The effective window cap is
    ``min(window_cap, W)``; wider candidate ranges report overflow and
    take the engine's uncapped host path (same contract as the XLA
    kernel, just a tighter cap).
    """
    from .kernel import QueryResults

    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable in this jax build")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    enc = encode_queries(queries) if isinstance(queries, list) else queries
    w = pindex.window
    cap = min(window_cap or w, w)
    b = len(enc["chrom"])
    if b == 0:
        z = np.zeros(0, np.int32)
        return QueryResults(
            exists=np.zeros(0, bool),
            call_count=z,
            n_variants=z,
            all_alleles_count=z,
            n_matched=z,
            overflow=np.zeros(0, bool),
            rows=np.zeros((0, record_cap), np.int32),
        )

    starts, qslot, slots, lo, hi, needs_host, nslots = _prepare_slots(
        pindex, enc, cap, group
    )
    real = slots >= 0

    agg, masks = _grouped_batch(
        pindex.mat,
        pindex.pack_mat,
        jnp.asarray(starts),
        jnp.asarray(qslot),
        W=w,
        CAP=cap,
        g=group,
        nslots=nslots,
        interpret=interpret,
        dup_shifts=_dup_shifts(pindex),
    )
    if with_rows:
        # one fetch for both outputs: through a tunnel every device_get
        # costs a full round trip, so agg and masks must not sync twice
        agg, masks = jax.device_get((agg, masks))
        agg = np.asarray(agg)
    else:
        # aggregate-only traffic never fetches the packed masks (the
        # largest transfer by far stays on device)
        agg = np.asarray(jax.device_get(agg))

    # first slot per original query (padding repeats map to the same qi)
    first_slot = np.full(b, -1, np.int64)
    slot_idx = np.nonzero(real)[0]
    first_slot[slots[slot_idx[::-1]]] = slot_idx[::-1]
    a = agg[first_slot]
    overflow = (a[:, 5] > 0) | ((hi - lo) > cap) | needs_host
    if with_rows:
        base_rows = starts[(first_slot // group)].astype(np.int64) * w
        rows = _rows_from_masks(
            np.asarray(masks)[first_slot], base_rows, record_cap
        )
    else:
        rows = np.zeros((b, 0), np.int32)
    return QueryResults(
        exists=a[:, 0] > 0,
        call_count=a[:, 1],
        n_variants=a[:, 2],
        all_alleles_count=a[:, 3],
        n_matched=a[:, 4],
        overflow=overflow,
        rows=rows,
    )


def run_queries_pallas(
    pindex: PallasDeviceIndex,
    queries,
    *,
    interpret: bool | None = None,
) -> dict[str, np.ndarray]:
    """Aggregate-only dict view of the grouped kernel (bench/test API)."""
    res = run_queries_grouped(
        pindex, queries, with_rows=False, interpret=interpret
    )
    return {
        "exists": np.asarray(res.exists),
        "call_count": np.asarray(res.call_count),
        "n_variants": np.asarray(res.n_variants),
        "all_alleles_count": np.asarray(res.all_alleles_count),
        "n_matched": np.asarray(res.n_matched),
        "overflow": np.asarray(res.overflow),
    }
