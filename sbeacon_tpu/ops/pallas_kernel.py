"""Pallas TPU kernel for the variant-query hot op.

The XLA kernel (``ops/kernel.py``) answers each query by a fixed-depth
bisection followed by a **gather** of ``window_cap`` rows per column —
XLA lowers that arbitrary-index gather row-by-row. But the candidate
window is *contiguous* in the sorted index, so this module exploits it
with Pallas: the index columns are stacked into one int32 matrix
``[16, L]`` (rows = columns of the columnar index, lanes = variant rows)
and each grid step DMAs the two W-wide tiles covering its query's window
HBM→VMEM via scalar-prefetched block index maps — a streaming sequential
copy, double-buffered across the query grid by the Pallas pipeline — then
evaluates the full predicate stack on the VPU and reduces to the Beacon
aggregates (exists / call_count / n_variants / all_alleles_count).

Scope: aggregate results only (boolean/count granularity — the bulk of
Beacon traffic). Record-granularity materialisation (matched row ids)
stays on the XLA kernel, which already returns order-preserving row ids.

Semantics are identical to ``ops/kernel._query_one`` (itself the exact
spec of the reference's matcher, performQuery/search_variants.py:84-254):
the same predicates, the same '<None' variant-type artifact, and the same
"AN once per matching record" rule — here computed with a segmented
first-match scan built from log-shift cumsum/cummax over the lane axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index.columnar import INT32_MAX, FLAG, VariantIndexShard
from .kernel import _PAD_FILLS, _bisect, bisect_iters, encode_queries

try:  # pallas import kept lazy-safe: CPU-only builds may lack TPU deps
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

# stacked-matrix row ids (lane axis = index rows, sublane axis = columns)
ROW_POS = 0
ROW_REC_END = 1
ROW_REF_LEN = 2
ROW_ALT_LEN = 3
ROW_REF_HASH = 4
ROW_ALT_HASH = 5
ROW_K = 6
ROW_FLAGS = 7
ROW_AC = 8
ROW_AN = 9
ROW_REC_ID = 10
ROW_AP = 11  # 11..14: alt_prefix words 0..3
N_ROWS = 16  # padded to an int32-friendly sublane count

_ROW_SOURCES = [
    ("pos", ROW_POS),
    ("rec_end", ROW_REC_END),
    ("ref_len", ROW_REF_LEN),
    ("alt_len", ROW_ALT_LEN),
    ("ref_hash", ROW_REF_HASH),
    ("alt_hash", ROW_ALT_HASH),
    ("ref_repeat_k", ROW_K),
    ("flags", ROW_FLAGS),
    ("ac", ROW_AC),
    ("an", ROW_AN),
    ("rec_id", ROW_REC_ID),
]

# query scalar-array field ids (all int32; prefix words bit-cast)
(
    F_CHROM,
    F_START_MIN,
    F_START_MAX,
    F_END_MIN,
    F_END_MAX,
    F_REF_WILD,
    F_REF_HASH,
    F_REF_LEN,
    F_ALT_MODE,
    F_ALT_HASH,
    F_ALT_LEN,
    F_VT_CODE,
    F_VP0,
    F_VP1,
    F_VP2,
    F_VP3,
    F_VM0,
    F_VM1,
    F_VM2,
    F_VM3,
    F_MIN_LEN,
    F_MAX_LEN,
    F_LO,
    F_HI,
) = range(24)
N_FIELDS = 24

# alt matching modes / variant-type codes (mirror ops.kernel)
from .kernel import (  # noqa: E402
    MODE_ANY_BASE,
    MODE_EXACT,
    VT_CNV,
    VT_DEL,
    VT_DUP,
    VT_DUP_TANDEM,
    VT_INS,
)


class PallasDeviceIndex:
    """One shard's columns stacked as an int32 ``[16, L]`` device matrix.

    L is a multiple of the tile width W with two tiles of tail padding so
    any window start block and its successor are always in range; padding
    lanes carry pos=INT32_MAX / rec_id=INT32_MAX so they never match.
    """

    def __init__(self, shard: VariantIndexShard, window: int = 2048):
        if window % 128:
            raise ValueError("window must be a multiple of 128 lanes")
        self.window = window
        n = shard.n_rows
        L = (n // window + 2) * window
        mat = np.empty((N_ROWS, L), dtype=np.int32)
        for name, row in _ROW_SOURCES:
            mat[row, :n] = shard.cols[name]
            mat[row, n:] = _PAD_FILLS[name]
        ap = shard.cols["alt_prefix"].view(np.int32)  # [n, 4]
        mat[ROW_AP : ROW_AP + 4, :n] = ap.T
        mat[ROW_AP : ROW_AP + 4, n:] = 0
        mat[ROW_AP + 4 :, :] = 0
        self.shard = shard
        self.n_rows = n
        self.mat = jnp.asarray(mat)
        self.chrom_offsets = jnp.asarray(
            shard.chrom_offsets.astype(np.int32)
        )
        self.n_iters = bisect_iters(L)


def _shift_right(x, k: int, fill):
    """Lane-axis right shift by static k with constant fill.

    Mosaic cannot lower a shifted concatenate (offset mismatch on the
    non-concat dimension), so this is a circular ``pltpu.roll`` with the
    wrapped lanes masked to ``fill``; interpret mode falls back to
    ``jnp.roll`` (same semantics) so the kernel stays CPU-testable.
    """
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    try:
        rolled = pltpu.roll(x, shift=k, axis=1)
    except Exception:
        rolled = jnp.roll(x, k, axis=1)
    return jnp.where(lane < k, fill, rolled)


def _cum(x, op, fill):
    """Inclusive scan along lanes via log-depth shifted combines."""
    n = x.shape[1]
    k = 1
    while k < n:
        x = op(x, _shift_right(x, k, fill))
        k *= 2
    return x


def _pallas_kernel(starts_ref, qarr_ref, t0_ref, t1_ref, out_ref, *, W):
    i = pl.program_id(0)
    q = lambda fld: qarr_ref[i, fld]

    win = jnp.concatenate([t0_ref[:, :], t1_ref[:, :]], axis=1)  # [16, 2W]
    row = lambda r: win[r : r + 1, :]  # [1, 2W]

    base = starts_ref[i] * W
    lo = q(F_LO)
    hi = q(F_HI)
    gidx = base + jax.lax.broadcasted_iota(jnp.int32, (1, 2 * W), 1)

    # Mosaic dislikes selects over 1-bit vectors, so the whole predicate
    # stack is int32 0/1 mask algebra; booleans appear only as compare
    # results immediately widened via jnp.where(cond, 1, 0).
    b2i = lambda cond: jnp.where(cond, jnp.int32(1), jnp.int32(0))
    valid = b2i(gidx >= lo) & b2i(gidx < jnp.minimum(hi, lo + W))

    rec_end = row(ROW_REC_END)
    end_ok = b2i(q(F_END_MIN) <= rec_end) & b2i(rec_end <= q(F_END_MAX))

    ref_ok = b2i(q(F_REF_WILD) != 0) | (
        b2i(row(ROW_REF_HASH) == q(F_REF_HASH))
        & b2i(row(ROW_REF_LEN) == q(F_REF_LEN))
    )

    alt_len = row(ROW_ALT_LEN)
    len_ok = b2i(q(F_MIN_LEN) <= alt_len) & b2i(alt_len <= q(F_MAX_LEN))

    flags = row(ROW_FLAGS)
    f = lambda bit: b2i((flags & bit) != 0)
    sym = f(FLAG.SYMBOLIC)
    nsym = 1 - sym
    k = row(ROW_K)
    ref_len = row(ROW_REF_LEN)

    # symbolic-prefix match over the 4 packed alt-prefix words (int32
    # bitwise XOR/AND is bit-identical to the uint32 original)
    pm = jnp.ones_like(valid)
    for w in range(4):
        diff = (row(ROW_AP + w) ^ q(F_VP0 + w)) & q(F_VM0 + w)
        pm = pm & b2i(diff == 0)

    del_ok = (sym & (pm | f(FLAG.CN0))) | (nsym & b2i(alt_len < ref_len))
    ins_ok = (sym & pm) | (nsym & b2i(alt_len > ref_len))
    dup_ok = (
        sym & (pm | (f(FLAG.CN_PREFIX) & (1 - f(FLAG.CN0)) & (1 - f(FLAG.CN1))))
    ) | (nsym & b2i(k >= 2))
    dupt_ok = (sym & (pm | f(FLAG.CN2))) | (nsym & b2i(k == 2))
    cnv_ok = (
        sym
        & (pm | f(FLAG.CN_PREFIX) | f(FLAG.DEL_PREFIX) | f(FLAG.DUP_PREFIX))
    ) | (nsym & (f(FLAG.DOT) | b2i(k >= 1)))
    other_ok = sym & pm
    vt = q(F_VT_CODE)
    type_ok = jnp.where(
        vt == VT_DEL,
        del_ok,
        jnp.where(
            vt == VT_INS,
            ins_ok,
            jnp.where(
                vt == VT_DUP,
                dup_ok,
                jnp.where(
                    vt == VT_DUP_TANDEM,
                    dupt_ok,
                    jnp.where(vt == VT_CNV, cnv_ok, other_ok),
                ),
            ),
        ),
    )
    exact_ok = b2i(row(ROW_ALT_HASH) == q(F_ALT_HASH)) & b2i(
        alt_len == q(F_ALT_LEN)
    )
    anyb_ok = f(FLAG.SINGLE_BASE)
    mode = q(F_ALT_MODE)
    alt_ok = jnp.where(
        mode == MODE_EXACT,
        exact_ok,
        jnp.where(mode == MODE_ANY_BASE, anyb_ok, type_ok),
    )

    m_i = valid & end_ok & ref_ok & len_ok & alt_ok  # int32 0/1

    ac = row(ROW_AC)
    call_count = jnp.sum(m_i * ac)
    n_variants = jnp.sum(m_i & b2i(ac != 0))
    n_matched = jnp.sum(m_i)

    # AN once per record with >= 1 matched row: segmented first-match via
    # cumsum (matched before lane) + cummax (matched-before at seg start)
    rec = jnp.where(valid != 0, row(ROW_REC_ID), INT32_MAX)
    seg_begin = b2i(rec != _shift_right(rec, 1, jnp.int32(-1)))
    cs = _cum(m_i, jnp.add, jnp.int32(0))
    before = cs - m_i
    seg_base = _cum(
        jnp.where(seg_begin != 0, before, jnp.int32(-1)),
        jnp.maximum,
        jnp.int32(-1),
    )
    first_match = m_i & b2i(before == seg_base)
    all_alleles = jnp.sum(first_match * row(ROW_AN))

    overflow = jnp.where((hi - lo) > W, jnp.int32(1), jnp.int32(0))

    # aggregates land in SMEM; one (1, 8)-scalar row per query (the block's
    # trailing dims equal the array dims, satisfying the tiling rule)
    out_ref[0, 0, 0] = jnp.where(call_count > 0, jnp.int32(1), jnp.int32(0))
    out_ref[0, 0, 1] = call_count
    out_ref[0, 0, 2] = n_variants
    out_ref[0, 0, 3] = all_alleles
    out_ref[0, 0, 4] = n_matched
    out_ref[0, 0, 5] = overflow
    out_ref[0, 0, 6] = 0
    out_ref[0, 0, 7] = 0


def pack_encoded(enc: dict[str, np.ndarray]) -> np.ndarray:
    """Host-side: one int32 ``[B, 22]`` array holding every query field —
    a single H2D transfer instead of 22 (the device may sit behind a
    network tunnel where each transfer costs milliseconds)."""
    b = len(enc["chrom"])
    packed = np.empty((b, N_FIELDS - 2), dtype=np.int32)
    packed[:, F_CHROM] = enc["chrom"]
    packed[:, F_START_MIN] = enc["start_min"]
    packed[:, F_START_MAX] = enc["start_max"]
    packed[:, F_END_MIN] = enc["end_min"]
    packed[:, F_END_MAX] = enc["end_max"]
    packed[:, F_REF_WILD] = enc["ref_wild"]
    packed[:, F_REF_HASH] = enc["ref_hash"]
    packed[:, F_REF_LEN] = enc["ref_len"]
    packed[:, F_ALT_MODE] = enc["alt_mode"]
    packed[:, F_ALT_HASH] = enc["alt_hash"]
    packed[:, F_ALT_LEN] = enc["alt_len"]
    packed[:, F_VT_CODE] = enc["vt_code"]
    packed[:, F_VP0 : F_VP0 + 4] = enc["vprefix"].view(np.int32)
    packed[:, F_VM0 : F_VM0 + 4] = enc["vprefix_mask"].view(np.int32)
    packed[:, F_MIN_LEN] = enc["min_len"]
    packed[:, F_MAX_LEN] = enc["max_len"]
    return packed


@partial(jax.jit, static_argnames=("W", "n_iters", "interpret"))
def _pallas_query_batch(mat, chrom_offsets, packed, *, W, n_iters, interpret):
    """Phase A (XLA): bisect window bounds. Phase B (Pallas): window scan.

    ``packed`` is the ``pack_encoded`` array, B a multiple of CHUNK (or
    ≤ CHUNK); the chunk loop runs on-device via ``lax.map`` so the whole
    batch is one dispatch regardless of size.
    """
    pos = mat[ROW_POS]
    chrom = packed[:, F_CHROM]
    seg_lo = chrom_offsets[chrom]
    seg_hi = chrom_offsets[chrom + 1]
    lo = jax.vmap(
        lambda t, a, b: _bisect(pos, t, a, b, n_iters, upper=False)
    )(packed[:, F_START_MIN], seg_lo, seg_hi)
    hi = jax.vmap(
        lambda t, a, b: _bisect(pos, t, a, b, n_iters, upper=True)
    )(packed[:, F_START_MAX], seg_lo, seg_hi)
    starts = (lo // W).astype(jnp.int32)
    qarr = jnp.concatenate(
        [packed, lo[:, None], hi[:, None]], axis=1
    ).astype(jnp.int32)

    b = qarr.shape[0]
    chunk = min(b, CHUNK)
    nc = b // chunk

    def run_chunk(args):
        starts_c, qarr_c = args
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(chunk,),
            in_specs=[
                pl.BlockSpec((N_ROWS, W), lambda i, s, q: (0, s[i])),
                pl.BlockSpec((N_ROWS, W), lambda i, s, q: (0, s[i] + 1)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, 8),
                lambda i, s, q: (i, 0, 0),
                memory_space=pltpu.SMEM,
            ),
        )
        out = pl.pallas_call(
            partial(_pallas_kernel, W=W),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((chunk, 1, 8), jnp.int32),
            interpret=interpret,
        )(starts_c, qarr_c, mat, mat)
        return out[:, 0, :]

    out = jax.lax.map(
        run_chunk,
        (starts.reshape(nc, chunk), qarr.reshape(nc, chunk, N_FIELDS)),
    ).reshape(b, 8)
    return {
        "exists": out[:, 0] > 0,
        "call_count": out[:, 1],
        "n_variants": out[:, 2],
        "all_alleles_count": out[:, 3],
        "n_matched": out[:, 4],
        "overflow": out[:, 5] > 0,
    }


# queries per pallas_call: the scalar-prefetched query array lives in SMEM
# (~1 MB), so batches are chunked; the tail chunk is padded to keep one
# compiled program per (W, n_iters) pair
CHUNK = 1024


def run_queries_pallas(
    pindex: PallasDeviceIndex,
    queries,
    *,
    interpret: bool | None = None,
) -> dict[str, np.ndarray]:
    """Aggregate query results via the Pallas window-scan kernel.

    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh; on TPU it compiles through Mosaic.
    """
    if not HAVE_PALLAS:
        raise RuntimeError("pallas is unavailable in this jax build")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    enc = encode_queries(queries) if isinstance(queries, list) else queries
    packed = pack_encoded(enc)
    b = len(packed)
    if b == 0:
        return {
            "exists": np.zeros(0, bool),
            "call_count": np.zeros(0, np.int32),
            "n_variants": np.zeros(0, np.int32),
            "all_alleles_count": np.zeros(0, np.int32),
            "n_matched": np.zeros(0, np.int32),
            "overflow": np.zeros(0, bool),
        }
    if b > CHUNK and b % CHUNK:
        pad = CHUNK - b % CHUNK
        packed = np.concatenate([packed, np.repeat(packed[-1:], pad, axis=0)])
    out = _pallas_query_batch(
        pindex.mat,
        pindex.chrom_offsets,
        jnp.asarray(packed),
        W=pindex.window,
        n_iters=pindex.n_iters,
        interpret=interpret,
    )
    return {k: np.asarray(v)[:b] for k, v in jax.device_get(out).items()}
