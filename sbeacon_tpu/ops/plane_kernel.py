"""Device-resident genotype bit planes + in-kernel masked reductions.

Round-3 left the selected-samples leaf half on host: the device matched
rows, then sample restriction ran as numpy popcounts over HOST-resident
genotype planes (~25 GB at 1000-Genomes width — engine.materialize_
response), capping the path at one host's RAM (VERDICT r3 missing #2).
This module puts the planes themselves in HBM and runs the per-row
masked popcounts and the sample-hit OR-reduction in one jitted program:

- ``PlaneDeviceIndex`` uploads the shard's planes as ``[n, W]`` int32
  device arrays (W = ceil(n_samples/32) words; XLA lays the minor dim
  out in 128-lane tiles, so a 2504-sample corpus costs ~512 B/row/plane
  of HBM). The count planes (gt2/tok1/tok2) are uploaded only when the
  shard has genotype-derived rows at all — INFO-sourced corpora (the
  common cohort-VCF case, and the bench corpus) only ever touch ``gt``
  for sample-hit extraction, so only it occupies HBM.
- ``plane_row_stats`` gathers the matched rows' plane words, ANDs the
  selected-sample mask, and returns per-row popcounts ``[R, 4]`` plus
  the OR of ``gt & mask`` over a caller-chosen row subset — the exact
  quantities ``materialize_response`` popcounted on host. The reference
  semantics (cumulative-truncation k0, ploidy>2 overflow side tables)
  stay host-side and UNCHANGED: the device call replaces only the
  bandwidth-heavy plane reads.

Capacity: a plane set that does not fit the configured HBM budget stays
host-resident and the engine serves exactly as before (the fallback is
the round-3 path, not an error). Multi-chip: planes shard row-wise with
their dataset over the mesh — ``parallel/mesh.py`` stacks them like the
index columns and the dryrun proves the sharded layout.

Reference parity: per-sample hit extraction and genotype-derived
counting mirror performQuery/search_variants_in_samples.py (the
reference's ``--samples`` bcftools leaf, search_variants.py:233-258).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index.columnar import FLAG, VariantIndexShard
from ..telemetry import record_device_launch

# R padding tiers: one compiled program per (tier, flags) combination;
# larger row sets chunk through the top tier (bounded compile cache)
_R_TIERS = (128, 1024, 8192)


def staged_device_put(a: np.ndarray, chunk_bytes: int | None):
    """H2D upload as pre-staged contiguous row chunks.

    One monolithic ``jnp.asarray`` of a GB-scale plane serialises
    host staging and transfer (the config7 wall: ~28 MB/s, 35.9 s for
    1.02 GB). Chunking double-buffers it: ``jax.device_put`` is
    asynchronous, so while chunk i's bytes stream to the device the
    host is already staging chunk i+1 into a fresh contiguous buffer.
    The chunks concatenate on-device — transiently ~2x the array's
    footprint, which the engine's HBM gate headroom absorbs (the gate
    reserves before upload). ``chunk_bytes`` None/<=0 or a small array
    falls back to the single-copy path.
    """
    if (
        not chunk_bytes
        or chunk_bytes <= 0
        or a.nbytes <= chunk_bytes
        or a.ndim != 2
    ):
        return jnp.asarray(np.ascontiguousarray(a))
    rows_per = max(1, int(chunk_bytes // max(1, a[:1].nbytes)))
    parts = [
        jax.device_put(np.ascontiguousarray(a[i : i + rows_per]))
        for i in range(0, a.shape[0], rows_per)
    ]
    out = jnp.concatenate(parts, axis=0)
    del parts
    return out


def sample_mask_words(
    selected_idx, n_words: int
) -> np.ndarray:
    """uint32[n_words] bit mask for a selected-sample index list — THE
    wire format every plane consumer shares (bit s%32 of word s//32)."""
    mask = np.zeros(n_words, dtype=np.uint32)
    for si in selected_idx:
        mask[si // 32] |= np.uint32(1 << (si % 32))
    return mask


class PlaneDeviceIndex:
    """Device-resident genotype planes of one shard.

    ``gt`` is always uploaded (sample-hit extraction needs it); the
    three count planes ride along only when the shard contains
    genotype-derived rows (any row without AC_INFO/AN_INFO) — otherwise
    the counting path never reads them (materialize_response's
    ``count_planes`` gate) and uploading them would waste HBM.
    """

    @staticmethod
    def wants_count_planes(shard: VariantIndexShard) -> bool:
        """True when the shard can need genotype-derived counting: all
        three count planes present AND at least one row without
        INFO-sourced AC/AN. ONE predicate shared by the constructor and
        the budget estimate so they can never drift."""
        flags = shard.cols["flags"]
        return bool(
            shard.has_count_planes
            and (
                ((flags & FLAG.AC_INFO) == 0).any()
                or ((flags & FLAG.AN_INFO) == 0).any()
            )
        )

    def __init__(
        self,
        shard: VariantIndexShard,
        upload_chunk_bytes: int | None = 256 * 1024 * 1024,
    ):
        if shard.gt_bits is None:
            raise ValueError("shard has no genotype planes")
        self.n_rows, self.n_words = shard.gt_bits.shape
        self.has_counts = self.wants_count_planes(shard)

        # no padding row: padded gather slots point at row 0 — their
        # count outputs are trimmed by the caller and their OR lanes
        # carry or_sel=0, so the value read is never observed. (An
        # appended zero row would cost a full host-side copy of the
        # largest array in the system.)
        def up(a):
            return staged_device_put(a.view(np.int32), upload_chunk_bytes)

        self.gt = up(shard.gt_bits)
        if self.has_counts:
            self.gt2 = up(shard.gt_bits2)
            self.tok1 = up(shard.tok_bits1)
            self.tok2 = up(shard.tok_bits2)
        else:
            self.gt2 = self.tok1 = self.tok2 = None

    def nbytes_hbm(self) -> int:
        """HBM bytes including XLA's 128-lane minor-dim padding."""
        w_pad = -(-self.n_words // 128) * 128
        per = self.n_rows * w_pad * 4
        return per * (4 if self.has_counts else 1)

    @staticmethod
    def estimate_hbm(shard: VariantIndexShard) -> int:
        """Upload-free HBM estimate for the capacity gate (same
        count-plane predicate as the constructor)."""
        if shard.gt_bits is None:
            return 0
        n, w = shard.gt_bits.shape
        w_pad = -(-w // 128) * 128
        has_counts = PlaneDeviceIndex.wants_count_planes(shard)
        return n * w_pad * 4 * (4 if has_counts else 1)


@partial(jax.jit, static_argnames=("R", "with_counts", "with_or"))
def _plane_stats(
    gt, gt2, tok1, tok2, rows, or_sel, mask, *, R, with_counts, with_or
):
    """[R,4] per-row masked popcounts + [W] OR of gt&mask over or_sel.

    ``rows`` int32[R] (padding slots point at row 0; callers discard
    their outputs), ``or_sel`` int32[R] 0/1, ``mask`` int32[W]. Popcount columns:
    0=gt, 1=gt2, 2=tok1, 3=tok2 (count columns zero when the plane set
    has no count planes)."""
    m = mask[None, :]

    def pc(plane):
        return jnp.sum(
            jax.lax.population_count(plane[rows] & m), axis=1
        ).astype(jnp.int32)

    g = gt[rows] & m  # [R, W]
    pc_gt = jnp.sum(jax.lax.population_count(g), axis=1).astype(jnp.int32)
    zero = jnp.zeros_like(pc_gt)
    if with_counts:
        cols = [pc_gt, pc(gt2), pc(tok1), pc(tok2)]
    else:
        cols = [pc_gt, zero, zero, zero]
    counts = jnp.stack(cols, axis=1)
    if with_or:
        or_words = jax.lax.reduce(
            jnp.where(or_sel[:, None] != 0, g, jnp.int32(0)),
            np.int32(0),
            jax.lax.bitwise_or,
            dimensions=(0,),
        )
    else:
        or_words = jnp.zeros((gt.shape[1],), jnp.int32)
    return counts, or_words


def plane_row_stats(
    pindex: PlaneDeviceIndex,
    rows: np.ndarray,
    selected_mask_words: np.ndarray | None,
    *,
    or_sel: np.ndarray | None = None,
    with_counts: bool | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Device masked plane reductions for a matched-row set.

    Returns ``(counts[len(rows), 4] int64, or_words[W] uint32)``.
    ``or_sel`` restricts the gt OR-reduction to a row subset (the
    caller's exact ``grp >= k0`` selection); None ORs nothing.
    ``with_counts`` defaults to the plane set's capability."""
    R = len(rows)
    if with_counts is None:
        with_counts = pindex.has_counts
    top = _R_TIERS[-1]
    if R > top:
        # chunk through the fixed top tier: counts concatenate, the OR
        # words fold on host (compile cache stays bounded)
        counts_parts = []
        or_acc = None
        for a in range(0, R, top):
            sl = slice(a, min(a + top, R))
            cnt, ow = plane_row_stats(
                pindex,
                rows[sl],
                selected_mask_words,
                or_sel=None if or_sel is None else or_sel[sl],
                with_counts=with_counts,
            )
            counts_parts.append(cnt)
            or_acc = ow if or_acc is None else (or_acc | ow)
        return (
            np.concatenate(counts_parts),
            or_acc
            if or_acc is not None
            else np.zeros(pindex.n_words, np.uint32),
        )
    tier = next(t for t in _R_TIERS if R <= t)
    # pad slots target row 0: counts are trimmed to [:R], OR lanes carry
    # or_sel=0, so the padded reads are never observed
    rows_p = np.zeros(tier, np.int32)
    rows_p[:R] = rows
    sel_p = np.zeros(tier, np.int32)
    if or_sel is not None:
        sel_p[:R] = np.asarray(or_sel, dtype=np.int32)
    if selected_mask_words is None:
        mask = np.full(pindex.n_words, 0xFFFFFFFF, np.uint32)
    else:
        mask = np.asarray(selected_mask_words, dtype=np.uint32)
    t0 = time.perf_counter()
    counts, or_words = _plane_stats(
        pindex.gt,
        pindex.gt2 if with_counts else pindex.gt,
        pindex.tok1 if with_counts else pindex.gt,
        pindex.tok2 if with_counts else pindex.gt,
        jnp.asarray(rows_p),
        jnp.asarray(sel_p),
        jnp.asarray(mask.view(np.int32)),
        R=tier,
        with_counts=with_counts,
        with_or=or_sel is not None,
    )
    # flight-recorder seam (the scatter seam feeds the historical
    # N_DISPATCHES property). The old `_sk.N_DISPATCHES += 1` here was
    # worse than the racy read-modify-write the lint bans: the read
    # went through scatter_kernel's PEP 562 recorder property and the
    # write then planted a REAL module attribute, permanently
    # shadowing the recorder behind a frozen snapshot for every later
    # reader in the process.
    record_device_launch(
        "plane",
        seam="scatter",
        tier=tier,
        specs_real=R,
        specs_padded=tier,
        launch_ms=(time.perf_counter() - t0) * 1e3,
    )
    counts, or_words = jax.device_get((counts, or_words))
    return (
        np.asarray(counts)[:R].astype(np.int64),
        np.asarray(or_words).view(np.uint32),
    )


def device_plane_probe(
    pindex: PlaneDeviceIndex,
    rows: np.ndarray,
    selected_mask_words: np.ndarray,
    *,
    iters: int = 64,
) -> float:
    """Seconds per plane-stats call on-device, by the same two-chain
    differencing the query kernels use (the backend's
    block_until_ready returns early — see scatter_kernel)."""
    import time as _time

    R = len(rows)
    tier = next((t for t in _R_TIERS if R <= t), _R_TIERS[-1])
    rows_p = np.zeros(tier, np.int32)
    rows_p[: min(R, tier)] = rows[:tier]
    sel_p = np.ones(tier, np.int32)
    mask = jnp.asarray(
        np.asarray(selected_mask_words, dtype=np.uint32).view(np.int32)
    )
    rd = jnp.asarray(rows_p)
    sd = jnp.asarray(sel_p)
    n_rows = jnp.int32(pindex.n_rows)

    @partial(jax.jit, static_argnames=("k",))
    def rep(rows0, k):
        def body(carry, _):
            counts, _ow = _plane_stats(
                pindex.gt,
                pindex.gt2 if pindex.has_counts else pindex.gt,
                pindex.tok1 if pindex.has_counts else pindex.gt,
                pindex.tok2 if pindex.has_counts else pindex.gt,
                carry,
                sd,
                mask,
                R=tier,
                with_counts=pindex.has_counts,
                with_or=True,
            )
            # real data dependency (XLA hoists invariant loop bodies)
            return (carry + counts[0, 0]) % n_rows, counts[0, 0]

        _, outs = jax.lax.scan(body, rows0, None, length=k)
        return jnp.sum(outs)

    def timed(k, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            np.asarray(jax.device_get(rep(rd, k)))
            best = min(best, _time.perf_counter() - t0)
        return best

    # auto-escalate the chain length until the signal CLEARS the
    # transport-jitter floor (merely-positive deltas are noise — see
    # scatter_kernel._probe_one_tier)
    floor_s = 0.020
    for k_iters in (iters, iters * 4, iters * 16, iters * 64):
        timed(4, reps=1)
        timed(4 + k_iters, reps=1)
        delta = timed(4 + k_iters) - timed(4)
        if delta >= floor_s:
            return delta / k_iters
    raise RuntimeError("device_plane_probe: below the jitter floor")
