"""Scattered-window variant-query kernel (XLA gather + vectorised algebra).

Why this exists: the round-2 grouped Pallas kernel (deleted in r5;
see git history for the measured comparison) packed
G=64 start-sorted queries per shared tile pair, which amortises HBM
traffic G-fold **only while queries are dense relative to the index** —
at the round-2 bench scale (~100k rows) consecutive sorted queries sit
~10 rows apart and grouping wins big. At 1000-Genomes scale (>=2e7
rows) random point queries land ~2000 rows apart: virtually every
64-slot group holds ONE real query, so the kernel DMAs and evaluates a
[64, 2W] tile span per query — a ~60x waste in both bandwidth and VPU
work (VERDICT r2 weak #2: per-query work proportional to the tile span
rather than the candidate window).

This module is the scale-independent path: **candidate compaction by
construction**. The device columns are bit-packed from 16 int32 rows
down to 8 (pos, rec_end, ref_hash, alt_hash, packed lens, packed
flags+repeat_k+rec-chaining, ac, an) and laid out tile-major:
``tiles[t] = packed[:, t*T : (t+1)*T]`` with shape ``[n_tiles, 8, T]``.
One XLA gather fetches each query's own ``C = cap//T + 1`` consecutive
tiles (8 KB for point queries at T=128) and the entire predicate stack
from the grouped kernel runs as plain vectorised jnp over the gathered
window — XLA
fuses the elementwise algebra into the gather's consumers, pipelines
HBM reads, and the same program runs natively on CPU for tests (no
interpret mode needed). Per-query cost is now proportional to the
(capped) candidate window, independent of index size, and batches are
split across window-cap tiers so point queries never pay a wide
bracket's gather (window-adaptive tiles, VERDICT r2 next #2).

Matching semantics are IDENTICAL to ``ops.kernel._query_one``
(the exact spec of the reference's
matcher, performQuery/search_variants.py:84-254) — same predicates,
same '<None' artifact, same AN-once-per-matching-record rule. The
"first matched row of each record" computation needs no rec_id column:
a single SAME_PREV flag bit (row i and i-1 belong to the same record)
reconstructs record segments, and a segmented cumsum/cummax scan marks
first matches — records straddling the window edge still count AN
exactly once because out-of-window lanes never match.

Lossless bit-packing, by two complementary guards: row alt_len clamps
to 0xFFFF and ref_len to 0x1FFF in the packed matrix, and (a)
``pack_q8`` host-flags any QUERY whose length fields could see the
clamp (>= the clamp value) while (b) any ROW that was actually clamped
carries ROW_CLAMPED, which overflows every query whose candidate
window contains it (length-relative DEL/INS predicates cannot be
evaluated against clamped lengths). Either way the rare affected query
takes the uncapped host path — a clamped row can never produce a
different verdict than the exact host matcher.

Record granularity: the per-query match mask bit-packs to 2T/16 words
(T=128 -> 16 words = 64 B/query) — already smaller than a
record_cap x 4 B compacted hit list for record_cap >= 16, so the mask
IS the bounded compact hit buffer (VERDICT r2 weak #3); the host
unpacks row ids with one vectorised ``np.unpackbits`` per batch.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index.columnar import FLAG, INT32_MAX, VariantIndexShard
from ..telemetry import note_device_stage, record_device_launch
from .kernel import (
    MODE_ANY_BASE,
    MODE_EXACT,
    QueryResults,
    VT_CNV,
    VT_DEL,
    VT_DUP,
    VT_DUP_TANDEM,
    VT_INS,
    _PAD_FILLS,
    encode_queries,
)
from .query_pack import (
    PM_CNV,
    PM_DUPT,
    PM_INS,
    _rows_from_masks,
    _window_bounds,
    pack_q8,
    stage_symbolic_flags,
)

# packed hot-matrix rows
P_POS = 0
P_REC_END = 1
P_REF_HASH = 2
P_ALT_HASH = 3
P_LENS = 4  # alt_len(16, clamped) | ref_len(13, clamped) << 16
P_FLAGS = 5  # FLAG/PM bits(0..18) | (repeat_k+1)(7) << 19 | SAME_PREV << 26
P_AC = 6
P_AN = 7
N_PACKED = 8

SAME_PREV = 1 << 26  # row belongs to the same record as the previous row
# row had ref_len/alt_len clamped in the packed matrix: length-RELATIVE
# predicates (DEL's alt_len<ref_len, INS's alt_len>ref_len) are not
# trustworthy near such a row, so any query whose candidate window
# contains one overflows to the exact host matcher (query-side clamps
# are handled separately by pack_q8's >= guards)
ROW_CLAMPED = 1 << 27

_ALT_LEN_CLAMP = 0xFFFF
_REF_LEN_CLAMP = 0x1FFF

# fixed device-batch sizes (compiled-program reuse across logical sizes)
CHUNK = 2048
CHUNK_SMALL = 64

# longest record (in SAME_PREV-chained rows minus one) the K-shift
# first-match form handles; longer records take the segmented-scan form
SEG_K_MAX = 8

def __getattr__(name: str):
    """Module back-compat property (PEP 562): ``N_DISPATCHES`` — one
    per kernel program launched (a multi-chunk _scatter_many lax.map
    is ONE dispatch; the bench divides deltas by request count to
    evidence the one-dispatch-per-request-batch serving contract,
    VERDICT r3 #4) — now served by the device flight recorder
    (telemetry.py), whose lock owns the increment instead of the old
    unlocked module-global read-modify-write."""
    if name == "N_DISPATCHES":
        from ..telemetry import flight_recorder

        return flight_recorder.scatter_dispatches
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


class ScatterDeviceIndex:
    """Non-overlapped packed tiles of one shard, for the gather kernel.

    ``tiles[t]`` covers global rows ``[t*T, (t+1)*T)``. A query whose
    capped window is ``cap`` rows wide gathers ``C = cap//T + 1``
    consecutive tiles starting at ``lo // T`` — window-adaptive cost:
    point queries pay 2 tiles (8 KB at T=128) while wide brackets pay
    proportionally more, each batch tier compiled once. Storage is the
    packed columns verbatim (~32 B/row -> ~640 MB HBM at 2e7 rows).
    ``MAX_C`` tail padding tiles guarantee every gather stays in range.
    """

    MAX_C = 17  # supports caps up to 2048 lanes at T=128

    def __init__(self, shard: VariantIndexShard, tile: int = 128):
        if tile % 128:
            raise ValueError("tile must be a multiple of 128 lanes")
        self.tile = tile
        n = shard.n_rows
        c = shard.cols
        n_tiles = n // tile + 1 + self.MAX_C
        L = n_tiles * tile
        packed = np.empty((N_PACKED, L), dtype=np.int32)

        def fill(row, values, pad):
            packed[row, :n] = values
            packed[row, n:] = pad

        fill(P_POS, c["pos"], _PAD_FILLS["pos"])
        fill(P_REC_END, c["rec_end"], _PAD_FILLS["rec_end"])
        fill(P_REF_HASH, c["ref_hash"], 0)
        fill(P_ALT_HASH, c["alt_hash"], 0)
        lens = np.minimum(
            c["alt_len"].astype(np.int64), _ALT_LEN_CLAMP
        ) | (
            np.minimum(c["ref_len"].astype(np.int64), _REF_LEN_CLAMP) << 16
        )
        fill(P_LENS, lens.astype(np.int64).astype(np.int32), 0)
        flags = stage_symbolic_flags(c["flags"], c["alt_prefix"])
        k1 = np.clip(c["ref_repeat_k"].astype(np.int64) + 1, 0, 127)
        flags |= k1 << 19
        clamped = (c["ref_len"].astype(np.int64) > _REF_LEN_CLAMP) | (
            c["alt_len"].astype(np.int64) > _ALT_LEN_CLAMP
        )
        flags |= np.where(clamped, np.int64(ROW_CLAMPED), 0)
        rec = c["rec_id"]
        same = np.zeros(n, dtype=np.int64)
        if n > 1:
            same[1:] = (rec[1:] == rec[:-1]).astype(np.int64)
        flags |= same * SAME_PREV
        fill(P_FLAGS, flags.astype(np.int32), 0)
        fill(P_AC, c["ac"], 0)
        fill(P_AN, c["an"], 0)

        # longest SAME_PREV run = (max rows per record) - 1: lets the
        # kernel replace the 14-pass cumsum+cummax segmented first-match
        # scan with K cheap shifted ANDs (K is static per shard; real
        # corpora have 1-3 alts per record so K is tiny)
        z = np.flatnonzero(
            np.concatenate(([0], same.astype(np.int8), [0])) == 0
        )
        self.seg_k = int(np.diff(z).max()) - 1

        # tile-major layout: tiles[t] = packed[:, t*T : (t+1)*T]
        self.tiles = jnp.asarray(
            np.ascontiguousarray(
                packed.reshape(N_PACKED, n_tiles, tile).transpose(1, 0, 2)
            )
        )  # [n_tiles, 8, T]
        self.n_rows = n
        self.n_tiles = n_tiles
        self.shard = shard
        self.pos_host = c["pos"]
        self.offsets_host = shard.chrom_offsets.astype(np.int64)

    def nbytes(self) -> int:
        return int(self.tiles.size) * 4


def _scatter_core(
    tiles, tile_ids, qarr, *, T, CAP, C=None, exact_only=False, seg_k=None
):
    """Traced core shared by the match-only and fused-selected batch
    programs: C-tile gather + the vectorised predicate stack.

    Returns ``(agg, masks, m_i, win, gidx, lo)`` — agg/masks are the
    public results; m_i/win/gidx/lo let the fused program reduce the
    genotype planes over the SAME gathered window without re-deriving
    the match semantics (one source of truth for the predicate stack).
    """
    from .query_pack import (
        Q_ALT_HASH,
        Q_END_MAX,
        Q_END_MIN,
        Q_HI,
        Q_LENS,
        Q_LO,
        Q_META,
        Q_REF_HASH,
    )

    if C is None:
        C = CAP // T + 1
    span = C * T
    gat = tiles[
        tile_ids[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    ]  # [B, C, 8, T]
    win = jnp.transpose(gat, (0, 2, 1, 3)).reshape(-1, N_PACKED, span)
    row = lambda r: win[:, r, :]  # [B, C*T]
    q = lambda f: qarr[:, f : f + 1]  # [B, 1]

    lo = q(Q_LO)
    hi = q(Q_HI)
    gidx = tile_ids[:, None] * T + jax.lax.broadcasted_iota(
        jnp.int32, (1, span), 1
    )

    meta = q(Q_META)
    ref_wild = meta & 1
    mode = (meta >> 1) & 3
    vt = (meta >> 3) & 7
    ref_len_q = (meta >> 6) & 0x1FFF
    min_len_q = (meta >> 19) & 0x1FFF
    lens_q = q(Q_LENS)
    alt_len_q = lens_q & 0xFFFF
    max_len_q = (lens_q >> 16) & 0xFFFF
    max_len_q = jnp.where(max_len_q == 0xFFFF, jnp.int32(INT32_MAX), max_len_q)

    b2i = lambda cond: jnp.where(cond, jnp.int32(1), jnp.int32(0))
    valid = b2i(gidx >= lo) & b2i(gidx < jnp.minimum(hi, lo + CAP))

    rec_end = row(P_REC_END)
    end_ok = b2i(q(Q_END_MIN) <= rec_end) & b2i(rec_end <= q(Q_END_MAX))

    lens = row(P_LENS)
    alt_len = lens & 0xFFFF
    ref_len = (lens >> 16) & 0x1FFF

    ref_ok = b2i(ref_wild != 0) | (
        b2i(row(P_REF_HASH) == q(Q_REF_HASH)) & b2i(ref_len == ref_len_q)
    )
    len_ok = b2i(min_len_q <= alt_len) & b2i(alt_len <= max_len_q)

    flags = row(P_FLAGS)
    f = lambda bit: b2i((flags & bit) != 0)
    exact_ok = b2i(row(P_ALT_HASH) == q(Q_ALT_HASH)) & b2i(
        alt_len == alt_len_q
    )
    if exact_only:
        # static specialisation: every query in the batch is MODE_EXACT
        # — the whole symbolic-type chain below is dead code
        alt_ok = exact_ok
    else:
        sym = f(FLAG.SYMBOLIC)
        nsym = 1 - sym
        k = ((flags >> 19) & 0x7F) - 1

        del_ok = (sym & (f(FLAG.DEL_PREFIX) | f(FLAG.CN0))) | (
            nsym & b2i(alt_len < ref_len)
        )
        ins_ok = (sym & f(PM_INS)) | (nsym & b2i(alt_len > ref_len))
        dup_ok = (
            sym
            & (
                f(FLAG.DUP_PREFIX)
                | (f(FLAG.CN_PREFIX) & (1 - f(FLAG.CN0)) & (1 - f(FLAG.CN1)))
            )
        ) | (nsym & b2i(k >= 2))
        dupt_ok = (sym & (f(PM_DUPT) | f(FLAG.CN2))) | (nsym & b2i(k == 2))
        cnv_ok = (
            sym
            & (
                f(PM_CNV)
                | f(FLAG.CN_PREFIX)
                | f(FLAG.DEL_PREFIX)
                | f(FLAG.DUP_PREFIX)
            )
        ) | (nsym & (f(FLAG.DOT) | b2i(k >= 1)))
        other_ok = jnp.zeros_like(valid)
        type_ok = jnp.where(
            vt == VT_DEL,
            del_ok,
            jnp.where(
                vt == VT_INS,
                ins_ok,
                jnp.where(
                    vt == VT_DUP,
                    dup_ok,
                    jnp.where(
                        vt == VT_DUP_TANDEM,
                        dupt_ok,
                        jnp.where(vt == VT_CNV, cnv_ok, other_ok),
                    ),
                ),
            ),
        )
        anyb_ok = f(FLAG.SINGLE_BASE)
        alt_ok = jnp.where(
            mode == MODE_EXACT,
            exact_ok,
            jnp.where(mode == MODE_ANY_BASE, anyb_ok, type_ok),
        )

    m_i = valid & end_ok & ref_ok & len_ok & alt_ok  # [B, 2T] 0/1

    ac = row(P_AC)
    call_count = jnp.sum(m_i * ac, axis=1, keepdims=True)
    n_variants = jnp.sum(m_i & b2i(ac != 0), axis=1, keepdims=True)
    n_matched = jnp.sum(m_i, axis=1, keepdims=True)

    # AN once per record with >= 1 matched row: segmented first-match
    # from the SAME_PREV chain bit — seg_begin marks each record's first
    # row; a matched lane is its record's first match iff the count of
    # matches before it equals the count at its segment's start. A
    # forced segment start at the window's first lane (gidx == lo)
    # covers records straddling the window edge: without it, a record
    # whose earlier rows precede the tile itself would leave seg_base
    # at its -1 initial value and silently drop the record's AN. Lanes
    # before lo never match, so the forced boundary cannot split a
    # record's *matched* lanes.
    if seg_k is not None:
        # K-shift formulation: a matched lane is its record's first
        # match iff no match sits 1..K lanes earlier within an unbroken
        # SAME_PREV chain (K = the shard's longest chain, static).
        # Lanes before lo never match, so records straddling the window
        # edge still count AN exactly once — no forced boundary needed.
        same_prev = f(SAME_PREV)
        same_before = jnp.zeros_like(m_i)
        chain = same_prev
        for k in range(1, seg_k + 1):
            shifted_m = jnp.pad(m_i, ((0, 0), (k, 0)))[:, :span]
            same_before = same_before | (chain & shifted_m)
            if k < seg_k:
                chain = chain & jnp.pad(
                    same_prev, ((0, 0), (k, 0))
                )[:, :span]
        first_match = m_i & (1 - same_before)  # same_before is 0/1
    else:
        # general segmented-scan form (unbounded record length)
        seg_begin = (1 - f(SAME_PREV)) | b2i(gidx == lo)
        cs = jnp.cumsum(m_i, axis=1)
        before = cs - m_i
        seg_base = jax.lax.cummax(
            jnp.where(seg_begin != 0, before, jnp.int32(-1)), axis=1
        )
        first_match = m_i & b2i(before == seg_base)
    all_alleles = jnp.sum(first_match * row(P_AN), axis=1, keepdims=True)

    # overflow: window wider than the cap, OR a length-clamped row
    # inside the candidate window (its DEL/INS verdicts are untrusted —
    # the host matcher resolves the query exactly)
    overflow = b2i((hi - lo) > CAP) | b2i(
        jnp.sum(valid & f(ROW_CLAMPED), axis=1, keepdims=True) > 0
    )
    zero = jnp.zeros_like(overflow)
    agg = jnp.concatenate(
        [
            b2i(call_count > 0),
            call_count,
            n_variants,
            all_alleles,
            n_matched,
            overflow,
            zero,
            zero,
        ],
        axis=1,
    )
    # bit-pack the match mask: [B, C*T] -> [B, C*T/16] words, bit l of
    # word w = window lane w*16 + l (same wire format as the grouped
    # kernel, so _rows_from_masks is shared)
    nw = span // 16
    weights = (1 << jnp.arange(16, dtype=jnp.int32))[None, None, :]
    masks = jnp.sum(m_i.reshape(-1, nw, 16) * weights, axis=2)
    return agg, masks, m_i, win, gidx, lo


@partial(
    jax.jit,
    static_argnames=("T", "CAP", "nslots", "C", "exact_only", "seg_k"),
)
def _scatter_batch(
    tiles, tile_ids, qarr, *, T, CAP, nslots, C=None, exact_only=False,
    seg_k=None,
):
    """One fixed-size device batch: C-tile gather + vectorised predicates.

    ``tile_ids``: [nslots] int32 (padding slots point at tile 0 with
    lo=hi=0 so nothing matches). ``qarr``: [nslots, 8] packed queries
    (query_pack.pack_q8 encoding).
    By default ``C = CAP//T + 1`` consecutive tiles cover any window of
    width <= CAP whose start lies anywhere inside the first tile. The
    single-tile fast tier passes ``C=1`` explicitly (half the HBM
    gather of the C=2 tier): the caller guarantees every query's
    window lies inside ONE tile (``lo//T == (hi-1)//T``), so one tile
    covers it. ``exact_only=True`` is a static specialisation for
    batches whose queries are ALL MODE_EXACT (the dominant point-lookup
    shape): the symbolic variant-type predicate chain and its flag/k
    extraction drop out of the compiled program (~1.35x on v5e —
    the C=1 batch is no longer purely gather-bound, so VPU work
    matters). Returns (agg [nslots, 8] int32,
    masks [nslots, C*T/16] int32).
    """
    agg, masks, _m, _w, _g, _lo = _scatter_core(
        tiles, tile_ids, qarr, T=T, CAP=CAP, C=C, exact_only=exact_only,
        seg_k=seg_k,
    )
    return agg, masks


@partial(
    jax.jit,
    static_argnames=(
        "T", "CAP", "nslots", "C", "exact_only", "R", "with_counts", "seg_k",
    ),
)
def _selected_batch(
    tiles,
    gt,
    gt2,
    tok1,
    tok2,
    tile_ids,
    qarr,
    mask,
    *,
    T,
    CAP,
    nslots,
    C=None,
    exact_only=False,
    R=64,
    with_counts=False,
    seg_k=None,
):
    """Fused match + genotype-plane reduction: ONE dispatch per batch.

    Extends ``_scatter_batch`` (same predicate core, same gathered
    window) with the selected-samples leaf the engine previously paid a
    second kernel dispatch for (VERDICT r4 next #2 — the reference's
    worker does match + per-sample extraction in one pass,
    performQuery/search_variants.py:233-258):

    - the top-R matched lanes become global row ids in ascending row
      order (stable argsort of the match mask — the in-device
      ``_rows_from_masks``),
    - their gt/count planes are gathered, masked per-query
      (``mask`` int32 [nslots, W]) and popcounted,
    - the sample-hit OR runs over the exact ``grp >= k0`` row subset
      via the same segmented scans as ``parallel.mesh._local_selected``
      (k0 = first record with positive cumulative rc; ploidy>2
      overflow extras can never flip rc positivity — a saturated
      2-bit plane cell popcounts >= 2 — so the device subset equals
      the host's even though the extras themselves stay host-added).

    Returns (agg [nslots,8], rows [nslots,R] global row ids (-1 pad),
    pc_call [nslots,R], pc_tok [nslots,R], or_words [nslots,W]).
    ``with_counts=False`` (INFO-sourced corpora) skips the three
    count-plane gathers entirely.
    """
    agg, _masks, m_i, win, gidx, _lo = _scatter_core(
        tiles, tile_ids, qarr, T=T, CAP=CAP, C=C, exact_only=exact_only,
        seg_k=seg_k,
    )
    # top-R matched lanes, ascending (stable sort keeps lane order)
    order = jnp.argsort(1 - m_i, axis=1, stable=True)[:, :R]
    matched = jnp.take_along_axis(m_i, order, axis=1) != 0  # [B, R]
    rows = jnp.where(
        matched, jnp.take_along_axis(gidx, order, axis=1), jnp.int32(-1)
    )
    take = lambda r: jnp.take_along_axis(win[:, r, :], order, axis=1)
    ac_r = take(P_AC)
    an_r = take(P_AN)
    flags_r = take(P_FLAGS)
    # record segments within the gathered window: cumsum of the
    # SAME_PREV chain breaks. Matched lanes of one record can never
    # straddle the window start (lanes before lo are invalid), so
    # window-local segment ids group exactly like rec_id does.
    seg_id = jnp.cumsum(
        1 - ((win[:, P_FLAGS, :] & SAME_PREV) != 0).astype(jnp.int32),
        axis=1,
    )
    rec_r = jnp.take_along_axis(seg_id, order, axis=1)

    n_rows = gt.shape[0]
    safe = jnp.clip(rows, 0, n_rows - 1)
    m = mask[:, None, :]  # [B, 1, W]
    g = gt[safe] & m  # [B, R, W]
    pcw = lambda x: jnp.sum(
        jax.lax.population_count(x), axis=-1
    ).astype(jnp.int32)
    pc_gt = pcw(g)
    if with_counts:
        pc_call = pc_gt + pcw(gt2[safe] & m)
        pc_tok = pcw(tok1[safe] & m) + pcw(tok2[safe] & m)
        rc = jnp.where((flags_r & FLAG.AC_INFO) != 0, ac_r, pc_call)
    else:
        pc_call = pc_gt
        pc_tok = jnp.zeros_like(pc_gt)
        rc = ac_r
    rc = rc * matched

    # or_sel == (record index >= k0) for matched lanes — the segmented
    # forward/backward scans from parallel.mesh._local_selected
    rec_eff = jnp.where(matched, rec_r, jnp.int32(-2))
    first = matched & jnp.concatenate(
        [
            jnp.ones_like(matched[:, :1]),
            rec_eff[:, 1:] != rec_eff[:, :-1],
        ],
        axis=1,
    )
    c = jnp.cumsum(rc, axis=1)
    before = c - rc
    base = jax.lax.cummax(
        jnp.where(first, before, jnp.int32(-1)), axis=1
    )
    fwd_any = (c - base) > 0
    rc_f = jnp.flip(rc, axis=1)
    rec_f = jnp.flip(rec_eff, axis=1)
    first_f = jnp.flip(matched, axis=1) & jnp.concatenate(
        [
            jnp.ones_like(matched[:, :1]),
            rec_f[:, 1:] != rec_f[:, :-1],
        ],
        axis=1,
    )
    c_f = jnp.cumsum(rc_f, axis=1)
    base_f = jax.lax.cummax(
        jnp.where(first_f, c_f - rc_f, jnp.int32(-1)), axis=1
    )
    bwd_any = jnp.flip((c_f - base_f) > 0, axis=1)
    or_sel = matched & ((base > 0) | fwd_any | bwd_any)
    or_words = jax.lax.reduce(
        jnp.where(or_sel[:, :, None], g, jnp.int32(0)),
        np.int32(0),
        jax.lax.bitwise_or,
        dimensions=(1,),
    )  # [B, W]
    return agg, rows, pc_call, pc_tok, or_words


class SelectedResults:
    """run_selected_scattered outputs: QueryResults fields + the fused
    per-row plane reductions (aligned with ``rows``)."""

    __slots__ = (
        "exists",
        "call_count",
        "n_variants",
        "all_alleles_count",
        "n_matched",
        "overflow",
        "rows",
        "pc_call",
        "pc_tok",
        "or_words",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])


def run_selected_scattered(
    sindex: ScatterDeviceIndex,
    pindex,
    queries,
    mask_words: np.ndarray,
    *,
    window_cap: int | None = None,
    record_cap: int = 1024,
    with_counts: bool | None = None,
) -> SelectedResults:
    """Selected-samples query batch in ONE kernel dispatch per tier.

    ``pindex``: ops.plane_kernel.PlaneDeviceIndex of the SAME shard as
    ``sindex``. ``mask_words``: uint32 [B, W] per-query selected-sample
    masks (all-ones rows extract the full cohort). A query whose
    matched-row count exceeds min(record_cap, its tier cap) reports
    ``overflow`` (its plane outputs would be truncated) and must take
    the host path, exactly like the match kernel's window overflow.
    """
    enc = encode_queries(queries) if isinstance(queries, list) else queries
    T = sindex.tile
    window_cap = window_cap or T
    b = len(enc["chrom"])
    if with_counts is None:
        with_counts = bool(pindex.has_counts)
    W = pindex.n_words
    mask_words = np.ascontiguousarray(mask_words, dtype=np.uint32)
    if mask_words.shape != (b, W):
        raise ValueError(f"mask_words must be [{b}, {W}]")
    if b == 0:
        z = np.zeros(0, np.int32)
        return SelectedResults(
            exists=np.zeros(0, bool),
            call_count=z,
            n_variants=z,
            all_alleles_count=z,
            n_matched=z,
            overflow=np.zeros(0, bool),
            rows=np.zeros((0, 0), np.int32),
            pc_call=np.zeros((0, 0), np.int32),
            pc_tok=np.zeros((0, 0), np.int32),
            or_words=np.zeros((0, W), np.uint32),
        )
    lo, hi = _window_bounds(sindex, enc)
    q8, needs_host = pack_q8(enc, lo, hi)
    tile_ids_all = (lo // T).astype(np.int32)
    caps = _tier_caps(sindex, window_cap)
    width = hi - lo
    tier_of = np.searchsorted(np.asarray(caps), width, side="left")
    tier_of = np.minimum(tier_of, len(caps) - 1)
    single = (np.maximum(hi, lo + 1) - 1) // T <= tile_ids_all
    tier_of = np.where(single & (tier_of == 0), -1, tier_of)

    R_top = min(record_cap, caps[-1])
    agg = np.zeros((b, 8), np.int32)
    rows = np.full((b, R_top), -1, np.int32)
    pc_call = np.zeros((b, R_top), np.int32)
    pc_tok = np.zeros((b, R_top), np.int32)
    or_words = np.zeros((b, W), np.uint32)
    is_exact = enc["alt_mode"] == MODE_EXACT
    for ti, cap in [(-1, T)] + list(enumerate(caps)):
        in_tier = tier_of == ti
        R = min(record_cap, cap)
        for exact in (True, False):
            sel = np.flatnonzero(in_tier & (is_exact == exact))
            if not len(sel):
                continue
            # chunk host-side at CHUNK_SMALL granularity: every padding
            # slot in the fused program pays the R-row plane gather (not
            # just the cheap tile gather), so padding 65 queries to 2048
            # slots would cost ~30x the plane traffic — small fixed
            # chunks bound both the waste and the compile cache
            for a0 in range(0, len(sel), CHUNK_SMALL):
                ss = sel[a0 : a0 + CHUNK_SMALL]
                bb = len(ss)
                nslots = CHUNK_SMALL
                pad = (-bb) % nslots
                tid = np.concatenate(
                    [tile_ids_all[ss], np.zeros(pad, np.int32)]
                )
                qq = np.concatenate(
                    [q8[ss], np.zeros((pad, 8), np.int32)]
                )
                mm = np.concatenate(
                    [
                        mask_words[ss],
                        np.zeros((pad, W), np.uint32),
                    ]
                )
                t0 = time.perf_counter()
                a, r, pc, pt, ow = _selected_batch(
                    sindex.tiles,
                    pindex.gt,
                    pindex.gt2 if with_counts else pindex.gt,
                    pindex.tok1 if with_counts else pindex.gt,
                    pindex.tok2 if with_counts else pindex.gt,
                    jnp.asarray(tid),
                    jnp.asarray(qq),
                    jnp.asarray(mm.view(np.int32)),
                    T=T,
                    CAP=cap,
                    nslots=nslots,
                    C=1 if ti == -1 else None,
                    exact_only=exact,
                    R=R,
                    with_counts=with_counts,
                    seg_k=_static_seg_k(sindex),
                )
                seq = record_device_launch(
                    "plane",
                    seam="scatter",
                    tier=nslots,
                    specs_real=bb,
                    specs_padded=nslots,
                    launch_ms=(time.perf_counter() - t0) * 1e3,
                    program_key=(
                        # tile count and plane shapes are argument
                        # shapes: another dataset's planes compile a
                        # fresh program even at the same slot count
                        "scatter_selected",
                        int(sindex.tiles.shape[0]),
                        tuple(int(d) for d in pindex.gt.shape),
                        W, nslots, cap, R,
                        1 if ti == -1 else None, exact, with_counts,
                        _static_seg_k(sindex), T,
                    ),
                )
                t0 = time.perf_counter()
                a, r, pc, pt, ow = jax.device_get((a, r, pc, pt, ow))
                note_device_stage(
                    seq,
                    fetch_ms=(time.perf_counter() - t0) * 1e3,
                    fetch_bytes=sum(
                        np.asarray(v).nbytes for v in (a, r, pc, pt, ow)
                    ),
                )
                agg[ss] = np.asarray(a)[:bb]
                rows[ss, :R] = np.asarray(r)[:bb]
                pc_call[ss, :R] = np.asarray(pc)[:bb]
                pc_tok[ss, :R] = np.asarray(pt)[:bb]
                or_words[ss] = np.asarray(ow)[:bb].view(np.uint32)

    # a truncated row set would silently under-reduce the planes: the
    # per-tier R bound makes truncation part of the overflow contract
    r_of = np.where(
        tier_of == -1,
        min(record_cap, T),
        np.minimum(record_cap, np.asarray(caps)[np.maximum(tier_of, 0)]),
    )
    overflow = (
        (agg[:, 5] > 0)
        | (width > min(window_cap, caps[-1]))
        | needs_host
        | (agg[:, 4] > r_of)
    )
    return SelectedResults(
        exists=agg[:, 0] > 0,
        call_count=agg[:, 1],
        n_variants=agg[:, 2],
        all_alleles_count=agg[:, 3],
        n_matched=agg[:, 4],
        overflow=overflow,
        rows=rows,
        pc_call=pc_call,
        pc_tok=pc_tok,
        or_words=or_words,
    )


def warmup_index(
    sindex: ScatterDeviceIndex,
    pindex=None,
    *,
    window_cap: int = 2048,
    record_cap: int = 1024,
    batch_shapes: tuple = (CHUNK_SMALL, CHUNK),
) -> int:
    """Pre-compile every program serving can dispatch against this
    index: (single-tile fast tier + each window-cap tier) x
    (exact / non-exact) x each fixed batch shape, plus the fused
    match+planes program when ``pindex`` planes are resident.

    The soak tail was first-compiles, not queueing (BENCH_r04 config9
    attribution): a cold engine pays 1-2 s per novel (tier, shape)
    signature mid-request. Returns the number of programs compiled
    (cached signatures are near-free, so calling this twice is cheap).
    VERDICT r4 next #7.
    """
    import jax

    T = sindex.tile
    caps = _tier_caps(sindex, window_cap)
    n = 0
    outs = []
    for nslots in sorted(set(batch_shapes)):
        tid = jnp.zeros(nslots, jnp.int32)
        for ti, cap in [(-1, T)] + list(enumerate(caps)):
            C = 1 if ti == -1 else None
            for exact in (True, False):
                # Q_META bits 1-2 = alt mode; zero queries match
                # nothing (lo=hi=0) — only the compile matters
                from .query_pack import Q_META

                q8 = np.zeros((nslots, 8), np.int32)
                q8[:, Q_META] = (
                    (MODE_EXACT if exact else MODE_ANY_BASE) << 1
                )
                qd = jnp.asarray(q8)
                outs.append(
                    _scatter_batch(
                        sindex.tiles, tid, qd,
                        T=T, CAP=cap, nslots=nslots, C=C,
                        exact_only=exact,
                        seg_k=_static_seg_k(sindex),
                    )
                )
                n += 1
                if pindex is not None and nslots == CHUNK_SMALL:
                    # run_selected_scattered chunks at CHUNK_SMALL only
                    mask = jnp.zeros(
                        (nslots, pindex.n_words), jnp.int32
                    )
                    outs.append(
                        _selected_batch(
                            sindex.tiles,
                            pindex.gt,
                            pindex.gt2 if pindex.has_counts else pindex.gt,
                            pindex.tok1 if pindex.has_counts else pindex.gt,
                            pindex.tok2 if pindex.has_counts else pindex.gt,
                            tid, qd, mask,
                            T=T, CAP=cap, nslots=nslots, C=C,
                            exact_only=exact,
                            R=min(record_cap, cap),
                            with_counts=bool(pindex.has_counts),
                            seg_k=_static_seg_k(sindex),
                        )
                    )
                    n += 1
    # one sync flushes every queued compile+execute
    for leaf in jax.tree_util.tree_leaves(outs[-1:]):
        np.asarray(jax.device_get(leaf))
    return n


def _tier_caps(sindex: ScatterDeviceIndex, window_cap: int) -> list[int]:
    """Window-cap tiers: T, 4T, ... doubling-by-4 up to the engine's
    window cap (bounded by MAX_C gather width). Each tier is one
    compiled program; queries run in the smallest tier that fits their
    candidate window, so point queries never pay a wide bracket's
    gather."""
    T = sindex.tile
    # the top tier rounds UP to a tile multiple: the gather span is
    # C*T = cap + T lanes, and a non-multiple cap would leave a window
    # starting late in its first tile short of gathered lanes —
    # silently dropping matches. Queries wider than the caller's
    # window_cap still overflow (run_queries_scattered marks them),
    # the rounded tier only sizes the gather.
    top = min(-(-window_cap // T) * T, (sindex.MAX_C - 1) * T)
    caps = []
    c = T
    while c < top:
        caps.append(c)
        c *= 4
    caps.append(top)
    return caps


def _static_seg_k(sindex) -> int | None:
    """The K-shift static for this index, or None (scan form) when the
    longest record exceeds the cheap-shift regime."""
    k = getattr(sindex, "seg_k", None)
    return k if k is not None and k <= SEG_K_MAX else None


def _launch_tier(sindex, tile_ids, q8, *, cap, C=None, exact_only=False):
    """ASYNC device launch for one tier, chunk-padded; returns device
    handles (agg, masks) still shaped [ceil(b/nslots)*nslots, ...].
    Launch-then-fetch lets a batch that splits across tiers overlap its
    dispatches instead of paying one tunnel RTT per tier serially (r5:
    the fast-tier/exact split had halved serial qps vs r3's
    single-dispatch batches). ``C=1`` is the single-tile fast tier."""
    b = len(tile_ids)
    nslots = CHUNK_SMALL if b <= CHUNK_SMALL else CHUNK
    pad = (-b) % nslots
    if pad:
        tile_ids = np.concatenate([tile_ids, np.zeros(pad, np.int32)])
        q8 = np.concatenate([q8, np.zeros((pad, 8), np.int32)])
    nc = len(tile_ids) // nslots
    T = sindex.tile
    seg_k = _static_seg_k(sindex)
    t0 = time.perf_counter()
    if nc == 1:
        agg, masks = _scatter_batch(
            sindex.tiles,
            jnp.asarray(tile_ids),
            jnp.asarray(q8),
            T=T,
            CAP=cap,
            nslots=nslots,
            C=C,
            exact_only=exact_only,
            seg_k=seg_k,
        )
    else:
        agg, masks = _scatter_many(
            sindex.tiles,
            jnp.asarray(tile_ids.reshape(nc, nslots)),
            jnp.asarray(q8.reshape(nc, nslots, 8)),
            T=T,
            CAP=cap,
            nslots=nslots,
            C=C,
            exact_only=exact_only,
            seg_k=seg_k,
        )
        agg = agg.reshape(nc * nslots, 8)
        masks = masks.reshape(nc * nslots, -1)
    seq = record_device_launch(
        "scatter",
        seam="scatter",
        tier=nslots,
        specs_real=b,
        specs_padded=nc * nslots,
        launch_ms=(time.perf_counter() - t0) * 1e3,
        program_key=(
            # tiles is an argument array: a different tile count is a
            # different compiled program, so it joins the identity
            "scatter", int(sindex.tiles.shape[0]), nslots, nc, cap, C,
            exact_only, seg_k, T,
        ),
    )
    return agg, masks, seq



def run_queries_scattered(
    sindex: ScatterDeviceIndex,
    queries,
    *,
    window_cap: int | None = None,
    record_cap: int = 1024,
    with_rows: bool = True,
) -> QueryResults:
    """Execute a query batch via the scattered gather kernel.

    Same contract as ``run_queries_grouped``: aggregates + matched row
    ids, overflow marks queries needing the uncapped host path. Queries
    are split across window-cap tiers (``_tier_caps``) so each pays a
    gather proportional to its own candidate window; windows wider than
    the top tier overflow to host.
    """
    enc = encode_queries(queries) if isinstance(queries, list) else queries
    T = sindex.tile
    window_cap = window_cap or T
    b = len(enc["chrom"])
    if b == 0:
        z = np.zeros(0, np.int32)
        return QueryResults(
            exists=np.zeros(0, bool),
            call_count=z,
            n_variants=z,
            all_alleles_count=z,
            n_matched=z,
            overflow=np.zeros(0, bool),
            rows=np.zeros((0, record_cap), np.int32),
        )
    lo, hi = _window_bounds(sindex, enc)
    q8, needs_host = pack_q8(enc, lo, hi)
    tile_ids_all = (lo // T).astype(np.int32)
    caps = _tier_caps(sindex, window_cap)
    width = hi - lo
    # smallest tier that fits; oversize windows run (and overflow) in
    # the top tier so their aggregate slots still exist
    tier_of = np.searchsorted(np.asarray(caps), width, side="left")
    tier_of = np.minimum(tier_of, len(caps) - 1)
    # single-tile fast tier (tier -1): a window wholly inside one tile
    # needs a C=1 gather — half the HBM bytes of the base C=2 tier. At
    # point-query widths (a handful of rows) ~97% of queries qualify;
    # only tile-straddlers pay the 2-tile gather. Empty windows
    # (hi <= lo) qualify trivially.
    single = (np.maximum(hi, lo + 1) - 1) // T <= tile_ids_all
    tier_of = np.where(single & (tier_of == 0), -1, tier_of)

    agg = np.zeros((b, 8), np.int32)
    rows = (
        np.full((b, record_cap), -1, np.int32)
        if with_rows
        else np.zeros((b, 0), np.int32)
    )
    # each tier further splits exact-mode queries from the rest so the
    # dominant point-lookup shape compiles to the specialised
    # exact-only program (the symbolic-type chain dropped); a tier
    # whose queries are all one kind costs no extra dispatch
    is_exact = enc["alt_mode"] == MODE_EXACT
    # launch EVERY (tier, exact) split before fetching anything: the
    # dispatches overlap in flight, so a split batch pays ~one RTT
    # instead of one per split (tunnel-serial throughput)
    launched = []
    for ti, cap in [(-1, T)] + list(enumerate(caps)):
        in_tier = tier_of == ti
        for exact in (True, False):
            sel = np.flatnonzero(in_tier & (is_exact == exact))
            if not len(sel):
                continue
            a_dev, m_dev, seq = _launch_tier(
                sindex,
                tile_ids_all[sel],
                q8[sel],
                cap=cap,
                C=1 if ti == -1 else None,
                exact_only=exact,
            )
            launched.append((sel, a_dev, m_dev, seq))
    if launched:
        t_fetch = time.perf_counter()
        if with_rows:
            fetched = jax.device_get(
                [(a, m) for _s, a, m, _q in launched]
            )
        else:
            fetched = [
                (a, None)
                for a in jax.device_get(
                    [a for _s, a, _m, _q in launched]
                )
            ]
        # ONE combined readback returns every tier's handles together:
        # its wall time is each launch's fetch stage (they complete as
        # a unit), so every record in the batch carries it
        fetch_ms = (time.perf_counter() - t_fetch) * 1e3
        for (_sel, _ad, _md, seq), (a, masks) in zip(launched, fetched):
            note_device_stage(
                seq,
                fetch_ms=fetch_ms,
                fetch_bytes=np.asarray(a).nbytes
                + (np.asarray(masks).nbytes if masks is not None else 0),
            )
        for (sel, _ad, _md, _q), (a, masks) in zip(launched, fetched):
            agg[sel] = np.asarray(a)[: len(sel)]
            if with_rows:
                base_rows = tile_ids_all[sel].astype(np.int64) * T
                rows[sel] = _rows_from_masks(
                    np.asarray(masks)[: len(sel)], base_rows, record_cap
                )

    # overflow honours the CALLER's window_cap (the engine's on-device
    # promise), not the tile-rounded top tier — answers for widths in
    # (window_cap, rounded_top] would be exact but must stay consistent
    # with the XLA kernel's overflow contract
    overflow = (
        (agg[:, 5] > 0)
        | (width > min(window_cap, caps[-1]))
        | needs_host
    )
    return QueryResults(
        exists=agg[:, 0] > 0,
        call_count=agg[:, 1],
        n_variants=agg[:, 2],
        all_alleles_count=agg[:, 3],
        n_matched=agg[:, 4],
        overflow=overflow,
        rows=rows,
    )


@partial(
    jax.jit,
    static_argnames=("T", "CAP", "nslots", "C", "exact_only", "seg_k"),
)
def _scatter_many(
    tiles, tile_ids, qarr, *, T, CAP, nslots, C=None, exact_only=False,
    seg_k=None,
):
    """lax.map over fixed-size chunks (one compiled program regardless
    of logical batch size, same trick as the grouped kernel)."""

    def run(args):
        tids, qs = args
        return _scatter_batch(
            tiles, tids, qs, T=T, CAP=CAP, nslots=nslots, C=C,
            exact_only=exact_only,
            seg_k=seg_k,
        )

    return jax.lax.map(run, (tile_ids, qarr))


@partial(
    jax.jit,
    static_argnames=("T", "CAP", "nslots", "k", "C", "exact_only", "seg_k"),
)
def _probe_rep(
    tiles, tile_ids, qarr, *, T, CAP, nslots, k, C=None, exact_only=False,
    seg_k=None,
):
    """k serialized batch executions inside ONE dispatch.

    The carry must be a REAL data dependency: the grouped-kernel probe's
    always-zero word trick fails here because without an opaque
    pallas_call boundary XLA constant-folds ``carry + 0``, proves the
    loop invariant, and hoists the single batch out of the scan (first
    observed as a negative differencing delta on v5e). Instead the
    carry drifts by the (unknowable) call_count, kept in gather range
    by a static modulo — iteration VALUES are garbage by design; the
    scalar result is timing ballast only, never assert on it."""
    n_tiles = jnp.int32(tiles.shape[0])

    def body(carry, _):
        agg, _masks = _scatter_batch(
            tiles, carry, qarr, T=T, CAP=CAP, nslots=nslots, C=C,
            exact_only=exact_only, seg_k=seg_k,
        )
        return (carry + agg[0, 1]) % n_tiles, agg[0, 1]

    _, outs = jax.lax.scan(body, tile_ids, None, length=k)
    return jnp.sum(outs)


def _probe_one_tier(
    sindex, tile_ids, q8, *, cap, C, iters, exact_only=False
) -> tuple[float, int]:
    """Chain-differenced (seconds per batch, bytes gathered per batch)
    for ONE compiled tier batch (tile_ids/q8 already nslots-sized)."""
    import time as _time

    T = sindex.tile
    nslots = len(tile_ids)
    td = jnp.asarray(tile_ids)
    qd = jnp.asarray(q8)
    k1 = 8
    k2 = k1 + iters

    def timed(k, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = _time.perf_counter()
            np.asarray(
                jax.device_get(
                    _probe_rep(
                        sindex.tiles,
                        td,
                        qd,
                        T=T,
                        CAP=cap,
                        nslots=nslots,
                        k=k,
                        C=C,
                        exact_only=exact_only,
                        seg_k=_static_seg_k(sindex),
                    )
                )
            )
            best = min(best, _time.perf_counter() - t0)
        return best

    # auto-escalate the chain length until the differencing signal
    # CLEARS the transport-jitter floor — merely-positive deltas are
    # noise: a ~2 ms delta under ~ms tunnel jitter once measured a
    # physically impossible 1.48x-of-HBM-roofline gather rate (r5
    # BENCH run 1, config2). 20 ms is ~10x the observed jitter on this
    # tunnel; a genuinely faster kernel still measures — it just rides
    # a longer chain.
    JITTER_FLOOR_S = 0.020
    MAX_CHAIN_S = 4.0  # wall budget per timed chain — the real ceiling
    delta = 0.0
    k_iters = iters
    while True:
        k2 = k1 + k_iters
        timed(k1, reps=1)
        t2_warm = timed(k2, reps=1)
        delta = timed(k2) - timed(k1)
        if delta >= JITTER_FLOOR_S:
            iters = k_iters
            break
        if t2_warm > MAX_CHAIN_S:
            # a multi-second chain whose delta still hides under the
            # floor means per-batch time < floor/k — genuinely
            # unmeasurable on this transport
            raise RuntimeError(
                f"device_time_probe: unmeasurable — {k_iters}-batch "
                f"signal below the jitter floor ({delta * 1e3:.3f} ms)"
            )
        k_iters *= 4
    n_gather_tiles = C if C is not None else cap // T + 1
    gathered = nslots * N_PACKED * n_gather_tiles * T * 4
    return delta / iters, gathered


def device_time_probe(
    sindex: ScatterDeviceIndex,
    queries,
    *,
    window_cap: int | None = None,
    iters: int = 128,
) -> tuple[float, int]:
    """(seconds per batch on-device, HBM bytes gathered per batch) by
    two-chain differencing through ``device_get`` — RTT, dispatch and
    transfer cancel exactly (methodology: time a k1-long and a k2-long
    serialized in-dispatch chain and difference; this backend's
    block_until_ready returns early, so wall-per-dispatch would lie).

    Times the SAME tier mix serving runs: queries whose window sits in
    one tile are timed in the C=1 fast tier (split exact/non-exact like
    serving), the rest in the windowed C-tile tier, and the reported
    per-batch figure is the share-weighted combination (each tier
    probed as a full batch of its own queries, cycled to batch size)."""
    enc = encode_queries(queries) if isinstance(queries, list) else queries
    T = sindex.tile
    # round UP like _tier_caps does for serving, so the probe times the
    # same gather width serving actually performs
    cap = min(-(-(window_cap or T) // T) * T, (sindex.MAX_C - 1) * T)
    lo, hi = _window_bounds(sindex, enc)
    q8, _nh = pack_q8(enc, lo, hi)
    tile_ids = (lo // T).astype(np.int32)
    b = len(tile_ids)
    nslots = CHUNK_SMALL if b <= CHUNK_SMALL else CHUNK
    single = (np.maximum(hi, lo + 1) - 1) // T <= tile_ids
    is_exact = enc["alt_mode"] == MODE_EXACT

    def cycle(sel):
        reps = -(-nslots // len(sel))
        idx = np.tile(sel, reps)[:nslots]
        return tile_ids[idx], q8[idx]

    per = 0.0
    gathered = 0.0
    for mask, C, tier_cap in (
        (single, 1, T),
        (~single, None, cap),
    ):
        for exact in (True, False):
            sel = np.flatnonzero(mask & (is_exact == exact))
            share = len(sel) / b
            if share == 0.0:
                continue
            t_ids, qs = cycle(sel)
            p, g = _probe_one_tier(
                sindex,
                t_ids,
                qs,
                cap=tier_cap,
                C=C,
                iters=iters,
                exact_only=exact,
            )
            per += share * p
            gathered += share * g
    return per, int(gathered)
