"""The TPU variant-query kernel.

This replaces the reference's entire splitQuery -> performQuery fan-out
(reference: lambda/splitQuery/lambda_function.py 10kb-window cross-product,
lambda/performQuery/search_variants.py per-region bcftools scan) with ONE
compiled program: a batch of queries is answered by a vmap'd fixed-depth
binary search over the sorted columnar index followed by a fixed-width
windowed gather and fully vectorised predicate evaluation.

Design notes (TPU/XLA):
- All shapes are static: the candidate window per query is ``window_cap``
  rows starting at the searchsorted lower bound; a query whose hit range
  exceeds the window reports ``overflow`` and the host falls back to the
  CPU oracle for that query (two-phase execution keeps the common case
  compiled).
- The binary search is a fixed-iteration bisection (no data-dependent
  control flow), vmapped over the query batch.
- int32 everywhere (TPU-native); no int64, no x64 mode. Chromosome
  segmentation is a 27-entry offsets table indexed by chromosome code, so
  the search key is plain ``pos``.
- "AN once per matching record" (reference :244-250) is computed with a
  windowed segmented first-match scan over ``rec_id`` — cumsum plus an
  intra-window searchsorted, no scatter.
"""

from __future__ import annotations

import math
import os
import threading
import time
import warnings
from contextlib import contextmanager
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index.columnar import (
    FLAG,
    INT32_MAX,
    VariantIndexShard,
    fnv1a32,
    pack_prefix16,
    prefix_mask,
)
from ..telemetry import note_device_stage, record_device_launch
from ..utils.chrom import chromosome_code
from ..utils.trace import graft_launch_span, span

# variant_type codes for the type-dispatch mode
VT_DEL, VT_INS, VT_DUP, VT_DUP_TANDEM, VT_CNV, VT_OTHER = range(6)
_VT_CODES = {
    "DEL": VT_DEL,
    "INS": VT_INS,
    "DUP": VT_DUP,
    "DUP:TANDEM": VT_DUP_TANDEM,
    "CNV": VT_CNV,
}

# alt matching modes
MODE_EXACT, MODE_ANY_BASE, MODE_TYPE = range(3)

def __getattr__(name: str):
    """Module back-compat properties (PEP 562): ``N_LAUNCHES`` — one
    per jitted query-batch dispatch, the perf_smoke evidence that
    fused dispatch and the response cache actually collapse launches —
    now reads the device flight recorder (telemetry.py). The old
    module-global ``N_LAUNCHES += 1`` was an unlocked read-modify-write
    racing across request threads on real accelerators; the recorder's
    lock owns the increment, and the name stays readable here.
    ``tools/check_launch_recording.py`` rejects any reintroduced
    direct counter assignment."""
    if name == "N_LAUNCHES":
        from ..telemetry import flight_recorder

        return flight_recorder.kernel_launches
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


@dataclass
class QuerySpec:
    """One Beacon variant query, coordinates 1-based inclusive."""

    chrom: str
    start_min: int
    start_max: int
    end_min: int
    end_max: int
    reference_bases: str | None = None
    alternate_bases: str | None = None
    variant_type: str | None = None
    variant_min_length: int = 0
    variant_max_length: int = -1


def encode_queries(
    queries: list[QuerySpec], shard_ids: list[int] | None = None
) -> dict[str, np.ndarray]:
    """Host-side encoding of a query batch into device arrays.

    ``shard_ids`` targets each query at one shard segment of a
    :class:`FusedDeviceIndex` (the ``shard`` field selects the row of
    its 2D ``chrom_offsets``); omitted for single-shard indexes."""
    b = len(queries)
    enc = {
        "chrom": np.zeros(b, np.int32),
        "start_min": np.zeros(b, np.int32),
        "start_max": np.zeros(b, np.int32),
        "end_min": np.zeros(b, np.int32),
        "end_max": np.zeros(b, np.int32),
        "ref_wild": np.zeros(b, np.bool_),
        "ref_hash": np.zeros(b, np.int32),
        "ref_len": np.zeros(b, np.int32),
        "alt_mode": np.zeros(b, np.int32),
        "alt_hash": np.zeros(b, np.int32),
        "alt_len": np.zeros(b, np.int32),
        "vt_code": np.zeros(b, np.int32),
        "vprefix": np.zeros((b, 4), np.uint32),
        "vprefix_mask": np.zeros((b, 4), np.uint32),
        "min_len": np.zeros(b, np.int32),
        "max_len": np.zeros(b, np.int32),
    }
    if shard_ids is not None:
        enc["shard"] = np.asarray(shard_ids, dtype=np.int32)
    for i, q in enumerate(queries):
        enc["chrom"][i] = chromosome_code(q.chrom)
        enc["start_min"][i] = q.start_min
        enc["start_max"][i] = q.start_max
        enc["end_min"][i] = q.end_min
        enc["end_max"][i] = q.end_max
        wild = q.reference_bases is None or q.reference_bases == "N"
        enc["ref_wild"][i] = wild
        if not wild:
            enc["ref_hash"][i] = fnv1a32(q.reference_bases.encode())
            enc["ref_len"][i] = len(q.reference_bases)
        if q.alternate_bases is None:
            enc["alt_mode"][i] = MODE_TYPE
            vt = q.variant_type
            enc["vt_code"][i] = _VT_CODES.get(vt, VT_OTHER)
            # '<' + str(vt): variant_type=None yields '<None', which matches
            # no alt — the reference's exact formatting artifact
            # (performQuery/search_variants.py:54)
            vpref = ("<" + str(vt)).encode()
            enc["vprefix"][i] = pack_prefix16(vpref)
            enc["vprefix_mask"][i] = prefix_mask(min(len(vpref), 16))
        elif q.alternate_bases == "N":
            enc["alt_mode"][i] = MODE_ANY_BASE
        else:
            enc["alt_mode"][i] = MODE_EXACT
            enc["alt_hash"][i] = fnv1a32(q.alternate_bases.encode())
            enc["alt_len"][i] = len(q.alternate_bases)
        enc["min_len"][i] = q.variant_min_length
        enc["max_len"][i] = (
            int(INT32_MAX) if q.variant_max_length < 0 else q.variant_max_length
        )
    return enc


# per-column padding fill values (pos/rec_end/rec_id = INT32_MAX so no
# searchsorted window ever selects a padding row)
_PAD_FILLS = {
    "pos": INT32_MAX,
    "rec_end": INT32_MAX,
    "ref_len": 0,
    "alt_len": 0,
    "ref_hash": 0,
    "alt_hash": 0,
    "ref_repeat_k": -1,
    "flags": 0,
    "ac": 0,
    "an": 0,
    "rec_id": INT32_MAX,
    "alt_prefix": 0,
}


def pad_columns(
    cols: dict[str, np.ndarray], n: int, n_pad: int
) -> dict[str, np.ndarray]:
    """``_PAD_FILLS``-padded copies of a device-column dict (single
    shard or stacked) — THE one pad-and-fill implementation, so the
    per-shard and fused indexes can never drift on pad-row sentinels."""
    if n > n_pad:
        raise ValueError(f"{n} rows > pad target {n_pad}")
    out = {}
    for name, fill in _PAD_FILLS.items():
        col = cols[name]
        padded = np.full((n_pad,) + col.shape[1:], fill, dtype=col.dtype)
        padded[:n] = col
        out[name] = padded
    return out


def pad_shard_columns(
    shard: VariantIndexShard, n_pad: int
) -> dict[str, np.ndarray]:
    """Host-side padded column dict (incl. chrom_offsets), numpy only."""
    out = pad_columns(shard.cols, shard.n_rows, n_pad)
    out["chrom_offsets"] = shard.chrom_offsets.astype(np.int32)
    return out


def padded_rows(n: int, pad_unit: int) -> int:
    return max(pad_unit, ((n + pad_unit - 1) // pad_unit) * pad_unit)


def window_hint_for(chrom_offsets, floor: int = 256) -> int:
    """Power-of-two window bound from a chromosome segment table.

    A query's candidate range is always contained in ONE (shard,
    chromosome) segment — the bisection never leaves ``[seg_lo,
    seg_hi)`` — so the widest segment bounds every ``hi - lo`` the
    kernel can produce. Launching with this instead of the engine-wide
    ``window_cap`` shrinks the per-lane gather (the launch's compute)
    without ever adding an overflow. Power-of-two with a floor, so the
    hint (a static program dimension) is stable across rebuilds."""
    offs = np.asarray(chrom_offsets)
    widest = (
        int(np.diff(offs, axis=-1).max(initial=0)) if offs.size else 0
    )
    hint = floor
    while hint < widest:
        hint *= 2
    return hint


def bisect_iters(n_pad: int) -> int:
    """Fixed bisection depth covering a padded row count."""
    return max(1, math.ceil(math.log2(n_pad + 1)))


class DeviceIndex:
    """A VariantIndexShard's device-bound columns, padded to a static shape.

    Padding rows carry pos=INT32_MAX so no searchsorted window ever selects
    them; ``chrom_offsets`` keeps real row extents.
    """

    PAD_UNIT = 8192

    def __init__(self, shard: VariantIndexShard, pad_unit: int | None = None):
        pad_unit = pad_unit or self.PAD_UNIT
        n = shard.n_rows
        n_pad = padded_rows(n, pad_unit)
        self.n_rows = n
        self.n_padded = n_pad
        self.shard = shard
        self.arrays = {
            k: jnp.asarray(v)
            for k, v in pad_shard_columns(shard, n_pad).items()
        }
        self.n_iters = bisect_iters(n_pad)
        #: measured widest-hit-range bound (see window_hint_for):
        #: run_queries clamps its window_cap to this
        self.window_hint = window_hint_for(shard.chrom_offsets)


class FusedDeviceIndex:
    """ALL warm shards stacked into one device index for fused dispatch.

    Shard rows stay contiguous and in their original order
    (``index.columnar.stack_shard_columns``); ``chrom_offsets`` becomes
    a ``[k, 27]`` per-shard segment table and each encoded query carries
    a ``shard`` id selecting its row. One ``_query_batch`` launch then
    answers (shard, query) pairs against any mix of shards — a
    k-dataset query costs ONE device launch instead of k, and the
    serving micro-batcher coalesces queries for *different* datasets
    into the same launch (previously each dataset's accumulator
    launched separately).

    Row ids come back as absolute stacked ids; ``shard_base[sid]``
    maps them back to shard-local ids for host materialisation. The
    index holds its own column copy (the per-shard device indexes —
    XLA gather or scatter-tile — stay alive for fallback and
    single-target paths), so the engine only builds it when >= 2
    shards are warm and the stacked row count fits
    ``fused_max_rows`` — budget notes in DEPLOYMENT.md.
    """

    PAD_UNIT = 8192

    #: flight-recorder program family (the L0 subclass overrides —
    #: tools/check_launch_recording.py pins the override literal)
    flight_family = "fused"

    def __init__(
        self, shards: list[VariantIndexShard], pad_unit: int | None = None
    ):
        from ..index.columnar import stack_shard_columns

        cols, chrom_offsets, base = stack_shard_columns(shards)
        n = int(base[-1])
        n_pad = padded_rows(n, pad_unit or self.PAD_UNIT)
        arrays = {
            k: jnp.asarray(v)
            for k, v in pad_columns(cols, n, n_pad).items()
        }
        arrays["chrom_offsets"] = jnp.asarray(chrom_offsets)
        self.arrays = arrays
        self.n_rows = n
        self.n_padded = n_pad
        self.n_iters = bisect_iters(n_pad)
        self.n_shards = len(shards)
        #: shard count as compiled (the L0 subclass pads the segment
        #: table, so its program identity uses the padded count)
        self.n_shards_padded = len(shards)
        self.shard_base = base  # int64[k+1]
        #: ragged-window bound generalised from the L0 mini-index
        #: (ISSUE 17): the widest (shard, chromosome) segment of the
        #: stack bounds every candidate range, so record-heavy
        #: launches stop paying the engine-wide window_cap gather
        #: width (the L0 subclass overrides with its tail-shard bound)
        self.window_hint = window_hint_for(chrom_offsets)

    def to_local_rows(self, rows: np.ndarray, sid: int) -> np.ndarray:
        """Stacked row ids (already -1-filtered) -> shard-local ids."""
        return rows.astype(np.int64) - int(self.shard_base[sid])


class L0DeviceIndex(FusedDeviceIndex):
    """The delta-tail mini-index — the LSM ``memtable -> L0`` tier
    (ISSUE 15), stacked over a key's standing delta shards.

    Same layout as :class:`FusedDeviceIndex` (``stack_shard_columns``
    over the tail shards — small rows, contiguous per-shard spans, a
    per-shard segment table row selected by the encoded query's
    ``shard`` id), with one addition: the ``[k, 27]`` segment table is
    padded up to a fixed shard-count tier (all-zero rows — every
    segment empty, so a pad shard can never match). The tail grows by
    one shard per delta publish, and without the pad each rebuild
    would be a novel ``[k, 27]`` operand shape — a fresh XLA compile
    per publish, exactly the mid-request-compile tail the batch tiers
    exist to prevent. With it, successive tail builds inside one tier
    reuse ONE compiled program, and the engine pre-warms the batch
    tiers at build time (off the request path).

    Launches against this index report to the flight recorder as the
    ``fused_l0`` family, so /device/status and ``device.launches``
    attribute tail serving separately from the base fused stack."""

    flight_family = "fused_l0"

    #: pad-to tiers for the segment table's shard axis
    SHARD_TIERS = (8, 16, 32, 64, 128, 256, 512)

    def __init__(
        self, shards: list[VariantIndexShard], pad_unit: int | None = None
    ):
        super().__init__(shards, pad_unit=pad_unit)
        k = self.n_shards
        k_pad = next((t for t in self.SHARD_TIERS if k <= t), k)
        co = np.asarray(self.arrays["chrom_offsets"])
        if k_pad != k:
            pad = np.zeros((k_pad - k, co.shape[1]), dtype=co.dtype)
            co = np.concatenate([co, pad])
            self.arrays["chrom_offsets"] = jnp.asarray(co)
        #: host copy of the padded segment table: the per-key composite
        #: (CompositeL0DeviceIndex) shifts and restacks it without a
        #: device round-trip per rebuild
        self.chrom_offsets_host = co
        self.n_shards_padded = k_pad
        # a tail shard's candidate window can never exceed its own
        # row count, so the launch may run with a window sized to the
        # LARGEST tail shard instead of the engine-wide window_cap —
        # the per-lane gather (the launch's compute) shrinks ~8-16x
        # for typical tails. Power-of-two with a floor, so the hint
        # (a static program dimension) is stable across builds.
        widest = max((s.n_rows for s in shards), default=1)
        hint = 256
        while hint < widest:
            hint *= 2
        self.window_hint = hint

    #: finer batch-tier ladder than the global BATCH_TIERS: a deep-tail
    #: query submits one spec per covered tail shard (typically 9-32),
    #: and padding those to the global 64 tier quadruples the launch's
    #: compute. The L0 program is tiny (window_hint-sized gathers over
    #: <=8192 rows), so the extra compiled tiers cost little and the
    #: engine pre-warms them at build time.
    batch_tiers = (8, 16, 32, 64, 512, 2048)


class CompositeL0DeviceIndex:
    """Per-key L0 blocks assembled into ONE serving index (ISSUE 20).

    The per-(dataset, vcf) L0 refactor keeps a standing
    :class:`L0DeviceIndex` block per covered key, so a delta publish to
    key A re-stacks (host gather + device upload) ONLY key A's block.
    Serving still holds the single-launch contract — ``l0_pre_rows``
    answers every covered tail row across keys with ONE coalesced
    launch — and this class is what squares the two: the blocks'
    device-resident row columns concatenate device-side (HBM-to-HBM, no
    host restack of untouched keys), each block's padded ``[k, 27]``
    segment table shifts by the block's row offset and stacks along the
    shard axis (a pad shard's all-zero row shifts to ``[off, off)`` —
    still empty, still unmatchable), and composite shard ids index the
    stacked table. It exposes the same attribute surface ``run_queries``
    reads (``arrays`` / ``n_iters`` / ``n_shards_padded`` /
    ``window_hint`` / ``flight_family`` / ``batch_tiers`` /
    ``to_local_rows``), so the launch path cannot tell it from a
    monolithic stack; the class name rides the program identity, so its
    programs never alias the monolithic index's."""

    flight_family = "fused_l0"
    batch_tiers = L0DeviceIndex.batch_tiers

    def __init__(self, blocks: list[L0DeviceIndex]):
        if not blocks:
            raise ValueError("CompositeL0DeviceIndex needs >= 1 block")
        parts: dict[str, list] = {}
        co_parts: list[np.ndarray] = []
        base_parts: list[np.ndarray] = []
        #: composite sid of each block's shard 0 (block order preserved)
        self.block_sid_offsets: list[int] = []
        row_off = 0
        sid_off = 0
        for b in blocks:
            self.block_sid_offsets.append(sid_off)
            co = b.chrom_offsets_host
            co_parts.append((co + row_off).astype(co.dtype, copy=False))
            sb = np.asarray(b.shard_base, dtype=np.int64)
            # pad shards (sid past the block's real count) clamp to the
            # block's end base: they are never routed, but the base
            # array must stay index-aligned with the stacked table
            clamp = np.minimum(np.arange(b.n_shards_padded), b.n_shards)
            base_parts.append(sb[clamp] + row_off)
            for name, arr in b.arrays.items():
                if name != "chrom_offsets":
                    parts.setdefault(name, []).append(arr)
            row_off += b.n_padded
            sid_off += b.n_shards_padded
        self.arrays = {
            name: (vals[0] if len(vals) == 1 else jnp.concatenate(vals))
            for name, vals in parts.items()
        }
        self.arrays["chrom_offsets"] = jnp.asarray(np.concatenate(co_parts))
        self.blocks = list(blocks)
        self.n_rows = sum(b.n_rows for b in blocks)
        self.n_padded = row_off
        self.n_iters = bisect_iters(row_off)
        self.n_shards = sum(b.n_shards for b in blocks)
        self.n_shards_padded = sid_off
        self.shard_base = np.concatenate(
            base_parts + [np.asarray([row_off], dtype=np.int64)]
        )
        self.window_hint = max(b.window_hint for b in blocks)

    def to_local_rows(self, rows: np.ndarray, sid: int) -> np.ndarray:
        """Stacked row ids (already -1-filtered) -> shard-local ids."""
        return rows.astype(np.int64) - int(self.shard_base[sid])


@dataclass
class QueryResults:
    """Per-query aggregates + matched row ids (numpy, host-side)."""

    exists: np.ndarray  # bool[B]
    call_count: np.ndarray  # int32[B] — sum of AC over matched rows
    n_variants: np.ndarray  # int32[B] — matched rows with AC != 0
    all_alleles_count: np.ndarray  # int32[B] — AN summed once per record
    n_matched: np.ndarray  # int32[B]
    overflow: np.ndarray  # bool[B] — window_cap exceeded, host fallback
    rows: np.ndarray  # int32[B, record_cap] global row ids, -1 padded
    # genotype-plane outputs (mesh plane program only; None on every
    # match-only path): per-row masked popcounts aligned with ``rows``
    # and the grp>=k0 sample-hit OR — the materialize_response
    # ``fused=(pc_call, pc_tok, or_words)`` triple, per query
    pc_call: np.ndarray | None = None  # int32[B, record_cap]
    pc_tok: np.ndarray | None = None  # int32[B, record_cap]
    or_words: np.ndarray | None = None  # int32[B, plane_words]


def _bisect(pos, target, lo0, hi0, n_iters, *, upper: bool):
    """Fixed-depth bisection over pos[lo0:hi0].

    upper=False: first index with pos[idx] >= target (lower bound).
    upper=True:  first index with pos[idx] >  target (upper bound) — used
    instead of lower_bound(target+1) so target=INT32_MAX cannot wrap.
    """

    def body(carry, _):
        lo, hi = carry
        # once lo == hi the search is done; further probes would read
        # pos[mid] outside [lo0, hi0) and walk past the segment end
        active = lo < hi
        mid = (lo + hi) // 2
        less = pos[mid] <= target if upper else pos[mid] < target
        return (
            jnp.where(active & less, mid + 1, lo),
            jnp.where(active & ~less, mid, hi),
        ), None

    (lo, _), _ = jax.lax.scan(body, (lo0, hi0), None, length=n_iters)
    return lo


def _query_one(arrays, q, *, window_cap: int, record_cap: int, n_iters: int):
    pos = arrays["pos"]
    offsets = arrays["chrom_offsets"]
    n = pos.shape[0]

    if offsets.ndim == 2:
        # fused multi-shard index: the query's shard id selects its
        # segment table row; the bisection then never leaves that
        # shard's contiguous row span
        seg_lo = offsets[q["shard"], q["chrom"]]
        seg_hi = offsets[q["shard"], q["chrom"] + 1]
    else:
        seg_lo = offsets[q["chrom"]]
        seg_hi = offsets[q["chrom"] + 1]
    lo = _bisect(pos, q["start_min"], seg_lo, seg_hi, n_iters, upper=False)
    hi = _bisect(pos, q["start_max"], seg_lo, seg_hi, n_iters, upper=True)

    idxs = lo + jnp.arange(window_cap, dtype=jnp.int32)
    valid = idxs < hi
    safe = jnp.clip(idxs, 0, n - 1)

    g = lambda name: arrays[name][safe]

    rec_end = g("rec_end")
    end_ok = (q["end_min"] <= rec_end) & (rec_end <= q["end_max"])

    ref_ok = q["ref_wild"] | (
        (g("ref_hash") == q["ref_hash"]) & (g("ref_len") == q["ref_len"])
    )

    alt_len = g("alt_len")
    len_ok = (q["min_len"] <= alt_len) & (alt_len <= q["max_len"])

    flags = g("flags")
    f = lambda bit: (flags & bit) != 0
    sym = f(FLAG.SYMBOLIC)
    k = g("ref_repeat_k")
    ref_len = g("ref_len")

    # symbolic-prefix match: first L bytes of alt equal '<'+variant_type
    ap = arrays["alt_prefix"][safe]  # [W, 4] uint32
    pm = jnp.all(
        ((ap ^ q["vprefix"][None, :]) & q["vprefix_mask"][None, :]) == 0, axis=1
    )

    del_ok = jnp.where(sym, pm | f(FLAG.CN0), alt_len < ref_len)
    ins_ok = jnp.where(sym, pm, alt_len > ref_len)
    dup_ok = jnp.where(
        sym, pm | (f(FLAG.CN_PREFIX) & ~f(FLAG.CN0) & ~f(FLAG.CN1)), k >= 2
    )
    dupt_ok = jnp.where(sym, pm | f(FLAG.CN2), k == 2)
    cnv_ok = jnp.where(
        sym,
        pm | f(FLAG.CN_PREFIX) | f(FLAG.DEL_PREFIX) | f(FLAG.DUP_PREFIX),
        f(FLAG.DOT) | (k >= 1),
    )
    other_ok = sym & pm
    type_ok = jnp.select(
        [
            q["vt_code"] == VT_DEL,
            q["vt_code"] == VT_INS,
            q["vt_code"] == VT_DUP,
            q["vt_code"] == VT_DUP_TANDEM,
            q["vt_code"] == VT_CNV,
        ],
        [del_ok, ins_ok, dup_ok, dupt_ok, cnv_ok],
        other_ok,
    )
    exact_ok = (g("alt_hash") == q["alt_hash"]) & (alt_len == q["alt_len"])
    anyb_ok = f(FLAG.SINGLE_BASE)
    alt_ok = jnp.where(
        q["alt_mode"] == MODE_EXACT,
        exact_ok,
        jnp.where(q["alt_mode"] == MODE_ANY_BASE, anyb_ok, type_ok),
    )

    matched = valid & end_ok & ref_ok & len_ok & alt_ok

    ac = g("ac")
    call_count = jnp.sum(jnp.where(matched, ac, 0))
    n_variants = jnp.sum(matched & (ac != 0))
    n_matched = jnp.sum(matched)

    # AN once per record with >= 1 matched row: segmented first-match scan
    rec_w = jnp.where(valid, g("rec_id"), INT32_MAX)
    m_i = matched.astype(jnp.int32)
    cums = jnp.cumsum(m_i)
    seg_start = jnp.searchsorted(rec_w, rec_w, side="left").astype(jnp.int32)
    before_all = cums - m_i  # matched strictly before row i
    before_seg = jnp.where(seg_start > 0, cums[jnp.clip(seg_start - 1, 0)], 0)
    first_match = matched & ((before_all - before_seg) == 0)
    all_alleles = jnp.sum(jnp.where(first_match, g("an"), 0))

    # matched row ids, ascending, -1 padded, capped at record_cap
    marked = jnp.where(matched, idxs, INT32_MAX)
    topk = jax.lax.sort(marked)[:record_cap]
    rows = jnp.where(topk == INT32_MAX, -1, topk)

    return {
        "exists": call_count > 0,
        "call_count": call_count,
        "n_variants": n_variants,
        "all_alleles_count": all_alleles,
        "n_matched": n_matched,
        "overflow": (hi - lo) > window_cap,
        "rows": rows,
    }


def _query_batch_impl(arrays, enc, *, window_cap, record_cap, n_iters):
    fn = partial(
        _query_one,
        arrays,
        window_cap=window_cap,
        record_cap=record_cap,
        n_iters=n_iters,
    )
    return jax.vmap(fn)(enc)


_JIT_STATICS = ("window_cap", "record_cap", "n_iters")

#: the jitted query-batch entry (tools/check_launch_recording.py pins
#: run_queries as its one caller)
_query_batch = partial(jax.jit, static_argnames=_JIT_STATICS)(
    _query_batch_impl
)

#: same program, but the encoded query-batch buffers (positional arg 1)
#: are DONATED: steady-state serving uploads a fresh encode dict per
#: launch, and without donation XLA double-buffers every one of them in
#: HBM next to its output. The index arrays (arg 0) are persistent and
#: never donated. Leaves whose shape/dtype match no output are simply
#: freed rather than aliased — that is still the win — so the advisory
#: "donated buffers were not usable" warning is noise here.
_query_batch_donated = partial(
    jax.jit, static_argnames=_JIT_STATICS, donate_argnums=(1,)
)(_query_batch_impl)


@contextmanager
def _quiet_donation():
    """Silence the advisory unusable-donation warning around a donated
    launch — a module-level filter would be undone by test harnesses
    that reset warning state per test."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        yield


def _donate_uploads() -> bool:
    """Process default for encode-buffer donation on the upload path
    (``BEACON_DONATE_UPLOADS``; on unless explicitly disabled)."""
    return os.environ.get(
        "BEACON_DONATE_UPLOADS", "1"
    ).lower() not in ("0", "false", "off", "no")


# the LEGACY fixed batch-size tiers (<=8x padding overhead, 4 programs
# total); batches beyond the top tier run at their exact size (bulk
# benchmark shapes, not serving). Kept as the documented baseline and
# the BEACON_TIER_LADDER=legacy escape hatch — live tier selection
# consults the process TierLadder below (ISSUE 17).
BATCH_TIERS = (8, 64, 512, 2048)


class TierLadder:
    """The batch-size tier ladder every padding seam consults.

    PR 14's flight recorder showed the coarse ``BATCH_TIERS`` ladder
    wasting up to 7 of 8 padded lanes at tier boundaries (worst
    (family, tier) cells ~0.86), and PR 15's private finer ladder on
    the L0 mini-index proved finer rungs pay for themselves: the extra
    compiled programs are warmed off the request path and the padding
    waste collapses. This class promotes that ladder to a single
    process-wide source of truth — ``run_queries`` batch padding, the
    mesh tier's replicated batch padding and per-device slice tiers,
    and the engine/dispatch warmup loops all read the SAME instance,
    so a rung can never exist for serving without being pre-compiled
    (``tools/check_launch_recording.py`` lints the parity).

    Rungs are fit to measured traffic: :meth:`fit` reads the
    recorder's per-(family, tier) real-vs-padded histogram and splits
    any rung whose waste exceeds ``WASTE_SPLIT`` — or the operator
    pins the ladder with ``BEACON_TIER_LADDER`` (comma-separated rungs,
    or ``legacy`` for the old 4-tier ladder)."""

    #: the L0-proven default (PR 15): fills the 8->64 gap where the
    #: recorder saw the worst serving-tier waste
    DEFAULT_RUNGS = (8, 16, 32, 64, 512, 2048)
    #: per-device slice rungs at or under this are pre-compiled by the
    #: mesh tier's warmup; larger rungs are bulk shapes that compile at
    #: first use like the legacy ladder's top tiers
    MESH_WARM_CAP = 64
    #: a (family, tier) histogram cell wasting more than this fraction
    #: of its padded lanes earns a finer rung below it
    WASTE_SPLIT = 0.5
    #: fit() never grows the ladder beyond this many rungs (each rung
    #: is a compiled program per family — warmup time and program
    #: cache both scale with it)
    MAX_RUNGS = 12
    #: families whose recorded padding carries the n_dev slice
    #: replication factor (``specs_padded = c_slot * n_dev``) — their
    #: waste measures batch SKEW across owning devices, which a finer
    #: batch rung cannot fix (the slice ladder already floors at 1), so
    #: fit() must not chase it; left unchecked it splits every warmup's
    #: own skewed mesh launches into ever-smaller rungs
    FIT_SKIP_FAMILIES = frozenset({"mesh_sliced", "plane"})

    __slots__ = ("rungs", "source")

    def __init__(self, rungs, source: str = "default"):
        clean = tuple(sorted({int(r) for r in rungs if int(r) > 0}))
        if not clean:
            raise ValueError("TierLadder needs at least one rung")
        self.rungs = clean
        self.source = source

    def tier_for(self, b: int):
        """Smallest rung holding a batch of ``b``; None past the top
        rung (bulk batches run at their exact size)."""
        return next((t for t in self.rungs if b <= t), None)

    @property
    def slice_rungs(self) -> tuple:
        """Per-device slice shape tiers: the ladder plus a 1-floor —
        the whole point of slicing is that each device sees
        ~batch/n_dev queries, so padding every slice back up to the
        8-floor would erase the win for the common pod fan-out."""
        return self.rungs if self.rungs[0] == 1 else (1,) + self.rungs

    def mesh_warm_rungs(self) -> tuple:
        """The slice rungs MeshDispatchTier pre-compiles (all rungs <=
        MESH_WARM_CAP; larger slices are bulk shapes outside the
        serving path, same exposure as the legacy ladder)."""
        return tuple(
            t for t in self.slice_rungs if t <= self.MESH_WARM_CAP
        )

    @classmethod
    def from_env(cls, env=None) -> "TierLadder":
        """The env-pinned ladder (``BEACON_TIER_LADDER``: comma rungs
        or ``legacy``), else the default. Malformed values fall back
        to the default — a bad knob must not take serving down."""
        raw = (env if env is not None else os.environ).get(
            "BEACON_TIER_LADDER", ""
        ).strip()
        if not raw:
            return cls(cls.DEFAULT_RUNGS, source="default")
        if raw.lower() == "legacy":
            return cls(BATCH_TIERS, source="env")
        try:
            return cls(
                [int(p) for p in raw.split(",") if p.strip()],
                source="env",
            )
        except ValueError:
            return cls(cls.DEFAULT_RUNGS, source="default")

    def fit(self, pad_tier_hist: dict) -> "TierLadder":
        """A traffic-fit refinement of this ladder: any (family, tier)
        cell of the recorder's real-vs-padded histogram wasting more
        than ``WASTE_SPLIT`` of its padded lanes earns the half-rung
        below its tier (repeatedly halving would chase noise; one
        split per observed-bad rung per fit keeps the ladder bounded
        and the warmup cheap). Slice-replicated families
        (``FIT_SKIP_FAMILIES``) and splits below the ladder floor are
        ignored, so successive fits converge — warming the fitted
        ladder never creates cells that would re-split it. Rung count
        is capped at MAX_RUNGS, keeping the worst offenders."""
        splits = []
        for (family, tier), (real, padded) in pad_tier_hist.items():
            tier = int(tier)
            if family in self.FIT_SKIP_FAMILIES:
                continue
            half = tier // 2
            # never split below the ladder floor: waste at the bottom
            # rung is the floor's known cost, not a mis-fit ladder, and
            # sub-floor rungs would leak into every consumer of
            # active_ladder() (a 3-query batch must keep padding to 8)
            if not padded or tier not in self.rungs or half < self.rungs[0]:
                continue
            waste = 1.0 - real / padded
            if waste > self.WASTE_SPLIT and half not in self.rungs:
                splits.append((waste, half))
        if not splits:
            return self
        splits.sort(reverse=True)
        budget = max(0, self.MAX_RUNGS - len(self.rungs))
        extra = []
        for _waste, rung in splits:
            if rung in extra:
                continue
            if len(extra) >= budget:
                break
            extra.append(rung)
        if not extra:
            return self
        return TierLadder(self.rungs + tuple(extra), source="fit")


_LADDER_LOCK = threading.Lock()
_ACTIVE_LADDER: TierLadder | None = None


def active_ladder() -> TierLadder:
    """The process tier ladder — THE single source every padding seam
    (run_queries, the mesh batch/slice tiers, dispatch fan-out padding,
    and all warmup loops) consults."""
    global _ACTIVE_LADDER
    with _LADDER_LOCK:
        if _ACTIVE_LADDER is None:
            _ACTIVE_LADDER = TierLadder.from_env()
        return _ACTIVE_LADDER


def set_active_ladder(ladder: TierLadder | None) -> None:
    """Install (or with None, reset to env/default) the process
    ladder. Callers own re-warming: a rung that reaches serving
    without a warmup compile is exactly what the warmup-ladder lint
    exists to catch."""
    global _ACTIVE_LADDER
    with _LADDER_LOCK:
        _ACTIVE_LADDER = ladder


def refit_active_ladder(recorder=None) -> TierLadder:
    """Traffic-fit the process ladder from the flight recorder's
    per-(family, tier) histogram — the engine calls this at the top of
    ``warmup()``, so every fitted rung is pre-compiled in the same
    warmup phase. An env-pinned ladder (``BEACON_TIER_LADDER``) is the
    operator's word and never refit."""
    global _ACTIVE_LADDER
    if recorder is None:
        from ..telemetry import flight_recorder as recorder
    with _LADDER_LOCK:
        ladder = _ACTIVE_LADDER or TierLadder.from_env()
        if ladder.source != "env":
            ladder = ladder.fit(recorder.pad_tier_histogram())
        _ACTIVE_LADDER = ladder
        return ladder


class PendingQueryResults:
    """An in-flight query batch: the launch has been dispatched, the
    device-to-host fetch is deferred to :meth:`fetch`.

    JAX dispatch is asynchronous — ``_query_batch`` returns device
    futures — so splitting launch from fetch lets the serving layer
    overlap host work (encoding batch i+1, materialising batch i-1)
    with the device execution of batch i instead of blocking the
    launcher thread inside ``device_get``."""

    __slots__ = ("_out", "_b", "flight_seq")

    def __init__(self, out, b: int, flight_seq: int | None = None):
        self._out = out
        self._b = b
        #: the launch's flight-recorder record: fetch attaches its
        #: device-readback wall time there (serving's launch/fetch
        #: stages run on different threads, so the seq is the handle)
        self.flight_seq = flight_seq

    def fetch(self) -> QueryResults:
        t0 = time.perf_counter()
        out = jax.device_get(self._out)
        note_device_stage(
            self.flight_seq,
            fetch_ms=(time.perf_counter() - t0) * 1e3,
            fetch_bytes=sum(
                np.asarray(v).nbytes for v in out.values()
            ),
        )
        self._out = None  # free the device buffers promptly
        b = self._b
        extra = {
            k: np.asarray(out[k])[:b]
            for k in ("pc_call", "pc_tok", "or_words")
            if k in out
        }
        return QueryResults(
            exists=np.asarray(out["exists"])[:b],
            call_count=np.asarray(out["call_count"])[:b],
            n_variants=np.asarray(out["n_variants"])[:b],
            all_alleles_count=np.asarray(out["all_alleles_count"])[:b],
            n_matched=np.asarray(out["n_matched"])[:b],
            overflow=np.asarray(out["overflow"])[:b],
            rows=np.asarray(out["rows"])[:b],
            **extra,
        )


class ReadyQueryResults:
    """Already-fetched results behind the PendingQueryResults contract
    (kernels that execute synchronously, e.g. the scatter tile path)."""

    __slots__ = ("_res",)

    def __init__(self, res: QueryResults):
        self._res = res

    def fetch(self) -> QueryResults:
        return self._res


def run_queries(
    dindex: DeviceIndex,
    queries: list[QuerySpec] | dict[str, np.ndarray],
    *,
    window_cap: int = 2048,
    record_cap: int = 1024,
    async_fetch: bool = False,
):
    """Execute a query batch against one device index (single-shard
    ``DeviceIndex`` or stacked ``FusedDeviceIndex``; fused batches must
    arrive pre-encoded with their ``shard`` ids).

    The batch pads up to a fixed size tier (``BATCH_TIERS``, repeating
    query 0 — always semantically inert, outputs trimmed) so the
    compiled-program cache is keyed by a handful of shapes instead of
    every micro-batch size the serving batcher can emit: un-padded, a
    16-client soak compiled a fresh program per novel batch size
    mid-request — the r4 soak tail (VERDICT r4 next #7).

    ``async_fetch=True`` returns a :class:`PendingQueryResults` right
    after dispatch (launch/fetch overlap); default blocks and returns
    :class:`QueryResults`.
    """
    enc = (
        encode_queries(queries) if isinstance(queries, list) else queries
    )
    b = int(enc["chrom"].shape[0])
    # ragged-window clamp: the index's measured widest-hit-range bound
    # (never adds an overflow — see window_hint_for). Applied HERE, the
    # one choke point, so warmup and serving can't compile different
    # window shapes for the same index.
    window_cap = min(
        window_cap, getattr(dindex, "window_hint", window_cap)
    )
    # an index may carry its own (finer) tier ladder — the L0
    # mini-index does, so a per-tail-shard spec batch is not padded to
    # the global 64 tier; everything else pads to the process ladder
    tiers = getattr(dindex, "batch_tiers", None)
    if tiers is None:
        tiers = active_ladder().rungs
    tier = next((t for t in tiers if b <= t), None)
    if b and tier and tier != b:
        enc = {
            k: np.concatenate(
                [v, np.repeat(v[:1], tier - b, axis=0)]
            )
            for k, v in enc.items()
        }
    padded = tier if (b and tier) else b
    donate = _donate_uploads()
    with span("kernel.run_queries") as sp:
        t0 = time.perf_counter()
        enc_dev = {k: jnp.asarray(v) for k, v in enc.items()}
        batch_fn = _query_batch_donated if donate else _query_batch
        with _quiet_donation():
            out = batch_fn(
                dindex.arrays,
                enc_dev,
                window_cap=window_cap,
                record_cap=record_cap,
                n_iters=dindex.n_iters,
            )
        launch_ms = (time.perf_counter() - t0) * 1e3
        # ONE flight-recorder seam per launch: counters, the launch
        # ring, and compile tracking (a first-seen (program, shape)
        # key below is an XLA compile — jit traces inside this call).
        # The family comes off the index (fused vs fused_l0): L0
        # tail launches are attributable separately from base-stack
        # launches on every recorder surface.
        family = getattr(dindex, "flight_family", "fused")
        seq = record_device_launch(
            family,
            seam="kernel",
            tier=padded,
            specs_real=b,
            specs_padded=padded,
            launch_ms=launch_ms,
            donated=len(enc_dev) if donate else 0,
            program_key=(
                "xla_gather",
                # the donated entry is a distinct compiled program
                # (separate jit cache), so donation is program identity
                "don" if donate else "nodon",
                type(dindex).__name__,
                dindex.n_padded,
                # a fused stack rebuild can keep n_padded while its
                # [k, 27] segment table grows a row — a distinct XLA
                # program, so the (padded) shard count is part of the
                # identity; the L0 index pads it to a tier exactly so
                # this key stays stable across tail builds
                getattr(
                    dindex,
                    "n_shards_padded",
                    getattr(dindex, "n_shards", 1),
                ),
                dindex.n_iters,
                padded,
                window_cap,
                record_cap,
            ),
        )
        sp.note(batch=b)
        graft_launch_span(
            sp,
            elapsed_ms=launch_ms,
            family=family,
            tier=padded,
            specs=b,
        )
    pending = PendingQueryResults(out, b, seq)
    if async_fetch:
        return pending
    return pending.fetch()
