"""Shared query-side packing for the device kernels (kernel-neutral).

The 8-word device query encoding, the host-side searchsorted window
bounds, the symbolic-prefix flag staging, and the packed-match-mask
unpacker were born inside the (since-deleted, r5) grouped Pallas
kernel and were extracted here when the scattered gather kernel
replaced it in serving (VERDICT r3 weak #8); the serving path
(``scatter_kernel``/``engine``) imports only this module.

Encoding recap (vs the legacy 24-word layout): symbolic-type prefix
matching is index-side flag bits (PM_*), start_min/start_max are
replaced by host-searchsorted lo/hi, chrom is host-only, and length
fields are bit-packed with lossless clamps — queries whose fields
cannot be represented exactly are host-flagged (``needs_host``) and
take the uncapped host path, never a silently-wrong device verdict.
"""

from __future__ import annotations

import numpy as np

from ..index.columnar import INT32_MAX
from .kernel import MODE_TYPE, VT_OTHER

(
    Q_LO,
    Q_HI,
    Q_END_MIN,
    Q_END_MAX,
    Q_REF_HASH,
    Q_ALT_HASH,
    Q_META,  # ref_wild(1) | alt_mode(2) | vt_code(3) | ref_len(13) | min_len(13)
    Q_LENS,  # alt_len(16) | max_len(16)
) = range(8)
N_QWORDS = 8

# extra flag bits staged into the device matrix's flags row only (never
# persisted): per-row symbolic-prefix matches. '<DEL'/'<DUP' prefixes
# reuse the shard's own FLAG.DEL_PREFIX/DUP_PREFIX bits; these cover
# the rest.
PM_INS = 1 << 16  # alt starts with '<INS'
PM_DUPT = 1 << 17  # alt starts with '<DUP:TANDEM'
PM_CNV = 1 << 18  # alt starts with '<CNV'


def stage_symbolic_flags(
    flags: np.ndarray, alt_prefix: np.ndarray
) -> np.ndarray:
    """Return ``flags`` with the PM_* symbolic-prefix bits staged from
    the 16-byte alt prefixes — the device-matrix-only bits every kernel
    index builder needs. One shared implementation so kernels can never
    drift on prefix semantics."""
    from ..index.columnar import pack_prefix16, prefix_mask

    out = flags.astype(np.int64, copy=True)
    for prefix, bit in (
        (b"<INS", PM_INS),
        (b"<DUP:TANDEM", PM_DUPT),
        (b"<CNV", PM_CNV),
    ):
        want = pack_prefix16(prefix)
        m = prefix_mask(min(len(prefix), 16))
        hit = (((alt_prefix ^ want) & m) == 0).all(axis=1)
        out |= np.where(hit, np.int64(bit), 0)
    return out


def window_bounds(
    index, enc: dict[str, np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorised host-side searchsorted window bounds per query.

    ``index`` is any device index exposing ``pos_host`` (the sorted
    position column) and ``offsets_host`` (per-chromosome row offsets);
    B·log N numpy searchsorted is microseconds."""
    pos = index.pos_host
    offs = index.offsets_host
    b = len(enc["chrom"])
    chrom = enc["chrom"].astype(np.int64)
    lo = np.zeros(b, np.int64)
    hi = np.zeros(b, np.int64)
    for c in np.unique(chrom):
        m = chrom == c
        a, e = int(offs[c]), int(offs[c + 1])
        seg = pos[a:e]
        lo[m] = a + np.searchsorted(seg, enc["start_min"][m], side="left")
        hi[m] = a + np.searchsorted(seg, enc["start_max"][m], side="right")
    return lo, hi


def pack_q8(
    enc: dict[str, np.ndarray], lo: np.ndarray, hi: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Compact 8-word device encoding + host-fallback flags.

    Returns (q8[B, 8] int32, needs_host[B] bool). ``needs_host`` marks
    queries the compact encoding cannot represent exactly — VT_OTHER
    symbolic-type matching (the '<'+str(vt) artifact for arbitrary type
    strings, host-resolved) and out-of-range length fields; the caller
    folds it into ``overflow`` so those queries take the uncapped host
    path, never a silently-wrong device verdict.
    """
    b = len(enc["chrom"])
    q = np.zeros((b, N_QWORDS), np.int64)
    q[:, Q_LO] = lo
    q[:, Q_HI] = hi
    q[:, Q_END_MIN] = enc["end_min"]
    q[:, Q_END_MAX] = enc["end_max"]
    q[:, Q_REF_HASH] = enc["ref_hash"]
    q[:, Q_ALT_HASH] = enc["alt_hash"]
    ref_len = np.minimum(enc["ref_len"].astype(np.int64), 0x1FFF)
    min_len = np.minimum(enc["min_len"].astype(np.int64), 0x1FFF)
    q[:, Q_META] = (
        enc["ref_wild"].astype(np.int64)
        | (enc["alt_mode"].astype(np.int64) << 1)
        | (np.minimum(enc["vt_code"].astype(np.int64), 7) << 3)
        | (ref_len << 6)
        | (min_len << 19)
    )
    # alt_len: row alt_len is an UNCLAMPED int32 column (columnar.py
    # stores len(alt) verbatim — multi-kb insertions are legal rows), so
    # only the query-side fields are range-limited. max_len uses 0xFFFF
    # as the unbounded sentinel (decoded to INT32_MAX in-kernel);
    # anything the 16-bit fields cannot represent exactly is host-flagged.
    alt_len = np.minimum(enc["alt_len"].astype(np.int64), 0xFFFF)
    unbounded = enc["max_len"].astype(np.int64) >= INT32_MAX
    max_len = np.where(
        unbounded, 0xFFFF, np.minimum(enc["max_len"].astype(np.int64), 0xFFFE)
    )
    q[:, Q_LENS] = alt_len | (max_len << 16)
    q8 = (q & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    needs_host = (
        ((enc["alt_mode"] == MODE_TYPE) & (enc["vt_code"] == VT_OTHER))
        # >= the clamp values (not >): the scattered kernel clamps the
        # ROW length columns to the same widths, so a query sitting
        # exactly at a clamp could otherwise hash-match a longer row
        | (enc["ref_len"] >= 0x1FFF)
        | (enc["min_len"] > 0x1FFF)
        | (enc["alt_len"] >= 0xFFFF)
        | (~unbounded & (enc["max_len"].astype(np.int64) > 0xFFFE))
    )
    return q8, needs_host


def rows_from_masks(
    masks: np.ndarray,
    base_rows: np.ndarray,
    record_cap: int,
) -> np.ndarray:
    """Packed per-query match masks -> [B, record_cap] global row ids
    (-1 padded), one vectorised unpackbits for the whole batch. Bit l
    of word w == window lane w*16 + l (the shared wire format)."""
    b, nw = masks.shape
    halves = np.ascontiguousarray(masks.astype(np.uint16))
    bits = np.unpackbits(
        halves.view(np.uint8).reshape(b, nw * 2), axis=1, bitorder="little"
    )  # [B, 2W], bit l of word w == window lane w*16+l
    qi_idx, lane_idx = np.nonzero(bits)
    counts = bits.sum(axis=1).astype(np.int64)
    cum = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int64)
    k = np.arange(len(lane_idx)) - np.repeat(cum, counts)
    keep = k < record_cap
    rows = np.full((b, record_cap), -1, np.int32)
    rows[qi_idx[keep], k[keep]] = (
        base_rows[qi_idx[keep]] + lane_idx[keep]
    ).astype(np.int32)
    return rows


# legacy aliases (the helpers kept their historical underscore names at
# several call sites while they lived in pallas_kernel)
_window_bounds = window_bounds
_rows_from_masks = rows_from_masks
