"""Cross-device hit-row gather for the mesh-sharded fused index.

The pod-local dispatch tier (``parallel/mesh.py MeshFusedIndex``) answers
each query on exactly ONE device — the owner of the query's dataset
shard — and every other device contributes zeros. Combining those
per-device partials into a replicated result is a gather in sum
clothing: the owner's block plus (n-1) zero blocks. This module provides
that combine in two implementations behind one call:

- **TPU**: a Pallas ring pass built on ``pltpu.make_async_remote_copy``
  (the right-permute remote-DMA idiom): each step every device DMAs its
  current block to its right neighbour over ICI and accumulates what it
  received, so after n-1 steps every device holds the full sum without
  ever staging the [B, R] row block through XLA's all-reduce scratch.
- **portable** (CPU/GPU/tests): ``lax.all_gather`` + a sum over the
  gathered device axis — semantically identical, runs anywhere
  shard_map does (the forced-host-device CI mesh included).

Both run INSIDE a shard_map body; the caller picks the implementation
at trace time (``jax.default_backend()``), never inside the program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def gather_partials_portable(x, axis: str):
    """Sum per-device partial blocks into a replicated block.

    ``x``: the device-local partial (owner carries real values, everyone
    else zeros). Uses ``all_gather`` + sum rather than ``psum`` so the
    gathered-axis layout mirrors the TPU ring pass (and the replication
    checker's view of both paths matches: neither is inferable, the
    caller runs under ``check_rep=False``)."""
    g = jax.lax.all_gather(x, axis)  # [n_dev, ...]
    return jnp.sum(g, axis=0)


def _ring_step_kernel(x_ref, out_ref, send_sem, recv_sem, *, axis: str):
    """One ring rotation: DMA my block to my right neighbour's output
    buffer and wait for the left neighbour's block to land in mine."""
    from jax.experimental.pallas import tpu as pltpu

    me = jax.lax.axis_index(axis)
    n = jax.lax.axis_size(axis)
    right = jax.lax.rem(me + 1, n)
    copy = pltpu.make_async_remote_copy(
        src_ref=x_ref,
        dst_ref=out_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=(right,),
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    copy.start()
    copy.wait()


@functools.lru_cache(maxsize=None)
def _ring_step_fn(axis: str, shape: tuple, dtype_name: str):
    import jax.numpy as _jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dtype = _jnp.dtype(dtype_name)
    return pl.pallas_call(
        functools.partial(_ring_step_kernel, axis=axis),
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )


def gather_partials_tpu(x, axis: str, n_dev: int):
    """TPU ring combine of per-device partials via async remote DMA.

    After step k every device holds the block that started k positions
    to its left; accumulating each arrival reconstructs the full sum on
    every device in n-1 ICI hops — the Pallas analogue of the portable
    all_gather+sum, with the DMA schedule explicit."""
    if n_dev <= 1:
        return x
    step = _ring_step_fn(axis, tuple(x.shape), str(x.dtype))
    acc = x
    blk = x
    for _ in range(n_dev - 1):
        blk = step(blk)
        acc = acc + blk
    return acc


def gather_partials(x, axis: str, n_dev: int, *, impl: str = "portable"):
    """Dispatch on the implementation chosen at trace time.

    ``impl``: ``"pallas"`` (TPU remote-DMA ring) or ``"portable"``
    (all_gather+sum). The caller decides from ``jax.default_backend()``
    OUTSIDE the shard_map body — backend probing does not trace."""
    if impl == "pallas":
        return gather_partials_tpu(x, axis, n_dev)
    return gather_partials_portable(x, axis)


def gather_partials_many(xs, axis: str, n_dev: int, *, impl: str = "portable"):
    """ONE combined gather pass over several partial blocks.

    The mesh plane program produces four per-query blocks to reassemble
    (hit rows, masked call/token popcounts, the sample-hit OR words) —
    all int32, all sharing the leading batch axis. Ring-combining them
    separately costs 4x(n-1) ICI hops and 4 semaphore pairs per step;
    concatenating along the trailing axis first makes it ONE ring pass
    (n-1 hops) over a single contiguous block, then a free split. The
    portable path concatenates too, so both implementations see the
    identical block layout."""
    xs = tuple(xs)
    if len(xs) == 1:
        return (gather_partials(xs[0], axis, n_dev, impl=impl),)
    # split points are static shape arithmetic (python ints, never
    # tracers — jnp.split needs concrete indices inside the trace)
    splits, acc = [], 0
    for x in xs[:-1]:
        acc += int(x.shape[-1])
        splits.append(acc)
    cat = jnp.concatenate(xs, axis=-1)
    out = gather_partials(cat, axis, n_dev, impl=impl)
    return tuple(jnp.split(out, splits, axis=-1))
