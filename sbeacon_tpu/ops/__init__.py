from .kernel import (
    DeviceIndex,
    QueryResults,
    QuerySpec,
    encode_queries,
    run_queries,
)
from .pallas_kernel import (
    HAVE_PALLAS,
    PallasDeviceIndex,
    run_queries_pallas,
)

__all__ = [
    "DeviceIndex",
    "HAVE_PALLAS",
    "PallasDeviceIndex",
    "QueryResults",
    "QuerySpec",
    "encode_queries",
    "run_queries",
    "run_queries_pallas",
]
