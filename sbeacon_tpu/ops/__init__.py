from .kernel import (
    DeviceIndex,
    FusedDeviceIndex,
    L0DeviceIndex,
    QueryResults,
    QuerySpec,
    ReadyQueryResults,
    encode_queries,
    run_queries,
)
from .scatter_kernel import (
    ScatterDeviceIndex,
    run_queries_scattered,
)


def make_device_index(
    shard, *, window: int | None = None, pad_unit: int | None = None
):
    """Device index for serving: the scattered C-tile gather kernel on
    real TPU backends, the XLA gather kernel elsewhere.

    The scattered kernel replaced the round-2 grouped Pallas kernel as
    the serving default after measuring BOTH regimes on v5e: at
    1000-Genomes scale (2e7 rows) sparse queries collapse the grouped
    kernel's tile sharing (0.83M q/s vs 26.8M q/s scattered, 32x);
    on small dense corpora the grouped kernel's device-only rate is
    higher (~128M vs ~41M q/s) but end-to-end serving throughput is
    equal-or-better for the gather path (and 3x on record granularity)
    because transport dominates — see ROUND3_NOTES.md. Real corpora
    are 2e7-scale, which decides the default. ``window`` only sizes
    the XLA fallback index;
    the scattered kernel applies the engine's window_cap per BATCH
    (tier split in run_queries_scattered), so the index needs no
    build-time window."""
    import jax

    if jax.default_backend() == "tpu":
        return ScatterDeviceIndex(shard)
    return DeviceIndex(shard, pad_unit=pad_unit)


def run_queries_auto(
    index,
    queries,
    *,
    window_cap: int = 2048,
    record_cap: int = 1024,
    async_fetch: bool = False,
    sample_masks=None,
    mask_counts=None,
):
    """Dispatch a query batch to whichever kernel the index was built
    for — one call site for the engine and the micro-batcher.

    ``async_fetch=True`` returns an object with ``.fetch() ->
    QueryResults`` immediately after the launch is dispatched so the
    caller can overlap host work with device execution (the scatter
    tile kernels execute synchronously and return already-fetched
    results behind the same contract).

    ``sample_masks``/``mask_counts`` arm the mesh tier's genotype-plane
    program (per-query sample masks reduced on the owning device) and
    are only meaningful for a plane-stacked MeshFusedIndex — passing
    them for any other index family is a caller bug and raises."""
    if isinstance(index, ScatterDeviceIndex):
        if sample_masks is not None:
            raise ValueError(
                "sample_masks only ride the mesh plane program"
            )
        res = run_queries_scattered(
            index, queries, window_cap=window_cap, record_cap=record_cap
        )
        return ReadyQueryResults(res) if async_fetch else res
    # mesh-sharded fused index (parallel.mesh.MeshFusedIndex): duck-typed
    # on its dispatch method so ops never imports parallel (no cycle) —
    # the micro-batcher coalesces onto it exactly like a FusedDeviceIndex
    mesh_run = getattr(index, "run_mesh_queries", None)
    if mesh_run is not None:
        kwargs = {}
        if sample_masks is not None:
            kwargs.update(
                sample_masks=sample_masks, mask_counts=mask_counts
            )
        return mesh_run(
            queries,
            window_cap=window_cap,
            record_cap=record_cap,
            async_fetch=async_fetch,
            **kwargs,
        )
    if sample_masks is not None:
        raise ValueError("sample_masks only ride the mesh plane program")
    return run_queries(
        index,
        queries,
        window_cap=window_cap,
        record_cap=record_cap,
        async_fetch=async_fetch,
    )


__all__ = [
    "DeviceIndex",
    "FusedDeviceIndex",
    "L0DeviceIndex",
    "QueryResults",
    "QuerySpec",
    "ReadyQueryResults",
    "encode_queries",
    "make_device_index",
    "run_queries",
    "run_queries_auto",
]
