from .kernel import (
    DeviceIndex,
    QueryResults,
    QuerySpec,
    encode_queries,
    run_queries,
)

__all__ = [
    "DeviceIndex",
    "QueryResults",
    "QuerySpec",
    "encode_queries",
    "run_queries",
]
