from .kernel import (
    DeviceIndex,
    QueryResults,
    QuerySpec,
    encode_queries,
    run_queries,
)
from .pallas_kernel import (
    HAVE_PALLAS,
    PallasDeviceIndex,
    run_queries_grouped,
    run_queries_pallas,
)


def make_device_index(
    shard, *, window: int | None = None, pad_unit: int | None = None
):
    """Device index for serving: the grouped Pallas window-scan kernel on
    real TPU backends (tile-shared DMA + in-kernel row materialisation),
    the XLA gather kernel elsewhere (Pallas interpret mode is far slower
    than XLA on CPU). ``window`` should match the engine's window_cap so
    candidate ranges the config promises to answer on-device actually
    stay on-device (capped at 2048 lanes to bound the kernel's VMEM)."""
    import jax

    if HAVE_PALLAS and jax.default_backend() == "tpu":
        w = min(window or 512, 2048)
        w = max(128, ((w + 127) // 128) * 128)
        return PallasDeviceIndex(shard, window=w)
    return DeviceIndex(shard, pad_unit=pad_unit)


def run_queries_auto(
    index, queries, *, window_cap: int = 2048, record_cap: int = 1024
) -> QueryResults:
    """Dispatch a query batch to whichever kernel the index was built
    for — one call site for the engine and the micro-batcher."""
    if isinstance(index, PallasDeviceIndex):
        return run_queries_grouped(
            index, queries, window_cap=window_cap, record_cap=record_cap
        )
    return run_queries(
        index, queries, window_cap=window_cap, record_cap=record_cap
    )


__all__ = [
    "DeviceIndex",
    "HAVE_PALLAS",
    "PallasDeviceIndex",
    "QueryResults",
    "QuerySpec",
    "encode_queries",
    "make_device_index",
    "run_queries",
    "run_queries_auto",
    "run_queries_grouped",
    "run_queries_pallas",
]
