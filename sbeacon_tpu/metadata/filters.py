"""Beacon filtering-terms -> SQL compiler.

Re-implements the reference's filter classification and SQL generation
(reference: shared_resources/athena/filter_functions.py:66-133,
`new_entity_search_conditions`) against the local sqlite metadata store:

Each filter id is classified as
1. an own-column of the queried entity  -> outer WHERE predicate,
2. ``Entity.column`` of a linked entity -> relations-join subquery,
3. otherwise an ontology term           -> descendant-expanded terms_index
                                           + relations-join subquery,
and the join subqueries are INTERSECTed, so multiple filters mean set
intersection over entity ids. All values travel as ``?`` parameters
(the reference's Athena execution-parameters sanitisation).
"""

from __future__ import annotations

from .entities import ENTITY_COLUMNS, RELATION_ID_COLUMN
from .ontology import OntologyStore

# filter ids of the form 'Individual.sex' name a linked entity class
# (reference queried_athena_models keys are the class names)
_CLASS_TO_KIND = {
    "Analysis": "analyses",
    "Biosample": "biosamples",
    "Individual": "individuals",
    "Cohort": "cohorts",
    "Dataset": "datasets",
    "Run": "runs",
}


class FilterError(ValueError):
    pass


def _comparison(f: dict) -> tuple[str, object, bool]:
    """(operator, value, is_numeric) for a filter
    (reference _get_comparrison_fragment). Numeric values keep their type
    so the SQL layer can CAST the TEXT column and compare numerically."""
    if "value" not in f:
        raise FilterError("filter missing 'value'")
    if "operator" not in f:
        raise FilterError("filter missing 'operator'")
    value = f["value"]
    operator = f["operator"]
    numeric = isinstance(value, (int, float)) and not isinstance(value, bool)
    if numeric:
        operator = "!=" if operator == "!" else operator
        if operator not in ("=", "<", ">", "<=", ">=", "!="):
            raise FilterError(f"unsupported numeric operator {operator!r}")
    else:
        if operator not in ("=", "!"):
            raise FilterError(f"unsupported string operator {operator!r}")
        operator = "LIKE" if operator == "=" else "NOT LIKE"
        value = str(value)
    return operator, value, numeric


def _predicate(column: str, op: str, numeric: bool) -> str:
    if numeric:
        # columns are TEXT; CAST for a true numeric compare, and exclude
        # absent ('') values so they don't coerce to 0
        return f"({column} != '' AND CAST({column} AS NUMERIC) {op} ?)"
    return f"{column} {op} ?"


def entity_search_parts(
    filters: list[dict],
    id_type: str,
    default_scope: str,
    *,
    ontology: OntologyStore | None = None,
):
    """Classify filters into structured SQL parts — the single source of
    truth for filter semantics: (outer_predicates, outer_params,
    join_subqueries, join_params, relation_id_column).

    ``entity_search_conditions`` assembles the reference-shaped WHERE
    fragment from these; the store's shape-specific fast paths (e.g.
    streaming ``exists``) consume them directly, so the two can never
    disagree on classification.
    """
    if id_type not in ENTITY_COLUMNS:
        raise FilterError(f"unknown id_type {id_type!r}")
    own_columns = ENTITY_COLUMNS[id_type]
    my_rel = RELATION_ID_COLUMN[id_type]

    join_subqueries: list[str] = []
    join_params: list[str] = []
    outer_predicates: list[str] = []
    outer_params: list[str] = []

    for f in filters:
        if "id" not in f:
            raise FilterError("filter missing 'id'")
        parts = f["id"].split(".")

        if len(parts) == 1 and parts[0] in own_columns:
            op, value, numeric = _comparison(f)
            outer_predicates.append(_predicate(parts[0].lower(), op, numeric))
            outer_params.append(value)
            continue

        linked = _CLASS_TO_KIND.get(parts[0]) if len(parts) == 2 else None
        if linked is not None and parts[1] in ENTITY_COLUMNS[linked]:
            op, value, numeric = _comparison(f)
            join_params.append(value)
            pred = _predicate(f"TI.{parts[1].lower()}", op, numeric)
            join_subqueries.append(
                f"SELECT RI.{my_rel} FROM relations RI "
                f"JOIN {linked} TI ON RI.{RELATION_ID_COLUMN[linked]} = TI.id "
                f"WHERE {pred}"
            )
            continue

        # ontology term
        if ontology is not None:
            expanded = sorted(
                ontology.expand_filter_term(
                    f["id"],
                    include_descendants=f.get("includeDescendantTerms", True),
                    similarity=f.get("similarity", "high"),
                )
            )
        else:
            expanded = [f["id"]]
        scope = f.get("scope", default_scope)
        if scope not in RELATION_ID_COLUMN:
            raise FilterError(f"unknown filter scope {scope!r}")
        join_params.extend(expanded)
        placeholders = " , ".join("?" for _ in expanded)
        join_subqueries.append(
            f"SELECT RI.{my_rel} FROM relations RI "
            f"JOIN terms_index TI ON RI.{RELATION_ID_COLUMN[scope]} = TI.id "
            f"WHERE TI.kind = '{scope}' AND TI.term IN ({placeholders})"
        )
    return outer_predicates, outer_params, join_subqueries, join_params, my_rel


def entity_search_conditions(
    filters: list[dict],
    id_type: str,
    default_scope: str,
    *,
    ontology: OntologyStore | None = None,
    id_modifier: str = "id",
    with_where: bool = True,
) -> tuple[str, list[str]]:
    """(sql_fragment, params) constraining ``id_type`` rows by ``filters``."""
    outer_predicates, outer_params, join_subqueries, join_params, _ = (
        entity_search_parts(
            filters, id_type, default_scope, ontology=ontology
        )
    )
    clauses: list[str] = []
    if join_subqueries:
        joined = " INTERSECT ".join(join_subqueries)
        clauses.append(f"{id_modifier} IN ({joined})")
    clauses.extend(outer_predicates)
    if not clauses:
        return "", []
    fragment = " AND ".join(clauses)
    return ("WHERE " if with_where else "") + fragment, join_params + outer_params
