"""Entity schemas + ontology-term extraction.

The six Beacon entity kinds and their filterable columns, matching the
reference's Athena models (reference: shared_resources/athena/{dataset,
cohort,individual,biosample,run,analysis}.py `_table_columns`). Columns
keep their camelCase spelling for filter-id matching (the Beacon filter
``{"id": "karyotypicSex", ...}`` must hit the column verbatim) and are
lowercased only at the SQL layer, exactly as Athena lowercases ORC struct
fields.
"""

from __future__ import annotations

import re

ENTITY_COLUMNS: dict[str, list[str]] = {
    "datasets": [
        "id",
        "_assemblyId",
        "_vcfLocations",
        "_vcfChromosomeMap",
        "createDateTime",
        "dataUseConditions",
        "description",
        "externalUrl",
        "info",
        "name",
        "updateDateTime",
        "version",
    ],
    "cohorts": [
        "id",
        "cohortDataTypes",
        "cohortDesign",
        "cohortSize",
        "cohortType",
        "collectionEvents",
        "exclusionCriteria",
        "inclusionCriteria",
        "name",
    ],
    "individuals": [
        "id",
        "_datasetId",
        "_cohortId",
        "diseases",
        "ethnicity",
        "exposures",
        "geographicOrigin",
        "info",
        "interventionsOrProcedures",
        "karyotypicSex",
        "measures",
        "pedigrees",
        "phenotypicFeatures",
        "sex",
        "treatments",
    ],
    "biosamples": [
        "id",
        "_datasetId",
        "_cohortId",
        "individualId",
        "biosampleStatus",
        "collectionDate",
        "collectionMoment",
        "diagnosticMarkers",
        "histologicalDiagnosis",
        "measurements",
        "obtentionProcedure",
        "pathologicalStage",
        "pathologicalTnmFinding",
        "phenotypicFeatures",
        "sampleOriginDetail",
        "sampleOriginType",
        "sampleProcessing",
        "sampleStorage",
        "tumorGrade",
        "tumorProgression",
        "info",
        "notes",
    ],
    "runs": [
        "id",
        "_datasetId",
        "_cohortId",
        "biosampleId",
        "individualId",
        "info",
        "libraryLayout",
        "librarySelection",
        "librarySource",
        "libraryStrategy",
        "platform",
        "platformModel",
        "runDate",
    ],
    "analyses": [
        "id",
        "_datasetId",
        "_cohortId",
        "_vcfSampleId",
        "individualId",
        "biosampleId",
        "runId",
        "aligner",
        "analysisDate",
        "info",
        "pipelineName",
        "pipelineRef",
        "variantCaller",
    ],
}

ENTITY_KINDS = list(ENTITY_COLUMNS)

# relations-table column per entity kind (reference filter_functions.py
# type_relations_table_id)
RELATION_ID_COLUMN = {
    "individuals": "individualid",
    "biosamples": "biosampleid",
    "runs": "runid",
    "analyses": "analysisid",
    "datasets": "datasetid",
    "cohorts": "cohortid",
}

# CURIE-shaped ontology term ids, e.g. 'HP:0000001', 'SNOMED:123'
# (reference athena/common.py:20 pattern)
TERM_PATTERN = re.compile(r"^\w[^:]+:.+$")


def extract_terms(value):
    """Yield (term, label, type) triples from anywhere in an entity doc.

    A dict whose 'id' looks like a CURIE contributes a term, labelled by
    its sibling 'label'/'type' fields; the walk recurses through every
    nested dict and list (reference: athena/common.py:108-124).
    """
    if isinstance(value, dict):
        label = value.get("label", "")
        typ = value.get("type", "string")
        for key, sub in value.items():
            if (
                key == "id"
                and isinstance(sub, str)
                and TERM_PATTERN.match(sub)
            ):
                yield sub, label, typ
            if isinstance(sub, (dict, list)):
                yield from extract_terms(sub)
    elif isinstance(value, list):
        for item in value:
            yield from extract_terms(item)
