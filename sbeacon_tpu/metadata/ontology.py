"""Ontology term-closure store.

Re-homes the reference's three DynamoDB ontology tables (reference:
dynamodb.tf Ontologies/Anscestors/Descendants; models in shared_resources/
dynamodb/ontologies.py) into one sqlite store, and replaces the indexer's
network calls to EBI OLS / CSIRO Ontoserver (reference: lambda/indexer/
lambda_function.py:62-97,137-192) with a pluggable resolver:

- ``register_edges``: load (child, parent) is-a edges from any local source
  (an OBO/OWL-derived edge list, a bundled subset, tests) and compute the
  full transitive closure in both directions.
- ``resolver``: optional callable term -> set[ancestor terms] for deployers
  with network access; results are cached in the same tables so the closure
  is fetched at index time, never at query time (same contract as the
  reference's indexer).

Terms with no known closure behave as their own singleton family —
identical to the reference's DoesNotExist fallback
(filter_functions.py:_get_term_descendants).
"""

from __future__ import annotations

import json
import sqlite3
from collections import defaultdict
from pathlib import Path
from typing import Callable, Iterable


class OntologyStore:
    def __init__(self, path: str | Path = ":memory:"):
        if path != ":memory:":
            Path(path).parent.mkdir(parents=True, exist_ok=True)
        # served from HTTP worker threads; sqlite objects are guarded by
        # the GIL for our single-statement usage
        self.conn = sqlite3.connect(str(path), check_same_thread=False)
        self.conn.executescript(
            """
            CREATE TABLE IF NOT EXISTS ontologies (
                prefix TEXT PRIMARY KEY, data TEXT
            );
            CREATE TABLE IF NOT EXISTS ancestors (
                term TEXT PRIMARY KEY, terms TEXT
            );
            CREATE TABLE IF NOT EXISTS descendants (
                term TEXT PRIMARY KEY, terms TEXT
            );
            """
        )
        self.conn.commit()
        self.resolver: Callable[[str], set[str]] | None = None

    # -- ontology metadata (reference Ontologies table) ---------------------

    def put_ontology(self, prefix: str, data: dict) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO ontologies VALUES (?, ?)",
            (prefix, json.dumps(data)),
        )
        self.conn.commit()

    def get_ontology(self, prefix: str) -> dict | None:
        row = self.conn.execute(
            "SELECT data FROM ontologies WHERE prefix = ?", (prefix,)
        ).fetchone()
        return json.loads(row[0]) if row else None

    def list_ontologies(self) -> list[dict]:
        return [
            json.loads(r[0])
            for r in self.conn.execute(
                "SELECT data FROM ontologies ORDER BY prefix"
            )
        ]

    # -- closure ------------------------------------------------------------

    def register_edges(self, edges: Iterable[tuple[str, str]]) -> None:
        """(child, parent) is-a edges -> full bidirectional closure.

        Closures include the term itself (the reference stores ancestors
        including self: indexer records term->ancestors from the OLS
        hierarchicalAncestors + self).
        """
        parents: dict[str, set[str]] = defaultdict(set)
        terms: set[str] = set()
        for child, parent in edges:
            parents[child].add(parent)
            terms.add(child)
            terms.add(parent)

        anc: dict[str, set[str]] = {}

        def ancestors_of(t: str, stack: tuple = ()) -> set[str]:
            if t in anc:
                return anc[t]
            if t in stack:  # cycle guard
                return {t}
            out = {t}
            for p in parents.get(t, ()):
                out |= ancestors_of(p, stack + (t,))
            anc[t] = out
            return out

        for t in terms:
            ancestors_of(t)
        self._merge_closures(anc)

    def register_ancestors(self, term: str, ancestors: set[str]) -> None:
        """Directly record a term's ancestor set (resolver result shape)."""
        self._merge_closures({term: set(ancestors) | {term}})

    def _merge_closures(self, anc: dict[str, set[str]]) -> None:
        desc: dict[str, set[str]] = defaultdict(set)
        for t, ancs in anc.items():
            for a in ancs:
                desc[a].add(t)
        cur = self.conn.cursor()
        for t, ancs in anc.items():
            ancs |= self.get_ancestors(t) or set()
            cur.execute(
                "INSERT OR REPLACE INTO ancestors VALUES (?, ?)",
                (t, json.dumps(sorted(ancs))),
            )
        for t, descs in desc.items():
            descs |= self.get_descendants(t) or set()
            cur.execute(
                "INSERT OR REPLACE INTO descendants VALUES (?, ?)",
                (t, json.dumps(sorted(descs))),
            )
        self.conn.commit()

    def _get(self, table: str, term: str) -> set[str] | None:
        row = self.conn.execute(
            f"SELECT terms FROM {table} WHERE term = ?", (term,)
        ).fetchone()
        return set(json.loads(row[0])) if row else None

    def get_ancestors(self, term: str) -> set[str] | None:
        return self._get("ancestors", term)

    def get_descendants(self, term: str) -> set[str] | None:
        return self._get("descendants", term)

    # -- expansion (the filter compiler's entry points) ---------------------

    def term_ancestors(self, term: str) -> set[str]:
        """Ancestors incl. self; unknown term -> {term}
        (reference _get_term_ancestors fallback)."""
        got = self.get_ancestors(term)
        if got is None and self.resolver is not None:
            try:
                fetched = self.resolver(term)
            except Exception:
                fetched = None
            if fetched is not None:
                self.register_ancestors(term, fetched)
                got = self.get_ancestors(term)
        return got if got is not None else {term}

    def term_descendants(self, term: str) -> set[str]:
        """Descendants incl. self; unknown term -> {term}."""
        got = self.get_descendants(term)
        return got if got is not None else {term}

    def expand_filter_term(
        self,
        term: str,
        *,
        include_descendants: bool = True,
        similarity: str = "high",
    ) -> set[str]:
        """Beacon similarity tiers (reference filter_functions.py:100-117):

        high   -> the term's own descendants;
        medium -> descendants of the ancestor half way up the closure;
        low    -> descendants of the broadest ancestor.
        """
        if not include_descendants:
            return {term}
        if similarity == "high":
            return self.term_descendants(term)
        ancestors = self.term_ancestors(term)
        families = sorted(
            (self.term_descendants(a) for a in ancestors), key=len
        )
        if similarity == "medium":
            return families[len(families) // 2]
        return families[-1]  # low

    def close(self) -> None:
        self.conn.close()
