"""Scalar SQL UDFs: the Athena-UDF Lambda re-homed onto the sqlite store.

The reference ships a Java Lambda exposing four scalar UDFs to Athena —
zlib ``compress``/``decompress`` (Base64-wrapped) and AES
``encrypt``/``decrypt`` with a Base64 data key fetched from Secrets
Manager (reference: lambda/udfs/src/main/java/com/amazonaws/athena/
connectors/udfs/AthenaUDFHandler.java:69-204, deployed by udfs.tf:26-42;
present but unreferenced by any query — carried as optional, SURVEY.md
§2.1). Here the same four functions are plain Python callables plus a
``register_udfs`` hook that installs them as sqlite scalar functions on
the metadata store's connection, so metadata SQL can use them exactly the
way Athena SQL would.

Wire-format parity: ``compress`` is raw zlib (Java ``Deflater`` default)
Base64'd; ``encrypt`` is AES/ECB/PKCS5Padding (Java ``Cipher.getInstance
("AES")`` default) over a Base64-decoded key. ECB is a weak mode — kept
because the wire format is the parity contract; prefer the additionally
provided GCM pair for new data.
"""

from __future__ import annotations

import base64
import os
import zlib
from typing import Callable

#: secrets provider signature (the CachableSecretsManager role): secret
#: name -> Base64-encoded AES data key string
SecretsProvider = Callable[[str], str]


def env_secrets(name: str) -> str:
    """Default provider: key material from SBEACON_SECRET_{NAME}."""
    key = os.environ.get(f"SBEACON_SECRET_{name.upper().replace('-', '_')}")
    if key is None:
        raise KeyError(f"secret {name!r} not configured")
    return key


def compress(text: str) -> str:
    """Base64(zlib(text)) — AthenaUDFHandler.compress."""
    return base64.b64encode(zlib.compress(text.encode())).decode()


def decompress(data: str) -> str:
    """Inverse of :func:`compress` — AthenaUDFHandler.decompress."""
    return zlib.decompress(base64.b64decode(data)).decode()


def _aes_ecb(key_b64: str):
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
        modes,
    )

    key = base64.b64decode(key_b64)
    return Cipher(algorithms.AES(key), modes.ECB())


def encrypt(plaintext: str, secret_name: str, secrets: SecretsProvider = env_secrets) -> str:
    """AES/ECB/PKCS5 + Base64 — AthenaUDFHandler.encrypt wire format."""
    from cryptography.hazmat.primitives import padding

    padder = padding.PKCS7(128).padder()
    padded = padder.update(plaintext.encode()) + padder.finalize()
    enc = _aes_ecb(secrets(secret_name)).encryptor()
    return base64.b64encode(enc.update(padded) + enc.finalize()).decode()


def decrypt(ciphertext: str, secret_name: str, secrets: SecretsProvider = env_secrets) -> str:
    """Inverse of :func:`encrypt` — AthenaUDFHandler.decrypt."""
    from cryptography.hazmat.primitives import padding

    dec = _aes_ecb(secrets(secret_name)).decryptor()
    padded = dec.update(base64.b64decode(ciphertext)) + dec.finalize()
    unpadder = padding.PKCS7(128).unpadder()
    return (unpadder.update(padded) + unpadder.finalize()).decode()


def encrypt_gcm(plaintext: str, secret_name: str, secrets: SecretsProvider = env_secrets) -> str:
    """Authenticated alternative (not in the reference): Base64 of
    nonce || AES-GCM ciphertext+tag."""
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    key = base64.b64decode(secrets(secret_name))
    nonce = os.urandom(12)
    ct = AESGCM(key).encrypt(nonce, plaintext.encode(), None)
    return base64.b64encode(nonce + ct).decode()


def decrypt_gcm(ciphertext: str, secret_name: str, secrets: SecretsProvider = env_secrets) -> str:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    key = base64.b64decode(secrets(secret_name))
    raw = base64.b64decode(ciphertext)
    return AESGCM(key).decrypt(raw[:12], raw[12:], None).decode()


def register_udfs(store, secrets: SecretsProvider = env_secrets) -> None:
    """Install the four UDFs (plus the GCM pair) as sqlite scalar
    functions on a MetadataStore — the udfs.tf deployment step."""
    conn = store.conn
    conn.create_function("compress", 1, compress, deterministic=True)
    conn.create_function("decompress", 1, decompress, deterministic=True)
    conn.create_function(
        "encrypt", 2, lambda p, s: encrypt(p, s, secrets), deterministic=True
    )
    conn.create_function(
        "decrypt", 2, lambda c, s: decrypt(c, s, secrets), deterministic=True
    )
    conn.create_function(
        "encrypt_gcm", 2, lambda p, s: encrypt_gcm(p, s, secrets)
    )
    conn.create_function(
        "decrypt_gcm", 2, lambda c, s: decrypt_gcm(c, s, secrets)
    )
