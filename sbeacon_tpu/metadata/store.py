"""Embedded columnar metadata engine.

Plays the role of the reference's entire Athena/Glue metadata plane — the
six ORC entity tables, the terms/terms_index/relations CTAS products, and
the AthenaModel query API (reference: athena.tf:15-851; shared_resources/
athena/common.py AthenaModel.get_by_query/get_count_by_query/
get_existence_by_query) — as one sqlite database with the same query
surface and no polling: queries return in microseconds instead of the
reference's 0.1 s x 300 Athena poll loop (athena/common.py:151-165).

Entity documents are stored whole (JSON) plus one lowercased SQL column per
filterable field, so the filter compiler's generated SQL runs verbatim.
``rebuild_indexes`` is the indexer lambda equivalent (reference:
lambda/indexer/lambda_function.py index_terms/record_terms/record_relations):
it derives terms, terms_index and the six-way relations join from current
entity rows in three CREATE-AS statements.
"""

from __future__ import annotations

import json
import sqlite3
import threading
from pathlib import Path

from .entities import ENTITY_COLUMNS, ENTITY_KINDS, extract_terms
from .filters import entity_search_conditions
from .ontology import OntologyStore


def _sql_value(doc: dict, col: str) -> str:
    """Column value from a doc: '_assemblyId' accepts either the private
    key or its public 'assemblyId' spelling (the reference models take
    assemblyId= and store _assemblyId)."""
    v = doc.get(col)
    if v is None and col.startswith("_"):
        v = doc.get(col[1:])
    if v is None:
        return ""
    if isinstance(v, str):
        return v
    return json.dumps(v)


class MetadataStore:
    def __init__(
        self,
        path: str | Path = ":memory:",
        *,
        ontology: OntologyStore | None = None,
    ):
        self._path = str(path)
        if self._path != ":memory:":
            Path(path).parent.mkdir(parents=True, exist_ok=True)
        self.conn = sqlite3.connect(self._path, check_same_thread=False)
        self._lock = threading.Lock()
        self._tlocal = threading.local()
        self._read_conns: list = []
        # per-kind row counts for the density heuristic: a COUNT(*) is
        # a full B-tree scan at 1M rows and was paid on EVERY
        # record-granularity fetch with one term filter (ADVICE r3);
        # invalidated by upsert/delete/rebuild_indexes
        self._kind_counts: dict[str, int] = {}
        if self._path != ":memory:":
            # WAL: writers never block readers, so per-thread read
            # connections can serve concurrently while upserts/rebuilds
            # proceed — one slow analytic count must not head-of-line
            # block the 0.13 ms boolean path (code-review r3)
            self.conn.execute("PRAGMA journal_mode=WAL")
            self.conn.execute("PRAGMA busy_timeout=10000")
        self.ontology = ontology
        self._create_tables()

    def _create_tables(self) -> None:
        cur = self.conn.cursor()
        for kind, cols in ENTITY_COLUMNS.items():
            col_defs = ", ".join(
                f"{c.lower()} TEXT" + (" PRIMARY KEY" if c == "id" else "")
                for c in cols
            )
            cur.execute(
                f"CREATE TABLE IF NOT EXISTS {kind} ({col_defs}, _doc TEXT)"
            )
        cur.executescript(
            """
            CREATE TABLE IF NOT EXISTS terms_cache (
                kind TEXT, id TEXT, term TEXT, label TEXT, type TEXT
            );
            CREATE INDEX IF NOT EXISTS terms_cache_kind_id
                ON terms_cache (kind, id);
            CREATE TABLE IF NOT EXISTS terms (
                term TEXT, label TEXT, type TEXT, kind TEXT
            );
            CREATE TABLE IF NOT EXISTS terms_index (
                id TEXT, term TEXT, kind TEXT
            );
            CREATE TABLE IF NOT EXISTS relations (
                datasetid TEXT, cohortid TEXT, individualid TEXT,
                biosampleid TEXT, runid TEXT, analysisid TEXT
            );
            """
        )
        self.conn.commit()

    def _read(self, sql: str, params=()):  # noqa: D401
        """Thread-safe read.

        File-backed stores: one sqlite connection PER READER THREAD
        (WAL mode), so reads run truly concurrently and never wait on
        the write lock. In-memory stores (tests): per-thread
        connections would each be a distinct empty database, so reads
        share the write connection under the lock — the lock is also
        what prevents the InterfaceError ('bad parameter or other API
        misuse') that concurrent cursor use on a shared connection
        raises under load (first seen as soak-test HTTP 500s)."""
        if self._path == ":memory:":
            with self._lock:
                return self.conn.execute(sql, params).fetchall()
        conn = getattr(self._tlocal, "conn", None)
        if conn is None:
            # check_same_thread=False: each reader connection is still
            # used only by its owning thread, but close() runs from the
            # closing thread — the default guard would raise there and
            # leak the file handle until GC
            conn = sqlite3.connect(self._path, check_same_thread=False)
            conn.execute("PRAGMA busy_timeout=10000")
            self._tlocal.conn = conn
            with self._lock:
                self._read_conns.append(conn)
        return conn.execute(sql, params).fetchall()

    # -- writes -------------------------------------------------------------

    def upsert(self, kind: str, docs: list[dict]) -> None:
        """Insert-or-replace entity documents; refresh their term cache rows
        (reference: per-entity upload_array ORC + terms-cache writes)."""
        if kind not in ENTITY_COLUMNS:
            raise ValueError(f"unknown entity kind {kind!r}")
        self._kind_counts.pop(kind, None)
        cols = ENTITY_COLUMNS[kind]
        col_names = ", ".join(c.lower() for c in cols) + ", _doc"
        placeholders = ", ".join("?" for _ in range(len(cols) + 1))
        with self._lock:
            cur = self.conn.cursor()
            for doc in docs:
                row = [_sql_value(doc, c) for c in cols]
                row.append(json.dumps(doc))
                cur.execute(
                    f"INSERT OR REPLACE INTO {kind} ({col_names}) "
                    f"VALUES ({placeholders})",
                    row,
                )
                cur.execute(
                    "DELETE FROM terms_cache WHERE kind = ? AND id = ?",
                    (kind, doc.get("id", "")),
                )
                cur.executemany(
                    "INSERT INTO terms_cache VALUES (?, ?, ?, ?, ?)",
                    [
                        (kind, doc.get("id", ""), term, label, typ)
                        for term, label, typ in extract_terms(doc)
                    ],
                )
            self.conn.commit()

    def delete(self, kind: str, entity_id: str) -> None:
        self._kind_counts.pop(kind, None)
        with self._lock:
            self._set_term_counts_clean(self.conn.cursor(), False)
            self.conn.execute(
                f"DELETE FROM {kind} WHERE id = ?", (entity_id,)
            )
            self.conn.execute(
                "DELETE FROM terms_cache WHERE kind = ? AND id = ?",
                (kind, entity_id),
            )
            self.conn.commit()

    # -- the indexer (reference lambda/indexer CTAS trio) -------------------

    _SECONDARY_INDEXES = {
        "terms_index_kind_term": "terms_index (kind, term, id)",
        "relations_dataset": "relations (datasetid)",
        "relations_cohort": "relations (cohortid)",
        "relations_individual": "relations (individualid)",
        "relations_biosample": "relations (biosampleid)",
        "relations_run": "relations (runid)",
        "relations_analysis": "relations (analysisid)",
        # cross-entity record pages (/datasets/{id}/individuals etc.,
        # _CROSS_ENTITY in api/app.py): each is WHERE <col> = ?
        # ORDER BY id LIMIT n — the (col, id) composite turns the 1M-row
        # scan-and-sort into an index range walk that stops at the page
        # boundary (VERDICT r4 next #6; reference pattern to beat:
        # athena/common.py:37-48 ORDER BY id OFFSET/LIMIT full scans)
        "individuals_dataset_id": "individuals (_datasetid, id)",
        "individuals_cohort_id": "individuals (_cohortid, id)",
        "biosamples_individual_id": "biosamples (individualid, id)",
        "biosamples_dataset_id": "biosamples (_datasetid, id)",
        "runs_biosample_id": "runs (biosampleid, id)",
        "analyses_biosample_id": "analyses (biosampleid, id)",
        "analyses_run_id": "analyses (runid, id)",
    }

    def rebuild_indexes(self) -> None:
        self._kind_counts.clear()
        with self._lock:
            cur = self.conn.cursor()
            # drop secondary indexes first: maintaining them during the
            # bulk INSERTs below roughly doubles a full rebuild. Plain
            # execute (NOT executescript, which commits the pending
            # transaction) keeps the whole rebuild one atomic unit — a
            # mid-rebuild failure must roll back to the indexed state.
            for name in self._SECONDARY_INDEXES:
                cur.execute(f"DROP INDEX IF EXISTS {name}")
            cur.execute("DELETE FROM terms")
            cur.execute(
                "INSERT INTO terms "
                "SELECT DISTINCT term, label, type, kind FROM terms_cache "
                "ORDER BY term ASC"
            )
            cur.execute("DELETE FROM terms_index")
            cur.execute(
                "INSERT INTO terms_index "
                "SELECT DISTINCT id, term, kind FROM terms_cache"
            )
            cur.execute("DELETE FROM relations")
            # six-way entity join (reference generate_query_relations.py)
            cur.execute(
                """
                INSERT INTO relations
                SELECT
                    D.id AS datasetid,
                    C.id AS cohortid,
                    I.id AS individualid,
                    B.id AS biosampleid,
                    R.id AS runid,
                    A.id AS analysisid
                FROM datasets D
                LEFT OUTER JOIN individuals I ON D.id = I._datasetid
                LEFT OUTER JOIN biosamples B ON I.id = B.individualid
                LEFT OUTER JOIN runs R ON B.id = R.biosampleid
                LEFT OUTER JOIN analyses A ON R.id = A.runid
                FULL OUTER JOIN cohorts C ON C.id = I._cohortid
                """
            )
            # the indexes the filter plans need at scale (profiled at 1M
            # individuals: unindexed terms_index/relations turned every
            # filtered query into seconds of full scans) + fresh planner
            # statistics. Built after the bulk INSERTs — index-then-insert
            # is ~2x slower for the CTAS-style rebuild.
            for name, spec in self._SECONDARY_INDEXES.items():
                cur.execute(f"CREATE INDEX IF NOT EXISTS {name} ON {spec}")
            # precomputed term cardinalities (VERDICT r3 #6): count
            # granularity with a single same-scope ontology-term filter
            # was a seconds-long id-IN materialisation at 1M rows; the
            # answer per (kind, term) is a rebuild-time aggregate. The
            # table derives ONLY from terms_index + relations, so it
            # shares their lifecycle exactly — upserts leave all three
            # equally stale until the next rebuild (the reference's
            # indexer-CTAS model, lambda/indexer/generate_query_terms.py).
            cur.execute("DROP TABLE IF EXISTS term_counts")
            cur.execute(
                "CREATE TABLE term_counts ("
                "kind TEXT, term TEXT, expanded INTEGER, n INTEGER, "
                "PRIMARY KEY (kind, term, expanded)) WITHOUT ROWID"
            )
            from .entities import RELATION_ID_COLUMN

            for kind, rel_col in RELATION_ID_COLUMN.items():
                # expanded=0: exact per-term cardinality
                cur.execute(
                    f"INSERT INTO term_counts "
                    f"SELECT '{kind}', TI.term, 0, "
                    f"COUNT(DISTINCT RI.{rel_col}) "
                    f"FROM relations RI JOIN terms_index TI "
                    f"ON RI.{rel_col} = TI.id "
                    f"WHERE TI.kind = '{kind}' GROUP BY TI.term"
                )
                # expanded=1: with-descendants cardinality for every
                # term a default filter could name (present terms and
                # their ancestors) — the multi-term COUNT DISTINCT was
                # still seconds at 1M, so the indexer precomputes it,
                # exactly like the reference's CTAS term tables
                # (lambda/indexer/generate_query_terms.py)
                if self.ontology is None:
                    continue
                present = [
                    r[0]
                    for r in cur.execute(
                        "SELECT DISTINCT term FROM terms_index "
                        "WHERE kind = ?",
                        (kind,),
                    )
                ]
                exact_n = dict(
                    cur.execute(
                        "SELECT term, n FROM term_counts "
                        "WHERE kind = ? AND expanded = 0",
                        (kind,),
                    ).fetchall()
                )
                candidates: set[str] = set(present)
                for t in present:
                    candidates |= self.ontology.term_ancestors(t)
                for t in sorted(candidates):
                    exp = sorted(self.ontology.term_descendants(t))
                    if len(exp) == 1:
                        n = exact_n.get(t, 0)
                    else:
                        ph = ", ".join("?" for _ in exp)
                        n = cur.execute(
                            f"SELECT COUNT(*) FROM ("
                            f"SELECT DISTINCT TI.id FROM terms_index TI "
                            f"WHERE TI.kind = ? AND TI.term IN ({ph})) d "
                            f"WHERE EXISTS(SELECT 1 FROM relations RI "
                            f"WHERE RI.{rel_col} = d.id)",
                            [kind, *exp],
                        ).fetchone()[0]
                    cur.execute(
                        "INSERT OR REPLACE INTO term_counts "
                        "VALUES (?, ?, 1, ?)",
                        (kind, t, int(n)),
                    )
            self._set_term_counts_clean(cur, True)
            cur.execute("ANALYZE")
            self.conn.commit()

    # -- query surface (AthenaModel equivalents) ----------------------------

    def _compile(self, filters, kind, **kw):
        return entity_search_conditions(
            filters, kind, kind, ontology=self.ontology, **kw
        )

    def _row_count(self, kind: str) -> int:
        """Cached COUNT(*) per entity table (write paths invalidate)."""
        n = self._kind_counts.get(kind)
        if n is None:
            n = self._read(f"SELECT COUNT(*) FROM {kind}")[0][0]
            self._kind_counts[kind] = n
        return n

    def _dense_single_term(self, filters, kind):
        """(expanded_terms, scope) when ``filters`` is exactly one
        ontology-term filter whose estimated match count is a large
        fraction of the table — the shape where the generic
        ``id IN (subquery)`` plan materialises hundreds of thousands of
        ids to return a 100-row page. None otherwise."""
        if not filters or len(filters) != 1 or self.ontology is None:
            return None
        f = filters[0]
        fid = f.get("id", "")
        parts = fid.split(".")
        from .entities import RELATION_ID_COLUMN

        if len(parts) != 1 or parts[0] in ENTITY_COLUMNS[kind]:
            return None  # own-column or malformed: generic path
        scope = f.get("scope", kind)
        if scope != kind or scope not in RELATION_ID_COLUMN:
            return None
        expanded = sorted(
            self.ontology.expand_filter_term(
                fid,
                include_descendants=f.get("includeDescendantTerms", True),
                similarity=f.get("similarity", "high"),
            )
        )
        ph = ", ".join("?" for _ in expanded)
        est = self._read(
            f"SELECT COUNT(*) FROM terms_index WHERE kind = ? "
            f"AND term IN ({ph})",
            [kind, *expanded],
        )[0][0]
        total = self._row_count(kind)
        if total and est >= total / 20:  # dense: walk beats materialise
            return expanded, scope
        return None

    def fetch(
        self,
        kind: str,
        filters: list[dict] | None = None,
        *,
        skip: int = 0,
        limit: int = 100,
        extra_where: str | None = None,
        extra_params: list | None = None,
    ) -> list[dict]:
        """Record-granularity page, ordered by id (reference
        get_record_query ORDER BY id OFFSET/LIMIT).

        Dense single-term filters switch from the reference-shaped
        ``id IN (subquery)`` plan to a correlated-EXISTS entity walk —
        logically identical (same relations semi-join), but it streams
        the PK in order and stops at the page boundary instead of
        materialising the full match set (1.8 s -> ms at 1M individuals
        for a 50%-selectivity filter)."""
        from .entities import RELATION_ID_COLUMN

        dense = self._dense_single_term(filters, kind)
        if dense is not None:
            expanded, scope = dense
            my_rel = RELATION_ID_COLUMN[kind]
            ph = ", ".join("?" for _ in expanded)
            where = (
                f"WHERE EXISTS(SELECT 1 FROM relations RI "
                f"JOIN terms_index TI ON RI.{RELATION_ID_COLUMN[scope]} = TI.id "
                f"WHERE RI.{my_rel} = {kind}.id AND TI.kind = '{scope}' "
                f"AND TI.term IN ({ph}))"
            )
            params: list = list(expanded)
            if extra_where:
                where += f" AND {extra_where}"
                params += list(extra_params or [])
            rows = self._read(
                f"SELECT _doc FROM {kind} {where} "
                f"ORDER BY id LIMIT ? OFFSET ?",
                [*params, limit, skip],
            )
            return [json.loads(r[0]) for r in rows]

        where, params = self._compile(filters or [], kind)
        if extra_where:
            where = (
                f"{where} AND {extra_where}"
                if where
                else f"WHERE {extra_where}"
            )
            params = params + list(extra_params or [])
        sql = (
            f"SELECT _doc FROM {kind} {where} "
            f"ORDER BY id LIMIT ? OFFSET ?"
        )
        rows = self._read(sql, [*params, limit, skip])
        return [json.loads(r[0]) for r in rows]

    def _single_term_filter(self, filters, kind):
        """The filter dict when ``filters`` is exactly one same-scope
        ontology-term filter (the count fast-path shape); None
        otherwise. Mirrors entity_search_parts' classification."""
        if not filters or len(filters) != 1 or self.ontology is None:
            return None
        f = filters[0]
        fid = f.get("id", "")
        parts = fid.split(".")
        from .entities import RELATION_ID_COLUMN

        if len(parts) != 1 or parts[0] in ENTITY_COLUMNS[kind]:
            return None
        scope = f.get("scope", kind)
        if scope != kind or scope not in RELATION_ID_COLUMN:
            return None
        return f

    def _has_term_counts(self) -> bool:
        return bool(
            self._read(
                "SELECT 1 FROM sqlite_master "
                "WHERE type='table' AND name='term_counts'"
            )
        )

    def _term_counts_clean(self) -> bool:
        """True when no delete() has happened since the last rebuild —
        the precomputed cardinalities still count deleted entities
        (upserts leave every derived table equally stale, deletes do
        not: the generic plan excludes a deleted entity immediately).
        Persisted in the database so a restarted process honours a
        prior process's deletes."""
        try:
            rows = self._read(
                "SELECT value FROM _store_meta "
                "WHERE key = 'term_counts_clean'"
            )
        except Exception:
            return False
        return bool(rows) and rows[0][0] == "1"

    def _set_term_counts_clean(self, cur, clean: bool) -> None:
        cur.execute(
            "CREATE TABLE IF NOT EXISTS _store_meta "
            "(key TEXT PRIMARY KEY, value TEXT)"
        )
        cur.execute(
            "INSERT OR REPLACE INTO _store_meta VALUES "
            "('term_counts_clean', ?)",
            ("1" if clean else "0",),
        )

    def count(
        self,
        kind: str,
        filters: list[dict] | None = None,
        *,
        extra_where: str | None = None,
        extra_params: list | None = None,
    ) -> int:
        from .entities import RELATION_ID_COLUMN

        f = (
            self._single_term_filter(filters, kind)
            if not extra_where
            else None
        )
        if f is not None and self._has_term_counts():
            fid = f["id"]
            desc = f.get("includeDescendantTerms", True)
            similarity = f.get("similarity", "high")
            if (not desc or similarity == "high") and (
                self._term_counts_clean()
            ):
                # O(1): the rebuild-time cardinality IS the answer —
                # expanded=0 (exact term) or expanded=1 (the indexer's
                # with-descendants precompute, keyed by the FILTER term)
                rows = self._read(
                    "SELECT n FROM term_counts WHERE kind = ? "
                    "AND term = ? AND expanded = ?",
                    [kind, fid, 1 if desc else 0],
                )
                if rows:
                    return int(rows[0][0])
            # uncached expansion (non-high similarity, or a term the
            # indexer has never seen): distinct-then-probe — ~5x the
            # generic id-IN plan at 1M rows, same semantics
            expanded = sorted(
                self.ontology.expand_filter_term(
                    fid, include_descendants=desc, similarity=similarity
                )
            )
            my_rel = RELATION_ID_COLUMN[kind]
            ph = ", ".join("?" for _ in expanded)
            # the extra entity-table EXISTS keeps this plan equivalent
            # to the generic id-IN count even for entities deleted
            # since the last rebuild (delete() removes the entity row
            # but not its terms_index/relations rows)
            rows = self._read(
                f"SELECT COUNT(*) FROM ("
                f"SELECT DISTINCT TI.id FROM terms_index TI "
                f"WHERE TI.kind = ? AND TI.term IN ({ph})) d "
                f"WHERE EXISTS(SELECT 1 FROM relations RI "
                f"WHERE RI.{my_rel} = d.id) "
                f"AND EXISTS(SELECT 1 FROM {kind} e WHERE e.id = d.id)",
                [kind, *expanded],
            )
            return int(rows[0][0])

        where, params = self._compile(filters or [], kind)
        if extra_where:
            where = (
                f"{where} AND {extra_where}"
                if where
                else f"WHERE {extra_where}"
            )
            params = params + list(extra_params or [])
        sql = f"SELECT COUNT(*) FROM {kind} {where}"
        return int(self._read(sql, params)[0][0])

    def exists(
        self,
        kind: str,
        filters: list[dict] | None = None,
        *,
        extra_where: str | None = None,
        extra_params: list | None = None,
    ) -> bool:
        """Boolean granularity without counting: streams the filter
        subqueries and stops at the first surviving row. At 1M
        individuals a 50%-selectivity filter answers in ~0 ms where
        ``count() > 0`` took seconds (the join subquery materialises
        fully under ``id IN (...)``; a streamed FROM-subquery with a
        correlated entity probe short-circuits instead, with identical
        semantics — the probe keeps the id-must-exist requirement).
        ``extra_where`` predicates (scoped routes) fold into the entity
        probe like own-column filters."""
        from .filters import entity_search_parts

        outer, outer_params, subs, join_params, my_rel = entity_search_parts(
            filters or [], kind, kind, ontology=self.ontology
        )
        if extra_where:
            outer = outer + [f"({extra_where})"]
            outer_params = outer_params + list(extra_params or [])
        if not subs:
            where = f"WHERE {' AND '.join(outer)}" if outer else ""
            rows = self._read(
                f"SELECT 1 FROM {kind} {where} LIMIT 1", outer_params
            )
            return bool(rows)
        comp = " INTERSECT ".join(subs)
        # unqualified outer-predicate columns resolve to ``e`` inside the
        # probe (the streamed row ``t`` exposes only the relation id)
        preds = "".join(f" AND {p}" for p in outer)
        rows = self._read(
            f"SELECT 1 FROM ({comp}) t WHERE EXISTS("
            f"SELECT 1 FROM {kind} e WHERE e.id = t.{my_rel}{preds}) "
            f"LIMIT 1",
            list(join_params) + list(outer_params),
        )
        return bool(rows)

    def get_by_id(self, kind: str, entity_id: str) -> dict | None:
        rows = self._read(
            f"SELECT _doc FROM {kind} WHERE id = ?", (entity_id,)
        )
        return json.loads(rows[0][0]) if rows else None

    def query(self, sql: str, params: list | tuple = ()) -> list[tuple]:
        """Raw parameterised SQL (the run_custom_query escape hatch)."""
        return self._read(sql, params)

    # -- filtering terms ----------------------------------------------------

    def filtering_terms(
        self, *, skip: int = 0, limit: int = 100, kinds: list[str] | None = None
    ) -> list[dict]:
        """Paginated distinct terms (reference getFilteringTerms SELECT
        DISTINCT term, label, type ORDER BY term)."""
        where = ""
        params: list = []
        if kinds:
            where = f"WHERE kind IN ({', '.join('?' for _ in kinds)})"
            params = list(kinds)
        rows = self._read(
            f"SELECT DISTINCT term, label, type FROM terms {where} "
            f"ORDER BY term ASC LIMIT ? OFFSET ?",
            [*params, limit, skip],
        )
        return [
            {"id": t, "label": lb, "type": ty} for t, lb, ty in rows
        ]

    # -- dataset helpers (reference athena/dataset.py get_datasets) ---------

    def datasets_for_assembly(
        self,
        assembly_id: str,
        *,
        dataset_ids: list[str] | None = None,
        filters: list[dict] | None = None,
        skip: int = 0,
        limit: int = 1_000_000,
    ) -> list[dict]:
        extra = "LOWER(_assemblyid) = LOWER(?)"
        params: list = [assembly_id]
        if dataset_ids:
            extra += f" AND id IN ({', '.join('?' for _ in dataset_ids)})"
            params.extend(dataset_ids)
        return self.fetch(
            "datasets",
            filters or [],
            skip=skip,
            limit=limit,
            extra_where=extra,
            extra_params=params,
        )

    def _sample_names_via_analyses(
        self, column: str, entity_id: str
    ) -> dict[str, list[str]]:
        """dataset_id -> vcf sample names via the analyses table
        (reference route_individuals_id_g_variants.py:23-34 Athena join)."""
        rows = self._read(
            f"SELECT _datasetid, _vcfsampleid FROM analyses "
            f"WHERE {column} = ? AND _vcfsampleid != ''",
            (entity_id,),
        )
        out: dict[str, list[str]] = {}
        for ds, sample in rows:
            out.setdefault(ds, []).append(sample)
        return out

    def sample_names_for_individual(
        self, individual_id: str
    ) -> dict[str, list[str]]:
        return self._sample_names_via_analyses("individualid", individual_id)

    def sample_names_for_biosample(
        self, biosample_id: str
    ) -> dict[str, list[str]]:
        return self._sample_names_via_analyses("biosampleid", biosample_id)

    def sample_names_for_run(self, run_id: str) -> dict[str, list[str]]:
        return self._sample_names_via_analyses("runid", run_id)

    def sample_names_for_analysis(
        self, analysis_id: str
    ) -> dict[str, list[str]]:
        return self._sample_names_via_analyses("id", analysis_id)

    def filtering_terms_for_entity(
        self, kind: str, entity_id: str, *, skip: int = 0, limit: int = 100
    ) -> list[dict]:
        """Terms attached to one dataset/cohort and every entity under it
        (reference route_datasets_id_filtering_terms.py:83-127 — the
        5-way UNION over the entity's own terms and its child entities)."""
        fk = "_datasetid" if kind == "datasets" else "_cohortid"
        union = [
            "SELECT term FROM terms_index WHERE id = ? AND kind = ?"
        ]
        params: list = [entity_id, kind]
        for child in ("individuals", "biosamples", "runs", "analyses"):
            union.append(
                f"SELECT TI.term FROM {child} E "
                f"JOIN terms_index TI ON TI.id = E.id "
                f"AND TI.kind = '{child}' WHERE E.{fk} = ?"
            )
            params.append(entity_id)
        rows = self._read(
            "SELECT DISTINCT term, label, type FROM terms WHERE term IN "
            f"({' UNION '.join(union)}) ORDER BY term LIMIT ? OFFSET ?",
            [*params, limit, skip],
        )
        return [{"id": t, "label": lb, "type": ty} for t, lb, ty in rows]

    def entities_for_samples(
        self,
        kind: str,
        dataset_id: str,
        sample_names: list[str],
        *,
        skip: int = 0,
        limit: int = 100,
    ) -> list[dict]:
        """Entities of ``kind`` whose analyses carry one of the VCF sample
        names in a dataset (reference route_g_variants_id_individuals.py
        get_record_query: individuals JOIN analyses ON individualid WHERE
        _vcfsampleid IN samples)."""
        join_col = {"individuals": "individualid", "biosamples": "biosampleid"}[
            kind
        ]
        if not sample_names:
            return []
        ph = ", ".join("?" for _ in sample_names)
        rows = self._read(
            f"SELECT DISTINCT E._doc FROM {kind} E "
            f"JOIN analyses A ON A.{join_col} = E.id "
            f"WHERE A._datasetid = ? AND A._vcfsampleid IN ({ph}) "
            f"ORDER BY E.id LIMIT ? OFFSET ?",
            [dataset_id, *sample_names, limit, skip],
        )
        return [json.loads(r[0]) for r in rows]

    def close(self) -> None:
        with self._lock:
            for c in self._read_conns:
                try:
                    c.close()
                except Exception:
                    pass
            self._read_conns.clear()
        self.conn.close()
