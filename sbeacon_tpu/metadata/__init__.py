from .entities import ENTITY_COLUMNS, ENTITY_KINDS, extract_terms
from .filters import entity_search_conditions
from .ontology import OntologyStore
from .store import MetadataStore

__all__ = [
    "ENTITY_COLUMNS",
    "ENTITY_KINDS",
    "MetadataStore",
    "OntologyStore",
    "entity_search_conditions",
    "extract_terms",
]
