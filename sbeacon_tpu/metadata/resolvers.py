"""External ontology resolver clients: the indexer's OLS / Ontoserver role.

The reference's indexer builds the ancestor/descendant closure by calling
EBI OLS ``hierarchicalAncestors`` for CURIE-prefixed ontologies and the
CSIRO Ontoserver FHIR ``ValueSet/$expand`` (``generalizes`` filter) for
SNOMED, with per-ontology metadata discovery and a 10-retry loop
(reference: lambda/indexer/lambda_function.py:40-222). Here those are
concrete client classes over an injectable HTTP transport — production
deployments pass a real transport; air-gapped environments (like this
build/test box, zero egress) inject a fake or skip resolution, and every
fetched closure lands in the persistent :class:`OntologyStore` cache so
resolution is a one-time, offline-tolerant step.

``TermTreeIndexer`` is the driver (``index_terms_tree`` equivalent):
cluster the metadata store's distinct terms by ontology prefix, discover
ontology metadata, fetch missing ancestor sets on a thread pool, and
merge the closure (ancestors + inverted descendants) into the store.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

log = logging.getLogger(__name__)

#: transport signature: (method, url, json_body|None) -> (status, parsed json)
Transport = Callable[[str, str, dict | None], tuple[int, dict]]

from ..config import (
    DEFAULT_OLS_URL as DEFAULT_OLS,
    DEFAULT_ONTOSERVER_URL as DEFAULT_ONTOSERVER,
)

SNOMED_BASE_URI = "http://snomed.info/sct"

def urllib_transport(method: str, url: str, body: dict | None = None):
    """Default stdlib transport. On a zero-egress host every call raises,
    which the resolvers treat as 'term not resolvable now'."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url,
        data=data,
        method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def term_prefix(term: str) -> str:
    """Ontology cluster key. The reference's SNOMED sniff
    (``re.match(r'(?i)(^SNOMED)|([0-9]+)', term)``, indexer:126) routes
    terms starting with 'SNOMED' or with a bare digit (SNOMED codes are
    submitted non-CURIE) to Ontoserver; everything else clusters by its
    CURIE prefix."""
    if term.upper().startswith("SNOMED") or term[:1].isdigit():
        return "SNOMED"
    return term.split(":")[0].upper()


class OlsResolver:
    """EBI OLS client: ontology metadata + hierarchicalAncestors
    (reference threaded_request_ensemble, indexer:61-73,149-163)."""

    def __init__(
        self, base_url: str = DEFAULT_OLS, transport: Transport | None = None
    ):
        self.base_url = base_url.rstrip("/")
        self.transport = transport or urllib_transport

    def ontology_meta(self, prefix: str) -> dict | None:
        """{'id', 'baseUri'} for an ontology prefix, or None."""
        try:
            status, doc = self.transport(
                "GET", f"{self.base_url}/{prefix.lower()}", None
            )
        except Exception as e:
            log.warning("OLS meta fetch failed for %s: %s", prefix, e)
            return None
        if status != 200:
            return None
        try:
            return {
                "id": doc["ontologyId"].upper(),
                "baseUri": doc["config"]["baseUris"][0],
            }
        except (KeyError, IndexError):
            return None

    def ancestors(self, term: str, meta: dict) -> set[str] | None:
        """obo_ids of the term's hierarchical ancestors; None on failure
        (the reference silently drops unresolvable terms)."""
        prefix, _, local = term.partition(":")
        iri = meta["baseUri"] + local
        # OLS wants the IRI double-URL-encoded in the path
        enc = urllib.parse.quote_plus(urllib.parse.quote_plus(iri))
        url = (
            f"{self.base_url}/{prefix.lower()}/terms/{enc}"
            "/hierarchicalAncestors?size=500"
        )
        out: set[str] = set()
        # OLS paginates (default page size 20): follow _links.next so
        # deep closures (HPO/NCIT routinely exceed a page) aren't
        # silently truncated into the persistent cache
        for _ in range(100):  # hard page cap
            try:
                status, doc = self.transport("GET", url, None)
            except Exception as e:
                log.warning("OLS ancestors failed for %s: %s", term, e)
                return None
            if status != 200:
                return None
            for t in doc.get("_embedded", {}).get("terms", []):
                if t.get("obo_id"):
                    out.add(t["obo_id"])
            nxt = doc.get("_links", {}).get("next", {}).get("href")
            if not nxt:
                break
            url = nxt
        return out or None


class OntoserverResolver:
    """FHIR terminology-server client for SNOMED: ``ValueSet/$expand``
    with a ``generalizes`` filter = the term's ancestors (reference
    threaded_request_ontoserver, indexer:76-97), retried up to 10x."""

    def __init__(
        self,
        url: str = DEFAULT_ONTOSERVER,
        transport: Transport | None = None,
        *,
        retries: int = 10,
        retry_sleep_s: float = 1.0,
    ):
        self.url = url
        self.transport = transport or urllib_transport
        self.retries = retries
        self.retry_sleep_s = retry_sleep_s

    def ancestors(self, term: str, meta: dict) -> set[str] | None:
        snomed = "SNOMED" in term.upper()
        # strip the CURIE prefix case-insensitively: 'snomed:123' must
        # send code '123', not the whole term
        prefix, sep, local = term.partition(":")
        code = local if sep and prefix.upper() == "SNOMED" else term
        body = {
            "resourceType": "Parameters",
            "parameter": [
                {
                    "name": "valueSet",
                    "resource": {
                        "resourceType": "ValueSet",
                        "compose": {
                            "include": [
                                {
                                    "system": meta.get(
                                        "baseUri", SNOMED_BASE_URI
                                    ),
                                    "filter": [
                                        {
                                            "property": "concept",
                                            "op": "generalizes",
                                            "value": code,
                                        }
                                    ],
                                }
                            ]
                        },
                    },
                }
            ],
        }
        for attempt in range(self.retries):
            try:
                status, doc = self.transport("POST", self.url, body)
            except Exception as e:
                # transport raise (urllib HTTPError on non-2xx, resets) is
                # as retryable as an error status — the reference's loop
                # retries any non-200 up to 10x (indexer:79-95)
                log.warning(
                    "ontoserver attempt %d failed for %s: %s",
                    attempt + 1,
                    term,
                    e,
                )
                if attempt + 1 < self.retries:
                    time.sleep(self.retry_sleep_s)
                continue
            if status == 200:
                out = set()
                for entry in doc.get("expansion", {}).get("contains", []):
                    c = entry.get("code")
                    if c:
                        out.add(f"SNOMED:{c}" if snomed else c)
                return out or None
            if attempt + 1 < self.retries:
                time.sleep(self.retry_sleep_s)
        log.warning("ontoserver gave up on %s", term)
        return None


class TermTreeIndexer:
    """The indexer's ``index_terms_tree`` driver over local stores.

    Pulls distinct terms from the metadata store, clusters by prefix,
    discovers per-ontology metadata (cached in the ontology store),
    resolves missing ancestor sets on a thread pool (SNOMED via
    Ontoserver, the rest via OLS), and merges the closure — ancestors
    plus inverted descendants — into the ontology store
    (reference indexer:202-222 batch writes)."""

    def __init__(
        self,
        store,
        ontology_store,
        *,
        ols: OlsResolver | None = None,
        ontoserver: OntoserverResolver | None = None,
        workers: int = 8,
    ):
        self.store = store
        self.ontology = ontology_store
        self.ols = ols or OlsResolver()
        self.ontoserver = ontoserver or OntoserverResolver()
        self.workers = workers

    def distinct_terms(self) -> list[str]:
        rows = self.store.query("SELECT DISTINCT term FROM terms")
        return [t for (t,) in rows if t]

    def _meta_for(self, prefix: str) -> dict | None:
        cached = self.ontology.get_ontology(prefix)
        if cached:
            return cached
        if prefix == "SNOMED":
            meta = {"id": "SNOMED", "baseUri": SNOMED_BASE_URI}
        else:
            meta = self.ols.ontology_meta(prefix)
        if meta:
            self.ontology.put_ontology(prefix, meta)
        return meta

    def run(self) -> dict:
        """Returns {'resolved': n, 'skipped': n, 'failed': n}."""
        clusters: dict[str, set[str]] = {}
        for term in self.distinct_terms():
            clusters.setdefault(term_prefix(term), set()).add(term)

        jobs: list[tuple[str, dict, object]] = []
        skipped = failed = 0
        for prefix, terms in sorted(clusters.items()):
            meta = self._meta_for(prefix)
            if meta is None:
                failed += len(terms)
                continue
            resolver = self.ontoserver if prefix == "SNOMED" else self.ols
            for term in sorted(terms):
                # fetch only closures not already cached (reference
                # Anscestors.DoesNotExist gate, indexer:168-186)
                if self.ontology.get_ancestors(term) is not None:
                    skipped += 1
                    continue
                jobs.append((term, meta, resolver))

        resolved = 0
        if jobs:
            with ThreadPoolExecutor(max_workers=self.workers) as pool:
                results = pool.map(
                    lambda j: (j[0], j[2].ancestors(j[0], j[1])), jobs
                )
                for term, ancestors in results:
                    if ancestors:
                        self.ontology.register_ancestors(term, ancestors)
                        resolved += 1
                    else:
                        failed += 1
        return {"resolved": resolved, "skipped": skipped, "failed": failed}
