"""Unified telemetry plane: metrics registry, request tracing, profiling.

The reference's only observability is a compile-gated C++ stopwatch
(reference: lambda/summariseSlice/source/stopwatch.h) and
print-to-CloudWatch logging; its request-identity story is the
``VariantQuery.startTime/endTime/elapsedTime`` DynamoDB columns
(shared_resources/dynamodb/variant_queries.py:29-59) — timing without a
propagated identity. After PR 1-2 this repo's own telemetry had
fragmented the same way: ``/metrics`` hand-assembled nested dicts from
the batcher, admission controller, breakers and response cache, and the
``Tracer`` in ``utils/trace.py`` was process-local with no request id
crossing the coordinator->worker HTTP boundary.

This module is the single plane the stack wires through:

- **Metrics registry** (:class:`MetricsRegistry`): typed
  :class:`Counter` / :class:`Gauge` / :class:`Histogram` instruments
  with stable dotted names and optional one-label fan-out. Producers
  register instruments (value-owning or callback-backed, the Prometheus
  collector style — the callback reads state the producer already
  maintains under its own lock); the registry renders one snapshot as
  nested JSON (back-compat with the old hand-assembled ``/metrics``
  shape) or as Prometheus text exposition.
- **Request context** (:class:`RequestContext`): a trace id minted at
  API ingress (or honored from an inbound ``X-Beacon-Trace`` header),
  carried thread-locally and re-installed across the pool hand-offs the
  batcher and async runner already do for deadlines, propagated as a
  header on every coordinator->worker call so worker-side spans parent
  correctly (the Dapper model), and returned in the response envelope.
- **Flight recorder** (:class:`EventJournal`): a bounded structured
  journal the control plane publishes transition events into (breaker
  state changes, replica failovers, hedges, rediscovery passes,
  route-table publishes, cache invalidations — wholesale and scoped,
  delta-shard publishes ``ingest.delta_publish``, compaction
  ``compaction.start``/``compaction.complete``, admission sheds), each
  stamped with monotonic + wall time and the ambient trace id; served
  at ``/ops/events``. Histograms can additionally carry **exemplars**
  — the trace id of the latest observation per bucket — so a slow
  latency bucket links directly to the request that landed in it.
- **Profiling + slow-query hooks**: ``SBEACON_PROFILE=<dir>`` arms
  :func:`profile_region` so kernel launch/fetch run under
  ``jax.profiler`` trace annotations; :class:`SlowQueryLog` records a
  structured JSON line (trace id, route, stage decomposition, outcome
  notes) for every request above a configurable latency threshold.

Everything here is stdlib-only (jax is imported lazily and only when
profiling is armed) and importable from any layer, like resilience.py.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import threading
import time
import uuid
from contextlib import contextmanager

log = logging.getLogger(__name__)

# -- metric instruments -------------------------------------------------------

#: fixed request/stage latency bucket upper bounds, in milliseconds
#: (Prometheus-style cumulative buckets; +Inf is implicit)
LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

#: instrument names are stable dotted lowercase identifiers —
#: ``tools/check_metric_names.py`` enforces the same grammar statically
_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")


#: default cap on distinct label values a value-owning instrument may
#: mint per family; overflow collapses to :data:`OVERFLOW_LABEL` and
#: ticks the registry's ``telemetry.label_overflow`` counter — the
#: registry-level twin of shaping's 64-tenant cap, so NO producer can
#: turn attacker-controlled input into unbounded series
DEFAULT_MAX_LABEL_VALUES = 64
#: the shared bucket overflowing label values collapse into
OVERFLOW_LABEL = "other"


class _Instrument:
    """Shared base: a named, optionally labeled, typed series.

    ``fn`` makes the instrument callback-backed (collector style): the
    callback returns the current value — a number, or a
    ``{label_value: number}`` dict when ``label`` is set. Without
    ``fn`` the instrument owns its value(s) under a short lock.

    ``label`` may also be a TUPLE of label names (e.g. ``("route",
    "window")``): the value dict is then keyed by matching tuples of
    label values, rendered as multi-label Prometheus series and as
    nested maps in the JSON snapshot.

    Value-owning labeled instruments enforce a **cardinality guard**:
    at most ``max_label_values`` distinct label values are ever minted
    per family; further values collapse into the shared ``"other"``
    bucket and tick ``telemetry.label_overflow{family=...}``. (Before
    this guard only shaping's tenant classifier enforced a cap — the
    registry itself would happily mint a series per attacker-chosen
    header value.) Callback-backed instruments are exempt: their
    producer owns the state and its bounds.
    """

    kind = "untyped"

    def __init__(self, name: str, help: str = "", *,
                 fn=None, label=None, json_render: bool = True,
                 max_label_values: int | None = None):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must be dotted lowercase "
                "(e.g. 'batcher.launches')"
            )
        self.name = name
        self.help = help
        self.fn = fn
        self.label = label
        #: normalized label-name tuple (None = unlabeled)
        self.labels: tuple[str, ...] | None = (
            None
            if label is None
            else (label,) if isinstance(label, str) else tuple(label)
        )
        #: False = Prometheus-only (used where the back-compat JSON
        #: shape differs from the dotted nesting, e.g. breaker state)
        self.json_render = json_render
        self.max_label_values = int(
            max_label_values
            if max_label_values is not None
            else DEFAULT_MAX_LABEL_VALUES
        )
        #: the registry's shared label-overflow counter (set at
        #: registration; None on free-standing instruments)
        self._overflow = None
        self._lock = threading.Lock()
        self._value = 0.0
        self._children: dict[str, float] = {}

    def _guard_label(self, label_value, children: dict):
        """The label value to actually mint, under the cardinality
        guard (call holding ``self._lock``): a NEW value on a family
        already at its cap collapses to ``"other"``."""
        if (
            label_value is None
            or label_value in children
            or len(children) < self.max_label_values
        ):
            return label_value
        ov = self._overflow
        if ov is not None and ov is not self:
            ov.inc(label_value=self.name)
        if isinstance(label_value, tuple):
            return (OVERFLOW_LABEL,) * len(label_value)
        return OVERFLOW_LABEL

    def _bump(self, n: float, label_value: str | None) -> None:
        with self._lock:
            if label_value is None:
                self._value += n
            else:
                label_value = self._guard_label(
                    label_value, self._children
                )
                self._children[label_value] = (
                    self._children.get(label_value, 0.0) + n
                )

    def collect(self):
        """Current value: a number, or {label_value: number}."""
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:  # a broken callback must not kill /metrics
                log.exception("metric %s callback failed", self.name)
                return None
        with self._lock:
            if self.label is not None:
                return dict(self._children)
            return self._value


class Counter(_Instrument):
    """Monotonic cumulative count (requests served, cache hits)."""

    kind = "counter"

    def inc(self, n: float = 1.0, *, label_value: str | None = None) -> None:
        self._bump(n, label_value)


class Gauge(_Instrument):
    """Point-in-time level (queue depth, entries resident)."""

    kind = "gauge"

    def set(self, v: float, *, label_value: str | None = None) -> None:
        with self._lock:
            if label_value is None:
                self._value = float(v)
            else:
                label_value = self._guard_label(
                    label_value, self._children
                )
                self._children[label_value] = float(v)


class Histogram(_Instrument):
    """Fixed-bucket latency histogram with per-label-value children.

    ``observe`` is the hot-path entry: one short lock, one linear
    bucket scan over the fixed boundary tuple (13 compares) — no
    allocation. Buckets are cumulative at render time, Prometheus
    semantics.

    With ``exemplars=True`` each observation may carry a trace id
    (explicit ``exemplar=`` argument, or the ambient request context's
    id): the most recent (trace id, value, wall time) is kept per
    bucket, so a slow bucket on a dashboard links straight to the
    distributed trace that landed in it (``/_trace?trace_id=...``).
    Rendered as OpenMetrics ``# {trace_id="..."} value ts`` suffixes in
    the text exposition and an ``exemplars`` map in the JSON snapshot.
    Memory is bounded by (label values x buckets) — one slot each.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", *,
                 buckets: tuple = LATENCY_BUCKETS_MS,
                 label: str | None = None,
                 exemplars: bool = False,
                 max_label_values: int | None = None):
        super().__init__(name, help, label=label,
                         max_label_values=max_label_values)
        self.buckets = tuple(float(b) for b in buckets)
        self.exemplars_enabled = bool(exemplars)
        # label_value (or "") -> [counts per bucket + overflow, count, sum]
        self._series: dict[str, list] = {}
        # (label_value, le) -> (trace_id, observed value, wall time)
        self._exemplars: dict[tuple[str, str], tuple] = {}

    def observe(self, v: float, *, label_value: str | None = None,
                exemplar: str | None = None) -> None:
        key = label_value if label_value is not None else ""
        if self.exemplars_enabled and exemplar is None:
            ctx = current_context()
            if ctx is not None:
                exemplar = ctx.trace_id
        with self._lock:
            if key:
                key = self._guard_label(key, self._series)
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [
                    [0] * (len(self.buckets) + 1), 0, 0.0
                ]
            counts, _n, _sum = s
            le = "+Inf"
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    le = f"{b:g}"
                    break
            else:
                counts[-1] += 1
            s[1] += 1
            s[2] += v
            if self.exemplars_enabled and exemplar:
                self._exemplars[(key, le)] = (exemplar, v, time.time())

    def collect(self):
        """{label_value: {"count", "sum", "buckets": {le: cumulative}
        [, "exemplars": {le: {traceId, value, time}}]}} (unlabeled
        histograms use the single key ``""``)."""
        out = {}
        with self._lock:
            for key, (counts, n, total) in self._series.items():
                cum, acc = {}, 0
                for b, c in zip(self.buckets, counts):
                    acc += c
                    cum[f"{b:g}"] = acc
                cum["+Inf"] = acc + counts[-1]
                out[key] = {
                    "count": n,
                    "sum": round(total, 3),
                    "buckets": cum,
                }
            for (key, le), (tid, v, t) in self._exemplars.items():
                series = out.get(key)
                if series is not None:
                    series.setdefault("exemplars", {})[le] = {
                        "traceId": tid,
                        "value": round(v, 4),
                        "time": round(t, 3),
                    }
        return out


class MetricsRegistry:
    """One process surface of typed series with stable dotted names.

    Registration raises on duplicates so renames/collisions break at
    wiring time (and in CI via ``tools/check_metric_names.py``), not
    silently on a dashboard.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}
        # the registry's own cardinality-guard evidence: one family
        # label per instrument that ever collapsed a label value to
        # "other" (family names are bounded by the registrations)
        registry = self
        self._label_overflow = registry.counter(
            "telemetry.label_overflow",
            "label values collapsed to 'other' by the cardinality guard",
            label="family",
        )

    def _register(self, inst: _Instrument) -> _Instrument:
        with self._lock:
            if inst.name in self._instruments:
                raise ValueError(f"metric {inst.name!r} already registered")
            self._instruments[inst.name] = inst
            # wire the shared overflow counter into every value-owning
            # instrument (the counter itself guards via its own cap)
            inst._overflow = getattr(self, "_label_overflow", None)
        return inst

    def counter(self, name: str, help: str = "", *,
                fn=None, label=None,
                json_render: bool = True,
                max_label_values: int | None = None) -> Counter:
        return self._register(
            Counter(name, help, fn=fn, label=label,
                    json_render=json_render,
                    max_label_values=max_label_values)
        )

    def gauge(self, name: str, help: str = "", *,
              fn=None, label=None,
              json_render: bool = True,
              max_label_values: int | None = None) -> Gauge:
        return self._register(
            Gauge(name, help, fn=fn, label=label,
                  json_render=json_render,
                  max_label_values=max_label_values)
        )

    def histogram(self, name: str, help: str = "", *,
                  buckets: tuple = LATENCY_BUCKETS_MS,
                  label: str | None = None,
                  exemplars: bool = False,
                  max_label_values: int | None = None) -> Histogram:
        return self._register(Histogram(name, help, buckets=buckets,
                                        label=label, exemplars=exemplars,
                                        max_label_values=max_label_values))

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._instruments)

    def _snapshot(self) -> list[_Instrument]:
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    # -- renderings ----------------------------------------------------------

    def render_json(self) -> dict:
        """Nested-by-dots snapshot: ``batcher.launcher.queued`` renders
        as ``{"batcher": {"launcher": {"queued": N}}}`` — the exact
        shape the old hand-assembled ``/metrics`` dict had, so
        dashboards and tests keep their keys."""
        out: dict = {}
        for inst in self._snapshot():
            if not inst.json_render:
                continue
            val = inst.collect()
            if val is None:
                continue
            if inst.kind == "histogram" and isinstance(val, dict):
                # unlabel single-series histograms for readability
                if set(val) == {""}:
                    val = val[""]
            elif (
                isinstance(val, dict)
                and val
                and isinstance(next(iter(val)), tuple)
            ):
                # multi-label series nest by label value:
                # {("g_variants", "5m"): 2.0} -> {"g_variants": {"5m": 2.0}}
                nested: dict = {}
                for key_tuple, v in val.items():
                    node = nested
                    for part in key_tuple[:-1]:
                        node = node.setdefault(str(part), {})
                    node[str(key_tuple[-1])] = v
                val = nested
            node = out
            parts = inst.name.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = val
        return out

    def render_prometheus(self, *, openmetrics: bool = False) -> str:
        """Prometheus text exposition. Dotted names flatten to
        underscores under the ``sbeacon_`` namespace. Exemplar
        annotations are only legal in the OpenMetrics dialect — the
        classic text format's parser rejects them — so they render
        only with ``openmetrics=True`` (which also appends the
        spec-required ``# EOF`` terminator)."""
        lines: list[str] = []
        for inst in self._snapshot():
            val = inst.collect()
            if val is None:
                continue
            pname = "sbeacon_" + inst.name.replace(".", "_")
            if inst.help:
                lines.append(f"# HELP {pname} {inst.help}")
            lines.append(f"# TYPE {pname} {inst.kind}")
            # OpenMetrics requires counter SAMPLES to be named
            # <family>_total (the TYPE line keeps the family name);
            # the classic format rejects the suffix form instead
            sname = (
                pname + "_total"
                if openmetrics and inst.kind == "counter"
                else pname
            )
            if inst.kind == "histogram":
                label = inst.label
                for key, series in sorted(val.items()):
                    base = f'{label}="{_esc(key)}",' if label and key else ""
                    exem = (
                        series.get("exemplars") or {}
                        if openmetrics
                        else {}
                    )
                    for le, cum in series["buckets"].items():
                        line = f'{pname}_bucket{{{base}le="{le}"}} {cum}'
                        ex = exem.get(le)
                        if ex is not None:
                            # OpenMetrics exemplar: the most recent
                            # observation that landed in this bucket,
                            # linked to its distributed trace
                            line += (
                                f' # {{trace_id="{_esc(ex["traceId"])}"}}'
                                f' {_num(ex["value"])} {ex["time"]:.3f}'
                            )
                        lines.append(line)
                    sfx = f"{{{base[:-1]}}}" if base else ""
                    lines.append(f"{pname}_sum{sfx} {series['sum']}")
                    lines.append(f"{pname}_count{sfx} {series['count']}")
            elif isinstance(val, dict):
                labels = inst.labels or ("key",)
                for key, v in sorted(val.items()):
                    vals = key if isinstance(key, tuple) else (key,)
                    lbl = ",".join(
                        f'{ln}="{_esc(str(lv))}"'
                        for ln, lv in zip(labels, vals)
                    )
                    lines.append(f"{sname}{{{lbl}}} {_num(v)}")
            else:
                lines.append(f"{sname} {_num(val)}")
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _esc(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def percentiles(xs) -> dict:
    """{p50, p95, p99} of a sample window (numpy-interpolated, 2dp),
    or {} when empty — the one summary shape every stage-timing
    producer (batcher, engine materialisation, runner admission wait)
    feeds into /debug/status and the bench records."""
    xs = list(xs)
    if not xs:
        return {}
    import numpy as np

    a = np.asarray(xs)
    return {
        "p50": round(float(np.percentile(a, 50)), 2),
        "p95": round(float(np.percentile(a, 95)), 2),
        "p99": round(float(np.percentile(a, 99)), 2),
    }


def _num(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    return f"{f:g}"


# -- per-request cost vector ---------------------------------------------------


class CostVector:
    """The resource cost ONE request incurred, accumulated additively
    by the instrumentation points along its path (ISSUE 11):

    - ``device_us`` — device-launch microseconds, pro-rated from the
      batcher's measured per-launch execute time to this request's
      share of the launch's query specs (serving.py);
    - ``host_rows`` — candidate rows walked by the numpy host matcher
      (``engine.host_match_rows`` — per-shard fallbacks, overflow
      paths, and the delta tail);
    - ``delta_shards`` — delta-tail shards walked for this query
      (engine / mesh-tier per-shard host dispatch);
    - ``worker_rtt_ms`` — coordinator->worker round-trip time on
      successful ``/search`` legs (a worker was occupied that long on
      this request's behalf);
    - ``queue_wait_ms`` — time queued (fair-queue admission wait +
      micro-batch wait); contention, not resource cost, so it is
      excluded from the cost-unit scalar but attributed per tenant;
    - ``response_bytes`` — serialized response size;
    - ``cache`` — response-cache outcome (``hit`` / ``negative_hit`` /
      ``miss`` / ``""`` when the cache never saw the query).

    One vector rides each :class:`RequestContext`; charges without an
    ambient context fall into the process-global
    :data:`UNATTRIBUTED_COST` residue so the accounting plane can
    prove what fraction of measured work it attributed. Additive
    updates take one short lock — engine scatter threads and the
    batcher's fetcher thread charge the same vector concurrently.
    """

    NUMERIC = (
        "device_us",
        "host_rows",
        "delta_shards",
        "worker_rtt_ms",
        "queue_wait_ms",
        "response_bytes",
    )

    __slots__ = NUMERIC + ("cache", "_sealed", "_lock")

    def __init__(self):
        for f in self.NUMERIC:
            setattr(self, f, 0.0)
        self.cache = ""
        self._sealed = False
        self._lock = threading.Lock()

    def add(self, *, cache: str | None = None, **fields) -> None:
        """Accumulate numeric fields (and/or set the cache outcome).
        Unknown field names raise — a typo'd charge site must fail in
        tests, not silently leak cost. Charges landing AFTER the
        vector was :meth:`seal`-ed (the request already folded into
        the accounting table — e.g. a launch completing after its
        submitter 504ed, or a losing hedge leg's RTT) redirect to the
        unattributed residue, so they appear in the attribution
        DENOMINATOR instead of vanishing from both sides."""
        with self._lock:
            sealed = self._sealed
            if not sealed:
                for k, v in fields.items():
                    if k not in self.NUMERIC:
                        raise ValueError(f"unknown cost field {k!r}")
                    setattr(self, k, getattr(self, k) + float(v))
                if cache:
                    self.cache = cache
        if sealed and self is not UNATTRIBUTED_COST:
            UNATTRIBUTED_COST.add(cache=cache, **fields)

    def seal(self) -> None:
        """Mark the vector folded: later charges go to the residue."""
        with self._lock:
            self._sealed = True

    def snapshot(self) -> dict:
        with self._lock:
            out = {f: getattr(self, f) for f in self.NUMERIC}
            out["cache"] = self.cache
        return out

    def nonzero(self) -> bool:
        with self._lock:
            return bool(self.cache) or any(
                getattr(self, f) for f in self.NUMERIC
            )

    def as_dict(self) -> dict:
        """Compact rounded rendering for slow-query-log records and
        ``/debug/status`` — zero fields are dropped."""
        snap = self.snapshot()
        out = {}
        for f in self.NUMERIC:
            v = snap[f]
            if v:
                out[f] = round(v, 2)
        if snap["cache"]:
            out["cache"] = snap["cache"]
        return out


#: process-global residue: charges that land with NO ambient request
#: context (warmup launches, background drains, abandoned waiters)
#: accumulate here, so ``/ops/costs`` can report an attribution ratio
#: instead of silently dropping unowned work
UNATTRIBUTED_COST = CostVector()


def charge_cost(**fields) -> None:
    """Charge the current request's cost vector (ambient context), or
    the process-global unattributed residue when off-request. The
    no-context fast path is one thread-local read."""
    ctx = getattr(_ambient, "ctx", None)
    vec = ctx.cost if ctx is not None else UNATTRIBUTED_COST
    vec.add(**fields)


def charge_cost_to(ctx, **fields) -> None:
    """Charge an EXPLICIT request context's cost vector (pool threads
    holding a captured context, e.g. the batcher's fetcher stage);
    ``ctx=None`` charges the unattributed residue."""
    vec = ctx.cost if ctx is not None else UNATTRIBUTED_COST
    vec.add(**fields)


# -- request context / distributed tracing ------------------------------------

#: the cross-process trace header (coordinator->worker and client->API)
TRACE_HEADER = "X-Beacon-Trace"


def new_trace_id() -> str:
    """64-bit hex trace id (the Dapper convention's width)."""
    return uuid.uuid4().hex[:16]


#: acceptable inbound trace ids — anything else is replaced with a
#: fresh id, since the value is re-emitted into outbound worker HTTP
#: headers and log lines (no CRLF or unbounded junk pass-through)
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


def sanitize_trace_id(raw: str | None) -> str | None:
    """``raw`` if it is a well-formed trace id, else None."""
    if raw and _TRACE_ID_RE.match(raw):
        return raw
    return None


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class RequestContext:
    """Ambient per-request identity: one trace id from ingress to every
    worker hop, plus an outcome-notes dict producers annotate (cache
    hit/miss, fused/mesh path, breaker trips) that the slow-query log
    snapshots. ``notes`` is copy-on-write (:func:`annotate` rebinds a
    fresh dict, never mutates in place), so a reader iterating its
    snapshot can never race a writer — an abandoned pool thread may
    still be annotating after the request returned. Two concurrent
    annotates may drop one note; acceptable for observability."""

    __slots__ = (
        "trace_id", "route", "t_start", "notes", "cost", "plan",
        "explain",
    )

    def __init__(self, trace_id: str | None = None, route: str = ""):
        self.trace_id = trace_id or new_trace_id()
        self.route = route
        self.t_start = time.perf_counter()
        self.notes: dict = {}
        #: the request's resource-cost vector (ISSUE 11): created
        #: eagerly so concurrent charge sites never race an install
        self.cost = CostVector()
        #: the request's execution-plan stage list (ISSUE 19):
        #: plan.plan_stage appends bounded entries; created eagerly
        #: like the cost vector so producers never race an install
        self.plan: list = []
        #: True when the API layer authorized ?explain=1 — the engine's
        #: cache front bypasses the response cache for explained
        #: requests (plan.explain_active)
        self.explain = False

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self.t_start) * 1e3


_ambient = threading.local()


def current_context() -> RequestContext | None:
    """The request context the API layer scoped onto this thread (or
    None). Pool workers re-install the submitting request's context via
    :func:`request_context`, exactly like ambient deadlines."""
    return getattr(_ambient, "ctx", None)


@contextmanager
def request_context(ctx: RequestContext | None):
    """Install ``ctx`` as this thread's ambient request context
    (``None`` restores 'no context' — safe to pass through)."""
    prev = getattr(_ambient, "ctx", None)
    _ambient.ctx = ctx
    try:
        yield ctx
    finally:
        _ambient.ctx = prev


#: the literal registry of every outcome-note key producers may
#: ``annotate(...)`` — the slow-query log's schema, in effect. The
#: static lint ``tools/check_annotation_keys.py`` (tier-1 via
#: tests/test_telemetry.py) enforces two-way parity between this set
#: and the annotate() call sites, exactly like the metric-name lint:
#: an unregistered key is an invisible note, a registered-but-unused
#: key is a dashboard field that silently flatlined.
ANNOTATION_KEYS = frozenset({
    "batch_index",
    "batch_ms",
    "breaker",
    "dispatch",
    "dispatch_l0",
    "dispatch_tier",
    "failover",
    "granularity",
    "lane",
    "mesh_delta_tail",
    "mesh_fallback",
    "mesh_tail_l0",
    "mesh_planes",
    "mesh_shards",
    "query_job",
    "replica_hedge",
    "response_cache",
    "short_circuit",
    "tenant",
    "unavailable_datasets",
})


def annotate(**kw) -> None:
    """Attach outcome notes (``response_cache="hit"``, ``path="fused"``)
    to the current request, if any — a no-op off-request, so producers
    call it unconditionally. Copy-on-write rebind: the previous notes
    dict is never mutated, so concurrent readers (the slow-query log
    snapshotting a request an abandoned pool thread still annotates)
    cannot crash mid-iteration."""
    ctx = getattr(_ambient, "ctx", None)
    if ctx is not None:
        ctx.notes = {**ctx.notes, **kw}


# -- slow-query log -----------------------------------------------------------


class SlowQueryLog:
    """Structured slow-request record: any request whose latency tops
    ``threshold_ms`` emits one JSON line (trace id, route, status,
    elapsed, outcome notes) to the ``sbeacon.slowquery`` logger (and an
    optional file) and lands in a bounded in-memory ring for ``/_trace``
    adjacency. ``threshold_ms < 0`` disables; ``0`` records everything
    (debug). The fast path for a request under threshold is one float
    compare."""

    def __init__(self, threshold_ms: float = 1000.0, *,
                 keep: int = 256, path: str = ""):
        self.threshold_ms = float(threshold_ms)
        self.path = path
        self._keep = max(1, keep)
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._count = 0
        self._logger = logging.getLogger("sbeacon.slowquery")

    def count(self) -> int:
        with self._lock:
            return self._count

    def recent(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def maybe_record(self, *, trace_id: str, route: str, status: int,
                     elapsed_ms: float, notes: dict | None = None) -> bool:
        if self.threshold_ms < 0 or elapsed_ms < self.threshold_ms:
            return False
        entry = {
            "traceId": trace_id,
            "route": route,
            "status": int(status),
            "elapsedMs": round(elapsed_ms, 2),
            "thresholdMs": self.threshold_ms,
            "time": time.time(),
        }
        if notes:
            entry["notes"] = dict(notes)
        line = json.dumps(entry, sort_keys=True, default=str)
        with self._lock:
            self._count += 1
            self._ring.append(entry)
            if len(self._ring) > self._keep:
                del self._ring[: -self._keep]
        self._logger.warning("%s", line)
        if self.path:
            try:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
            except OSError:  # a full disk must not fail the request
                log.exception("slow-query log write failed")
        return True


# -- flight recorder (control-plane event journal) ----------------------------


class EventJournal:
    """Bounded structured journal of control-plane transitions — the
    flight recorder. Breaker opens/closes, replica failovers, hedges,
    rediscovery passes, route-table publishes, cache invalidations,
    fused-stack rebuilds, mesh-tier bring-up/fallbacks
    (``mesh.tier_ready`` / ``mesh.fallback``) and admission sheds each
    publish ONE small event here, stamped with monotonic time (ordering
    survives wall
    clock jumps), wall time (human correlation) and the ambient trace
    id when the transition happened inside a request. ``/ops/events``
    serves the ring with ``since``/``kind`` filters, so "what did the
    control plane just do and to whom" is one query instead of a log
    dig.

    Publishing is O(1): one lock, one deque append — safe to call from
    breaker/dispatch hot paths. The ring holds the last ``keep``
    events; ``published()`` counts lifetime publishes so a consumer
    can detect it missed events that already rolled off.
    """

    def __init__(self, keep: int = 1024, *, enabled: bool = True,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.enabled = bool(enabled)
        self._keep = max(1, int(keep))
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self._keep
        )
        self._seq = 0
        self._published = 0

    def configure(self, *, keep: int | None = None,
                  enabled: bool | None = None) -> None:
        """Apply config-tier settings to an already-constructed journal
        (the process-global one is built at import from env defaults;
        ObservabilityConfig re-applies through the app)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if keep is not None and max(1, int(keep)) != self._keep:
                self._keep = max(1, int(keep))
                self._ring = collections.deque(
                    self._ring, maxlen=self._keep
                )

    def publish(self, kind: str, **data) -> int | None:
        """Record one event; returns its sequence number (None when the
        journal is disabled). ``data`` values must be JSON-safe — the
        event is served verbatim at ``/ops/events``."""
        if not self.enabled:
            return None
        evt: dict = {"kind": kind, "tMono": round(self._clock(), 6),
                     "time": time.time()}
        ctx = current_context()
        if ctx is not None:
            evt["traceId"] = ctx.trace_id
        if data:
            evt["data"] = data
        with self._lock:
            self._seq += 1
            self._published += 1
            evt["seq"] = self._seq
            self._ring.append(evt)
        return evt["seq"]

    @staticmethod
    def _kind_matcher(kind: str):
        """``kind`` is a COMMA-SEPARATED list of filters, each
        matching exactly or by prefix (``breaker`` matches
        ``breaker.open``) — one parser for BOTH the newest-capped and
        the paginated read paths, so their filter semantics can never
        diverge."""
        kinds = [k.strip() for k in kind.split(",") if k.strip()]

        def _match(k: str) -> bool:
            return not kinds or any(
                k == want or k.startswith(want + ".") for want in kinds
            )

        return _match

    def events(self, *, since: int = 0, kind: str = "",
               limit: int = 256) -> list[dict]:
        """Events with seq > ``since``, newest last, optionally
        filtered by kind (comma-separated exact-or-prefix list — an
        operator correlating two control planes tails ONE interleaved
        stream), capped at the most recent ``limit``."""
        _match = self._kind_matcher(kind)
        with self._lock:
            evs = [
                dict(e)
                for e in self._ring
                if e["seq"] > since and _match(e["kind"])
            ]
        limit = int(limit)
        return evs[-limit:] if limit > 0 else []

    def events_page(
        self, *, since: int = 0, kind: str = "", limit: int = 256
    ) -> tuple[list[dict], int]:
        """Forward pagination for tailing clients (ISSUE 12 satellite):
        the OLDEST ``limit`` matching events with seq > ``since`` plus a
        ``nextSince`` cursor — pass it back as ``since`` to resume with
        no re-reads and no silently skipped middle (the newest-capped
        :meth:`events` drops a burst's middle entries, so a tailer had
        to guess the next monotonic stamp). When the page is truncated
        the cursor is the last returned seq (more pages follow); when
        the caller is caught up it jumps to the journal head, so
        filtered tails skip non-matching events instead of rescanning
        them every poll. Entries that rolled off the ring during the
        client's gap are gone either way — ``published()`` vs the count
        consumed detects that loss."""
        _match = self._kind_matcher(kind)
        limit = int(limit)
        if limit <= 0:
            return [], int(since)
        page: list[dict] = []
        truncated = False
        with self._lock:
            # stop at limit+1 matches: a far-behind tailer must cost a
            # page's worth of copies under the lock, not a full-ring
            # copy discarded down to `limit` (publish_event contends
            # on this lock from control-plane hot paths)
            for e in self._ring:
                if e["seq"] > since and _match(e["kind"]):
                    if len(page) == limit:
                        truncated = True
                        break
                    page.append(dict(e))
            head = self._seq
        if truncated:  # resume right after this page
            return page, page[-1]["seq"]
        return page, max(int(since), head)

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def published(self) -> int:
        with self._lock:
            return self._published


def _env_journal() -> EventJournal:
    from .config import ENV_OFF

    size = os.environ.get("BEACON_EVENT_JOURNAL_SIZE", "") or "1024"
    enabled = os.environ.get(
        "BEACON_EVENT_JOURNAL_ENABLED", ""
    ).lower() not in ENV_OFF
    try:
        keep = int(size)
    except ValueError:
        keep = 1024
    return EventJournal(keep=keep, enabled=enabled)


#: the process flight recorder: control-plane sites publish here via
#: :func:`publish_event`; ``/ops/events`` serves it. Process-global
#: like ``profiler`` — breakers/routers live below the app layer and
#: must not need a registry reference to be observable.
journal = _env_journal()


def publish_event(kind: str, **data) -> int | None:
    """Publish one control-plane event to the process journal."""
    return journal.publish(kind, **data)


# -- device-plane flight recorder ---------------------------------------------


#: the compiled-program families the device plane dispatches: the
#: scatter tile kernels, the XLA gather kernel (single-shard and fused
#: stacked alike — one program family), the delta-tail L0 mini-index
#: (same kernel, its own family so tail serving is attributable), the
#: mesh shard_map program in its replicated and sliced batch layouts,
#: and the genotype-plane program. Every launch record names exactly
#: one of these.
DEVICE_FAMILIES = (
    "scatter",
    "fused",
    "fused_l0",
    "mesh_replicated",
    "mesh_sliced",
    "plane",
)


class DeviceFlightRecorder:
    """Per-launch telemetry for every compiled device program — the
    device-plane twin of the control plane's :class:`EventJournal`
    (ISSUE 14).

    The reference gets per-invocation visibility for free (every
    Lambda in its scatter-gather is individually metered by
    CloudWatch); our replacement for that fan-out — the micro-batcher's
    compiled launches, the fused stack, the pod-local mesh tier — used
    to count launches in UNLOCKED module globals (``mesh.N_LAUNCHES``
    ``+= 1`` raced across request threads on real accelerators, where
    no ``_CPU_COLLECTIVE_LOCK`` serialises launches) and recorded
    nothing else. This recorder is the single seam all kernel families
    report through:

    - a bounded **launch ring**: program family, batch tier,
      real-vs-padded spec counts (padding-waste ratio), evaluated
      (device, query) pairs, encode/launch/fetch ms, and the ambient
      trace id per launch;
    - lifetime **counters** under one lock (the old module names stay
      readable as module properties backed by these);
    - a **compile-event tracker**: the first launch of a novel
      (program, shape) key is a compile — its wall duration is
      stamped, and a compile observed OUTSIDE a warmup phase emits a
      ``device.compile`` journal event and ticks
      ``device.mid_request_compiles`` (the config9-era "fresh program
      per novel batch size" soak-tail regression becomes a named,
      alertable signal instead of a latency mystery).

    Everything is O(1) per launch (one short lock, dict upserts) and
    every read surface snapshots under the same short lock — never an
    engine or stack-rebuild lock — so ``/device/status`` answers while
    a mesh rebuild is in flight.
    """

    def __init__(self, ring_size: int = 256, *,
                 compile_tracking: bool = True):
        self._lock = threading.Lock()
        self._keep = max(1, int(ring_size))
        self._ring: "collections.deque[dict]" = collections.deque()
        self._by_seq: dict[int, dict] = {}
        self._seq = 0
        self.compile_tracking = bool(compile_tracking)
        # lifetime counters: per family, per seam (the module-property
        # back-compat views), sliced launches, evaluated pairs
        self._families: dict[str, int] = {}
        self._seams: dict[str, int] = {}
        self._sliced = 0
        self._pairs = 0
        # padding accounting: family -> [real, padded] spec slots, and
        # (family, tier) -> [real, padded] for the tier-boundary view
        self._pad: dict[str, list] = {}
        self._pad_tier: dict[tuple, list] = {}
        # output-diet accounting (ISSUE 17): bytes actually
        # materialised on host by result fetches, and encode buffers
        # donated to their launch instead of double-buffered in HBM
        self._fetched_bytes = 0
        self._donated = 0
        # compile tracker: first-seen (program, shape) keys
        self._compiles: dict[str, dict] = {}
        self._warmup_depth = 0
        self._mid_request = 0
        self._last_mid: dict | None = None

    def configure(self, *, ring_size: int | None = None,
                  compile_tracking: bool | None = None) -> None:
        """Apply config-tier settings to the process-global recorder
        (built at import from env defaults, like :data:`journal`)."""
        with self._lock:
            if compile_tracking is not None:
                self.compile_tracking = bool(compile_tracking)
            if ring_size is not None:
                self._keep = max(1, int(ring_size))
                while len(self._ring) > self._keep:
                    old = self._ring.popleft()
                    self._by_seq.pop(old["seq"], None)

    @contextmanager
    def warmup_phase(self):
        """Mark compiles as EXPECTED while a warmup runs (engine /
        mesh-tier program pre-compilation). A process-wide depth
        counter, not a thread-local flag: warmup launches ride the
        batcher's pool threads, so the compiling thread is not the
        thread that entered warmup."""
        with self._lock:
            self._warmup_depth += 1
        try:
            yield
        finally:
            with self._lock:
                self._warmup_depth -= 1

    # -- the one write seam ---------------------------------------------------

    def record_launch(
        self,
        family: str,
        *,
        seam: str,
        tier: int,
        specs_real: int,
        specs_padded: int,
        evaluated_pairs: int = 0,
        launch_ms: float = 0.0,
        program_key=None,
        sliced: bool = False,
        donated: int = 0,
    ) -> int:
        """Record ONE device launch; returns its sequence number (the
        handle :meth:`note_stage` later attaches encode/fetch timings
        to). ``seam`` is the dispatching module (``kernel`` / ``mesh``
        / ``scatter`` — the back-compat module properties read these);
        ``program_key`` is a hashable (program, shape) identity fed to
        the compile tracker (None skips tracking for this launch)."""
        specs_real = int(specs_real)
        specs_padded = max(int(specs_padded), specs_real, 1)
        rec: dict = {
            "family": family,
            "tier": int(tier),
            "specs": specs_real,
            "padded": specs_padded,
            "padWaste": round(1.0 - specs_real / specs_padded, 4),
            "evaluatedPairs": int(evaluated_pairs),
            "launchMs": round(float(launch_ms), 3),
            "time": time.time(),
        }
        if sliced:
            rec["sliced"] = True
        if donated:
            rec["donated"] = int(donated)
        ctx = current_context()
        if ctx is not None:
            rec["traceId"] = ctx.trace_id
        compile_evt = None
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._families[family] = self._families.get(family, 0) + 1
            self._seams[seam] = self._seams.get(seam, 0) + 1
            if sliced:
                self._sliced += 1
            self._pairs += int(evaluated_pairs)
            self._donated += int(donated)
            pad = self._pad.setdefault(family, [0, 0])
            pad[0] += specs_real
            pad[1] += specs_padded
            ptier = self._pad_tier.setdefault((family, int(tier)), [0, 0])
            ptier[0] += specs_real
            ptier[1] += specs_padded
            if program_key is not None and self.compile_tracking:
                key = self._key_str(program_key)
                if key not in self._compiles:
                    warm = self._warmup_depth > 0
                    entry = {
                        "key": key,
                        "family": family,
                        "tier": int(tier),
                        "durationMs": round(float(launch_ms), 3),
                        "time": rec["time"],
                        "warmup": warm,
                    }
                    self._compiles[key] = entry
                    rec["compiled"] = True
                    if not warm:
                        self._mid_request += 1
                        self._last_mid = entry
                        compile_evt = entry
            self._ring.append(rec)
            self._by_seq[rec["seq"]] = rec
            while len(self._ring) > self._keep:
                old = self._ring.popleft()
                self._by_seq.pop(old["seq"], None)
        if compile_evt is not None:
            # outside the recorder lock: the journal has its own, and a
            # mid-request compile inside a request carries its trace id
            publish_event(
                "device.compile",
                program=family,
                shape=compile_evt["key"],
                tier=compile_evt["tier"],
                durationMs=compile_evt["durationMs"],
            )
        return rec["seq"]

    @staticmethod
    def _key_str(program_key) -> str:
        if isinstance(program_key, str):
            return program_key
        if isinstance(program_key, (tuple, list)):
            return ":".join(str(p) for p in program_key)
        return str(program_key)

    def note_stage(self, seq: int, *, encode_ms: float | None = None,
                   fetch_ms: float | None = None,
                   fetch_bytes: int | None = None) -> None:
        """Attach a stage timing to a recorded launch (the encode
        happens before dispatch on the submitting thread, the fetch
        after it on the fetcher thread — neither is known at
        :meth:`record_launch` time). The per-record annotation no-ops
        once the record has rolled off the ring, but ``fetch_bytes``
        still accumulates into the lifetime counter — ring eviction
        must not leak fetched bytes out of ``device.fetched_bytes``."""
        with self._lock:
            if fetch_bytes is not None:
                self._fetched_bytes += int(fetch_bytes)
            rec = self._by_seq.get(seq)
            if rec is None:
                return
            if encode_ms is not None:
                rec["encodeMs"] = round(float(encode_ms), 3)
            if fetch_ms is not None:
                rec["fetchMs"] = round(float(fetch_ms), 3)
            if fetch_bytes is not None:
                rec["fetchBytes"] = int(fetch_bytes)

    # -- back-compat module-property views ------------------------------------

    @property
    def kernel_launches(self) -> int:
        """XLA gather-kernel launches (the old ``kernel.N_LAUNCHES``)."""
        with self._lock:
            return self._seams.get("kernel", 0)

    @property
    def mesh_launches(self) -> int:
        """Mesh shard_map launches (the old ``mesh.N_LAUNCHES``)."""
        with self._lock:
            return self._seams.get("mesh", 0)

    @property
    def scatter_dispatches(self) -> int:
        """Scatter tile-kernel dispatches (``scatter_kernel.N_DISPATCHES``)."""
        with self._lock:
            return self._seams.get("scatter", 0)

    @property
    def sliced_launches(self) -> int:
        with self._lock:
            return self._sliced

    @property
    def evaluated_pairs(self) -> int:
        with self._lock:
            return self._pairs

    @property
    def fetched_bytes(self) -> int:
        """Lifetime bytes result fetches materialised on host — the
        owner-sharded output diet's structural evidence (ISSUE 17)."""
        with self._lock:
            return self._fetched_bytes

    @property
    def donated_buffers(self) -> int:
        """Lifetime encode buffers donated to their launch instead of
        double-buffered in HBM (the upload-path donation seam)."""
        with self._lock:
            return self._donated

    # -- read surfaces --------------------------------------------------------

    def launches_by_family(self) -> dict:
        with self._lock:
            return dict(self._families)

    def _pad_waste_by_family_locked(self) -> dict:
        return {
            f: round(1.0 - real / padded, 4)
            for f, (real, padded) in self._pad.items()
            if padded
        }

    def pad_waste_by_family(self) -> dict:
        """{family: lifetime padding-waste ratio} — wasted pad slots
        over total padded slots, the structural metric for the
        ROADMAP item 1 owner-sharded-output follow-up."""
        with self._lock:
            return self._pad_waste_by_family_locked()

    def _worst_pad_waste_locked(self) -> dict | None:
        worst = None
        for (family, tier), (real, padded) in self._pad_tier.items():
            if not padded:
                continue
            waste = 1.0 - real / padded
            if worst is None or waste > worst[0]:
                worst = (waste, family, tier)
        if worst is None:
            return None
        return {
            "family": worst[1],
            "tier": worst[2],
            "waste": round(worst[0], 4),
        }

    def worst_pad_waste(self) -> dict | None:
        """The worst (family, tier) padding-waste cell, or None before
        any launch — ``/debug/status`` diagnosis material."""
        with self._lock:
            return self._worst_pad_waste_locked()

    def pad_tier_histogram(self) -> dict:
        """{(family, tier): (real, padded)} spec-slot totals — the
        traffic histogram ``ops.kernel.TierLadder.fit`` reads to split
        wasteful rungs (ISSUE 17)."""
        with self._lock:
            return {
                k: (int(v[0]), int(v[1]))
                for k, v in self._pad_tier.items()
            }

    def mid_request_compiles(self) -> int:
        with self._lock:
            return self._mid_request

    def last_mid_request_compile(self) -> dict | None:
        with self._lock:
            return dict(self._last_mid) if self._last_mid else None

    def _compile_snapshot_locked(self) -> dict:
        entries = [dict(e) for e in self._compiles.values()]
        return {
            "enabled": self.compile_tracking,
            "programs": len(entries),
            "midRequestCompiles": self._mid_request,
            "lastMidRequestCompile": (
                dict(self._last_mid) if self._last_mid else None
            ),
            "warmupShapes": sorted(
                e["key"] for e in entries if e["warmup"]
            ),
            "entries": sorted(entries, key=lambda e: e["time"]),
        }

    def compile_snapshot(self) -> dict:
        """The compile cache contents vs the warmup shape set."""
        with self._lock:
            return self._compile_snapshot_locked()

    def launch_summary(self) -> dict:
        """The compact rollup (no ring) ``/debug/status`` embeds."""
        with self._lock:
            total = sum(self._families.values())
            by_family = dict(self._families)
            sliced = self._sliced
            pairs = self._pairs
        return {
            "total": total,
            "byFamily": by_family,
            "sliced": sliced,
            "evaluatedPairs": pairs,
        }

    def snapshot(self) -> dict:
        """The full ``/device/status`` launch document: counters, the
        ring (oldest first), padding waste by family and tier, and the
        compile cache — assembled under ONE lock hold, so the ring and
        the counters describe the same instant (a launch landing
        between two separate acquisitions would break the
        ring-vs-counter reconciliation the golden test asserts). Never
        a stack or publish lock."""
        with self._lock:
            ring = [dict(r) for r in self._ring]
            keep = self._keep
            seq = self._seq
            families = dict(self._families)
            sliced = self._sliced
            pairs = self._pairs
            fetched = self._fetched_bytes
            donated = self._donated
            by_family = self._pad_waste_by_family_locked()
            by_tier = {
                f"{family}:{tier}": round(1.0 - real / padded, 4)
                for (family, tier), (real, padded)
                in sorted(self._pad_tier.items())
                if padded
            }
            worst = self._worst_pad_waste_locked()
            compiles = self._compile_snapshot_locked()
        return {
            "total": sum(families.values()),
            "byFamily": families,
            "sliced": sliced,
            "evaluatedPairs": pairs,
            "fetchedBytes": fetched,
            "donatedBuffers": donated,
            "ring": {"size": keep, "recorded": seq, "entries": ring},
            "padWaste": {
                "byFamily": by_family,
                "byTier": by_tier,
                "worst": worst,
            },
            "compiles": compiles,
        }

def _env_flight_recorder() -> DeviceFlightRecorder:
    from .config import ENV_OFF

    raw = os.environ.get("BEACON_DEVICE_RING_SIZE", "") or "256"
    try:
        ring = int(raw)
    except ValueError:
        ring = 256
    tracking = os.environ.get(
        "BEACON_COMPILE_TRACKING", ""
    ).lower() not in ENV_OFF
    return DeviceFlightRecorder(ring, compile_tracking=tracking)


#: the process device-plane flight recorder. Process-global like
#: ``journal`` — the kernel modules live below the app layer and must
#: not need a registry reference to be observable.
flight_recorder = _env_flight_recorder()


def record_device_launch(family: str, **kw) -> int:
    """Record one device launch on the process flight recorder (the
    kernel seams call this; reading the global at call time keeps the
    recorder swappable in tests)."""
    return flight_recorder.record_launch(family, **kw)


def note_device_stage(seq, **kw) -> None:
    """Attach encode/fetch ms to a recorded launch; seq=None no-ops."""
    if seq is not None:
        flight_recorder.note_stage(seq, **kw)


def device_warmup_phase():
    """``with device_warmup_phase(): engine.warmup()`` — compiles
    inside the scope are expected, not mid-request regressions."""
    return flight_recorder.warmup_phase()


def register_device_metrics(registry) -> None:
    """The device-plane series, callback-backed off the process
    recorder (the usual app fallback registration: call once per
    registry; producers keep no registry reference)."""
    registry.counter(
        "device.launches",
        "compiled device-program launches by family (scatter / fused "
        "/ fused_l0 / mesh_replicated / mesh_sliced / plane)",
        label="family",
        fn=lambda: flight_recorder.launches_by_family(),
    )
    registry.counter(
        "device.evaluated_pairs",
        "evaluated (device, query-slot) pairs summed over all mesh "
        "launches — the per-device FLOP proxy",
        fn=lambda: flight_recorder.evaluated_pairs,
    )
    registry.gauge(
        "device.pad_waste",
        "lifetime padding-waste ratio by program family (padded spec "
        "slots never carrying a real query / total padded slots)",
        label="family",
        fn=lambda: flight_recorder.pad_waste_by_family(),
    )
    registry.counter(
        "device.mid_request_compiles",
        "device-program compiles observed OUTSIDE a warmup phase (a "
        "novel batch shape paid its XLA compile inside a request)",
        fn=lambda: flight_recorder.mid_request_compiles(),
    )
    registry.counter(
        "device.fetched_bytes",
        "bytes result fetches materialised on host across all kernel "
        "families (the owner-sharded output diet's structural metric)",
        fn=lambda: flight_recorder.fetched_bytes,
    )
    registry.counter(
        "device.donated_buffers",
        "encoded query-batch buffers donated to their launch instead "
        "of double-buffered in HBM (BEACON_DONATE_UPLOADS)",
        fn=lambda: flight_recorder.donated_buffers,
    )


# -- profiling hooks ----------------------------------------------------------


class _Profiler:
    """``SBEACON_PROFILE=<dir>`` arms jax.profiler capture: the first
    :func:`profile_region` entry starts one process-wide trace into the
    directory (stopped at exit), and every region runs under a named
    ``TraceAnnotation`` so kernel launch/fetch show up as labeled spans
    in the profile. Unarmed (the default), a region entry is one
    attribute check — the hot path pays nothing."""

    def __init__(self, directory: str | None = None):
        if directory is None:
            directory = os.environ.get("SBEACON_PROFILE", "")
        self.directory = directory
        self._lock = threading.Lock()
        self._started = False
        self._failed = False

    def _ensure_started(self) -> bool:
        with self._lock:
            if self._started:
                return True
            if self._failed:
                return False
            try:
                import atexit

                import jax

                os.makedirs(self.directory, exist_ok=True)
                jax.profiler.start_trace(self.directory)
                atexit.register(self._stop)
                self._started = True
                return True
            except Exception:
                # profiling is an optimisation aid, never a dependency
                log.exception("jax profiler unavailable; disabling")
                self._failed = True
                return False

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass

    @contextmanager
    def region(self, name: str):
        if not self.directory or not self._ensure_started():
            yield
            return
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
        except Exception:
            yield
            return
        with ann:
            yield


profiler = _Profiler()


def profile_region(name: str):
    """``with profile_region("kernel.launch"): ...`` — no-op unless
    ``SBEACON_PROFILE`` is set."""
    return profiler.region(name)
