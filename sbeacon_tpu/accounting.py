"""Per-request cost attribution: the tenant accounting plane.

The telemetry plane (PRs 3/7) measures latency per route and SLO burn
globally, but nothing attributed *resource cost* to the request that
incurred it — an operator staring at a breached ``/slo`` could not tell
which tenant or query shape was burning the budget, and ROADMAP item
4's cost-aware scheduling had no signal to run on. The reference gets
this for free from per-Lambda CloudWatch billing granularity (SURVEY
L0/L4); our monolithic coordinator builds the attribution itself.

The plane has two halves:

- **The per-request** :class:`~sbeacon_tpu.telemetry.CostVector`
  (telemetry.py, riding every :class:`RequestContext`): instrumentation
  points along the request path charge it additively — the batcher
  pro-rates each launch's measured device-execute time to the specs in
  the launch (serving.py), the host matcher charges candidate rows
  walked (engine.py), worker ``/search`` legs charge their RTT
  (parallel/dispatch.py), the response cache stamps its outcome
  (response_cache.py), the fair queue charges admission wait
  (shaping.py), and the API layer charges response bytes. Charges with
  no ambient context land in ``telemetry.UNATTRIBUTED_COST``, so the
  attribution ratio is measurable, never assumed.
- **This module's** :class:`CostAccounting` table: at the end of every
  tracked request the API layer folds the vector into a per-``(tenant,
  lane, query-shape)`` bucket — bounded tenant cardinality reusing
  shaping's 64-bucket overflow cap, decaying time windows with an
  injectable clock, lifetime totals, and a bounded per-shape sample
  ring for mean/p99 cost. Ingest and compaction work that runs off any
  request (the background compactor's folds) is recorded under the
  ``system`` tenant.

Served surfaces: ``/ops/costs`` (JSON rollup — top tenants by cost
unit, per-shape mean/p99, attribution ratio), tenant-labeled ``cost.*``
metrics, cost fields on slow-query-log records and the
``/debug/status`` diagnosis ("costliest tenant/shape"), and the
**scheduling seam**: :meth:`CostAccounting.shape_cost` /
:meth:`drr_charge` let shaping's deficit-round-robin charge a measured
per-shape cost instead of the flat 1-per-request deficit
(``BEACON_COST_DRR``, default off — observability first).

Cost units are **device-microsecond equivalents**: one unit is one
microsecond of device-launch time, and the other resources convert at
fixed documented rates (host scan ~50M rows/s, response serialization
~100 MB/s, a worker RTT occupies that worker for its duration). Queue
wait is attributed per tenant but excluded from the unit scalar — it
is contention, not work.

Everything here is stdlib-only and importable from any layer, like
resilience.py and shaping.py.
"""

from __future__ import annotations

import collections
import threading
import time

from .shaping import FairQueueAdmission
from .telemetry import UNATTRIBUTED_COST, percentiles

#: the tenant background work (compaction, off-request ingest) bills to
SYSTEM_TENANT = "system"
#: shared bucket once ``max_tenants`` distinct tenants are tracked —
#: the same cap and bucket name as shaping's classifier
OVERFLOW_TENANT = "overflow"
#: shared bucket once ``max_shapes`` distinct query shapes are tracked
OVERFLOW_SHAPE = "other"

# -- the cost-unit conversion rates (device-microsecond equivalents) ----------

#: one host-scanned candidate row ≈ 0.02 µs (a ~50M rows/s numpy scan)
HOST_ROW_US = 0.02
#: a worker RTT occupies that worker for its duration: 1 ms = 1000 µs
WORKER_RTT_US_PER_MS = 1000.0
#: one response byte ≈ 0.01 µs (~100 MB/s serialization)
RESPONSE_BYTE_US = 0.01
#: fixed per-delta-shard walk overhead (dispatch + materialize setup)
DELTA_SHARD_US = 5.0


def cost_units(vec: dict) -> float:
    """The scalar cost of one request's vector snapshot, in
    device-microsecond equivalents (queue wait excluded — contention
    is not work)."""
    return (
        vec.get("device_us", 0.0)
        + vec.get("host_rows", 0.0) * HOST_ROW_US
        + vec.get("worker_rtt_ms", 0.0) * WORKER_RTT_US_PER_MS
        + vec.get("response_bytes", 0.0) * RESPONSE_BYTE_US
        + vec.get("delta_shards", 0.0) * DELTA_SHARD_US
    )


def query_shape(route: str, granularity: str | None) -> str:
    """The bounded query-shape key: route label (already cardinality-
    bounded by the API layer) x requested granularity. This is the SAME
    key the DRR charge hook looks up, so learned per-shape costs apply
    to admission of the shape that incurred them."""
    g = str(granularity or "default").lower()
    if g not in ("boolean", "count", "record", "default"):
        g = "other"
    return f"{route}:{g}"


class _Window:
    """Decaying sums over ``window_s``: N epoch-stamped slots, each
    lazily reset when its epoch rolls over (the slo.py `_BucketRing`
    idiom, generalised to float field sums). Thread-safety is the
    caller's — CostAccounting holds one lock across the table."""

    SLOTS = 8

    __slots__ = ("_bucket_s", "_epoch", "_n", "_units", "_clock")

    def __init__(self, window_s: float, clock):
        self._bucket_s = max(0.001, float(window_s)) / self.SLOTS
        self._epoch = [-1] * self.SLOTS
        self._n = [0] * self.SLOTS
        self._units = [0.0] * self.SLOTS
        self._clock = clock

    def add(self, units: float, n: int = 1) -> None:
        idx = int(self._clock() / self._bucket_s)
        slot = idx % self.SLOTS
        if self._epoch[slot] != idx:
            self._epoch[slot] = idx
            self._n[slot] = 0
            self._units[slot] = 0.0
        self._n[slot] += n
        self._units[slot] += units

    def totals(self) -> tuple[int, float]:
        """(requests, units) over the live window."""
        now_idx = int(self._clock() / self._bucket_s)
        lo = now_idx - self.SLOTS
        n, units = 0, 0.0
        for slot in range(self.SLOTS):
            if lo < self._epoch[slot] <= now_idx:
                n += self._n[slot]
                units += self._units[slot]
        return n, units


class _Bucket:
    """One (tenant, lane, shape) accounting bucket: lifetime field
    sums + a decaying window of (requests, units)."""

    __slots__ = ("requests", "units", "fields", "window")

    def __init__(self, window_s: float, clock):
        self.requests = 0
        self.units = 0.0
        self.fields = collections.defaultdict(float)
        self.window = _Window(window_s, clock)

    def fold(self, vec: dict, units: float) -> None:
        self.requests += 1
        self.units += units
        for k, v in vec.items():
            if isinstance(v, (int, float)) and v:
                self.fields[k] += v
        self.window.add(units)


class _ShapeAgg:
    """Per-(lane, shape) aggregate across tenants: the scheduling
    seam's lookup — windowed mean plus a bounded sample ring for
    mean/p99 reporting."""

    SAMPLES = 512

    __slots__ = ("requests", "units", "window", "recent")

    def __init__(self, window_s: float, clock):
        self.requests = 0
        self.units = 0.0
        self.window = _Window(window_s, clock)
        self.recent = collections.deque(maxlen=self.SAMPLES)

    def fold(self, units: float) -> None:
        self.requests += 1
        self.units += units
        self.window.add(units)
        self.recent.append(units)


class CostAccounting:
    """The per-(tenant, lane, query-shape) cost table.

    ``record`` folds one finished request's cost-vector snapshot;
    ``record_system`` books off-request work (compaction) under the
    ``system`` tenant; ``snapshot`` renders the ``/ops/costs``
    document; ``shape_cost``/``drr_charge`` are the cost-aware
    scheduling seam. Cardinality is bounded on BOTH axes: distinct
    tenants beyond ``max_tenants`` share the ``overflow`` bucket
    (shaping's cap, reused) and distinct shapes beyond ``max_shapes``
    share ``other``. The clock is injectable so the decaying windows
    are testable without sleeping.
    """

    #: windowed samples required before shape_cost trusts the window
    #: over the lifetime mean
    MIN_WINDOW_SAMPLES = 8
    #: clamp on the normalized DRR charge, sourced from the fair
    #: queue (the module whose deficit refill cap DEFINES the safe
    #: bound — a charge above its cap could strand a queued request
    #: forever); one source, so the two sides cannot drift apart
    MIN_DRR_CHARGE = FairQueueAdmission.MIN_DRR_CHARGE
    MAX_DRR_CHARGE = FairQueueAdmission.MAX_DRR_CHARGE

    def __init__(
        self,
        *,
        window_s: float = 300.0,
        max_tenants: int = 64,
        max_shapes: int = 64,
        clock=time.monotonic,
    ):
        self.window_s = float(window_s)
        self.max_tenants = max(1, int(max_tenants))
        self.max_shapes = max(1, int(max_shapes))
        self._clock = clock
        self._lock = threading.Lock()
        # (tenant, lane, shape) -> _Bucket
        self._buckets: dict[tuple[str, str, str], _Bucket] = {}
        self._tenants: set[str] = set()
        self._shapes: set[str] = set()
        # (lane, shape) -> _ShapeAgg ; lane -> _ShapeAgg (lane mean)
        self._shape_agg: dict[tuple[str, str], _ShapeAgg] = {}
        self._lane_agg: dict[str, _ShapeAgg] = {}
        # lifetime grand totals (the attribution numerator)
        self._total = collections.defaultdict(float)
        self._total_requests = 0

    # -- folding -------------------------------------------------------------

    def _bound_tenant(self, tenant: str) -> str:
        if tenant in self._tenants:
            return tenant
        if (
            len(self._tenants) >= self.max_tenants
            and tenant not in (OVERFLOW_TENANT, SYSTEM_TENANT)
        ):
            tenant = OVERFLOW_TENANT
        self._tenants.add(tenant)
        return tenant

    def _bound_shape(self, shape: str) -> str:
        if shape in self._shapes:
            return shape
        if len(self._shapes) >= self.max_shapes and shape != OVERFLOW_SHAPE:
            shape = OVERFLOW_SHAPE
        self._shapes.add(shape)
        return shape

    def record(
        self, tenant: str, lane: str, shape: str, vec: dict
    ) -> float:
        """Fold one request's cost-vector snapshot; returns the cost
        units charged. O(#fields) under one lock — request-path safe."""
        units = cost_units(vec)
        with self._lock:
            tenant = self._bound_tenant(tenant or "anon")
            shape = self._bound_shape(shape or OVERFLOW_SHAPE)
            key = (tenant, lane, shape)
            b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = _Bucket(self.window_s, self._clock)
            b.fold(vec, units)
            sk = (lane, shape)
            agg = self._shape_agg.get(sk)
            if agg is None:
                agg = self._shape_agg[sk] = _ShapeAgg(
                    self.window_s, self._clock
                )
            agg.fold(units)
            lagg = self._lane_agg.get(lane)
            if lagg is None:
                lagg = self._lane_agg[lane] = _ShapeAgg(
                    self.window_s, self._clock
                )
            lagg.fold(units)
            self._total_requests += 1
            self._total["units"] += units
            for k, v in vec.items():
                if isinstance(v, (int, float)) and v:
                    self._total[k] += v
        return units

    def record_system(self, shape: str, **fields) -> float:
        """Book off-request background work (compaction, deferred
        ingest folds) under the ``system`` tenant / ``bulk`` lane, so
        amortised cost shows up next to the tenants it serves."""
        return self.record(SYSTEM_TENANT, "bulk", shape, dict(fields))

    # -- the scheduling seam (cost-aware DRR) --------------------------------

    def shape_cost(self, lane: str, shape: str) -> float:
        """Measured mean cost units of one request of ``shape`` in
        ``lane``: the decaying window's mean once it has enough
        samples, else the lifetime mean, else 0.0 (unknown shape)."""
        with self._lock:
            agg = self._shape_agg.get((lane, shape))
            if agg is None:
                return 0.0
            n, units = agg.window.totals()
            if n >= self.MIN_WINDOW_SAMPLES:
                return units / n
            if agg.requests:
                return agg.units / agg.requests
            return 0.0

    def drr_charge(self, lane: str, shape: str) -> float:
        """The deficit a DRR grant of this shape should cost, as a
        multiple of the lane's mean request cost, clamped to
        [0.25, 2.0] so no shape can be starved outright or ride free.
        Unknown shapes (or an idle lane) charge the flat 1.0."""
        sc = self.shape_cost(lane, shape)
        if sc <= 0.0:
            return 1.0
        with self._lock:
            lagg = self._lane_agg.get(lane)
            if lagg is None:
                return 1.0
            n, units = lagg.window.totals()
            if n >= self.MIN_WINDOW_SAMPLES:
                mean = units / n
            elif lagg.requests:
                mean = lagg.units / lagg.requests
            else:
                return 1.0
        if mean <= 0.0:
            return 1.0
        return min(
            self.MAX_DRR_CHARGE, max(self.MIN_DRR_CHARGE, sc / mean)
        )

    # -- rollups -------------------------------------------------------------

    def tenant_field(self, field: str) -> dict[str, float]:
        """{tenant: lifetime value} for the tenant-labeled ``cost.*``
        series (``field='units'``/``'requests'``/a vector field)."""
        out: dict[str, float] = {}
        with self._lock:
            for (tenant, _lane, _shape), b in self._buckets.items():
                if field == "units":
                    v = b.units
                elif field == "requests":
                    v = float(b.requests)
                else:
                    v = b.fields.get(field, 0.0)
                out[tenant] = out.get(tenant, 0.0) + v
        return {t: round(v, 3) for t, v in out.items()}

    def shape_units(self) -> dict[tuple[str, str], float]:
        """{(lane, shape): windowed mean cost units} for the
        ``cost.shape_units`` gauge."""
        out = {}
        with self._lock:
            for (lane, shape), agg in self._shape_agg.items():
                n, units = agg.window.totals()
                if n:
                    out[(lane, shape)] = round(units / n, 3)
                elif agg.requests:
                    out[(lane, shape)] = round(
                        agg.units / agg.requests, 3
                    )
        return out

    def snapshot(self, top_n: int = 8) -> dict:
        """The ``/ops/costs`` document."""
        unattributed = UNATTRIBUTED_COST.snapshot()
        with self._lock:
            tenants: dict[str, dict] = {}
            for (tenant, lane, shape), b in self._buckets.items():
                doc = tenants.setdefault(
                    tenant,
                    {"requests": 0, "units": 0.0, "windowUnits": 0.0},
                )
                doc["requests"] += b.requests
                doc["units"] += b.units
                _n, w_units = b.window.totals()
                doc["windowUnits"] += w_units
                for k, v in b.fields.items():
                    doc[k] = doc.get(k, 0.0) + v
            for doc in tenants.values():
                for k, v in list(doc.items()):
                    if isinstance(v, float):
                        doc[k] = round(v, 3)
            shapes: dict[str, dict] = {}
            # rendering key: the bare shape, lane-qualified only when
            # two lanes share one shape string (the 'other' overflow
            # bucket can legitimately exist in both) — a plain
            # shape-keyed dict would silently overwrite one lane's
            # aggregate with the other's
            shape_lanes: dict[str, int] = {}
            for (_lane, shape) in self._shape_agg:
                shape_lanes[shape] = shape_lanes.get(shape, 0) + 1
            for (lane, shape), agg in self._shape_agg.items():
                qs = percentiles(agg.recent)
                key = shape if shape_lanes[shape] == 1 else (
                    f"{shape}|{lane}"
                )
                shapes[key] = {
                    "lane": lane,
                    "requests": agg.requests,
                    "units": round(agg.units, 3),
                    "meanUnits": round(
                        agg.units / agg.requests, 3
                    )
                    if agg.requests
                    else 0.0,
                    "p99Units": qs.get("p99", 0.0),
                }
            totals = {
                k: round(v, 3) for k, v in sorted(self._total.items())
            }
            totals["requests"] = self._total_requests
        top = sorted(
            tenants.items(), key=lambda kv: -kv[1]["units"]
        )[:top_n]
        costliest_shape = max(
            shapes.items(), key=lambda kv: kv[1]["units"], default=(None,)
        )[0] if shapes else None
        # attribution ratio: what fraction of MEASURED work landed in
        # some (tenant, shape) bucket vs. the unattributed residue —
        # the acceptance bar is >= 0.95 on device µs and host rows
        attribution = {}
        for field in ("device_us", "host_rows"):
            att = totals.get(field, 0.0)
            tot = att + unattributed.get(field, 0.0)
            attribution[field] = round(att / tot, 4) if tot else 1.0
        return {
            "enabled": True,
            "windowS": self.window_s,
            "costUnit": "device-microsecond equivalents",
            "totals": totals,
            "unattributed": {
                k: round(v, 3)
                for k, v in unattributed.items()
                if isinstance(v, (int, float)) and v
            },
            "attributionRatio": attribution,
            "tenants": tenants,
            "topTenants": [[t, d["units"]] for t, d in top],
            "shapes": shapes,
            "costliestTenant": top[0][0] if top else None,
            "costliestShape": costliest_shape,
        }

    def debug(self) -> dict:
        """The compact ``/debug/status`` rollup."""
        snap = self.snapshot(top_n=3)
        return {
            "requests": snap["totals"].get("requests", 0),
            "units": snap["totals"].get("units", 0.0),
            "topTenants": snap["topTenants"],
            "costliestTenant": snap["costliestTenant"],
            "costliestShape": snap["costliestShape"],
            "attributionRatio": snap["attributionRatio"],
        }

    # -- metrics -------------------------------------------------------------

    def register_metrics(self, registry) -> None:
        """The tenant-labeled ``cost.*`` series (callback-backed off
        the table, whose tenant axis is already cardinality-bounded)
        plus the per-shape windowed mean."""
        registry.counter(
            "cost.requests",
            "requests folded into the cost accounting table",
            label="tenant",
            fn=lambda: self.tenant_field("requests"),
        )
        registry.counter(
            "cost.units",
            "attributed cost units (device-microsecond equivalents)",
            label="tenant",
            fn=lambda: self.tenant_field("units"),
        )
        registry.counter(
            "cost.device_us",
            "attributed device-launch microseconds",
            label="tenant",
            fn=lambda: self.tenant_field("device_us"),
        )
        registry.counter(
            "cost.host_rows",
            "attributed host-scan candidate rows",
            label="tenant",
            fn=lambda: self.tenant_field("host_rows"),
        )
        registry.counter(
            "cost.worker_rtt_ms",
            "attributed worker round-trip milliseconds",
            label="tenant",
            fn=lambda: self.tenant_field("worker_rtt_ms"),
        )
        registry.counter(
            "cost.response_bytes",
            "attributed serialized response bytes",
            label="tenant",
            fn=lambda: self.tenant_field("response_bytes"),
        )
        registry.gauge(
            "cost.shape_units",
            "windowed mean cost units per (lane, query shape)",
            label=("lane", "shape"),
            fn=self.shape_units,
        )


def disabled_snapshot() -> dict:
    """The ``/ops/costs`` body when accounting is configured off."""
    return {"enabled": False}
