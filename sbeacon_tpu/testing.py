"""Synthetic data generation for tests, benchmarks and simulations.

Plays the role of the reference's simulation generator (reference:
simulations/simulate.py — synthetic populations from a template VCF), but
generates structured-random VCF records directly, covering every branch of
the variant-matching semantics: SNPs, indels, multi-alt records, symbolic
alleles (<DEL>, <DUP>, <CN0>...), records with and without INFO AC/AN, and
genotype columns.
"""

from __future__ import annotations

import random
from pathlib import Path

from .genomics.vcf import VcfRecord, write_vcf

BASES = "ACGT"

SYMBOLIC_ALTS = [
    "<DEL>",
    "<INS>",
    "<DUP>",
    "<DUP:TANDEM>",
    "<CN0>",
    "<CN1>",
    "<CN2>",
    "<CN3>",
    "<INV>",
]


def _random_seq(rng: random.Random, lo: int, hi: int) -> str:
    return "".join(rng.choice(BASES) for _ in range(rng.randint(lo, hi)))


def random_records(
    rng: random.Random,
    chrom: str = "1",
    n: int = 500,
    start: int = 1000,
    spacing: int = 30,
    n_samples: int = 8,
    p_multiallelic: float = 0.15,
    p_symbolic: float = 0.08,
    p_no_acan: float = 0.2,
    p_indel: float = 0.2,
) -> list[VcfRecord]:
    """Generate sorted synthetic records exercising all matcher branches."""
    records = []
    pos = start
    for _ in range(n):
        pos += rng.randint(1, spacing)
        ref = _random_seq(rng, 1, 1) if rng.random() > p_indel else _random_seq(rng, 1, 6)
        n_alts = 2 if rng.random() < p_multiallelic else 1
        alts = []
        for _ in range(n_alts):
            r = rng.random()
            if r < p_symbolic:
                alts.append(rng.choice(SYMBOLIC_ALTS))
            elif r < p_symbolic + 0.1 and len(ref) <= 3:
                # duplication-shaped alt: ref repeated k times
                alts.append(ref * rng.randint(2, 3))
            else:
                alt = _random_seq(rng, 1, 6)
                while alt == ref:
                    alt = _random_seq(rng, 1, 6)
                alts.append(alt)
        # genotypes: diploid calls over alleles 0..n_alts
        genotypes = []
        for _ in range(n_samples):
            a = rng.randint(0, n_alts)
            b = rng.randint(0, n_alts)
            sep = rng.choice("|/")
            genotypes.append(f"{a}{sep}{b}")
        vt = rng.choice(["SNP", "INDEL", "SV", "N/A"])
        rec = VcfRecord(
            chrom=chrom,
            pos=pos,
            ref=ref,
            alts=alts,
            ac=None,
            an=None,
            vt=vt,
            genotypes=genotypes,
        )
        if rng.random() >= p_no_acan:
            # derive INFO AC/AN through the one shared implementation
            rec.ac = rec.effective_ac()
            rec.an = rec.effective_an()
        records.append(rec)
    return records


def make_test_vcf(
    path: str | Path,
    seed: int = 0,
    chroms: tuple[str, ...] = ("1",),
    n_per_chrom: int = 500,
    n_samples: int = 8,
    **kw,
) -> list[VcfRecord]:
    """Write a synthetic bgzipped VCF; returns its records."""
    rng = random.Random(seed)
    records: list[VcfRecord] = []
    for chrom in chroms:
        records.extend(
            random_records(rng, chrom=chrom, n=n_per_chrom, n_samples=n_samples, **kw)
        )
    write_vcf(path, records, sample_names=[f"S{i:04d}" for i in range(n_samples)])
    return records


# ---------------------------------------------------------------------------
# Range-supporting HTTP object server (tests + demos of the object-store
# data plane; stdlib http.server does not honour Range)
# ---------------------------------------------------------------------------


def range_server(directory: str | Path, *, require_token: str = ""):
    """Context manager serving ``directory`` over HTTP with Range support.

    Yields the base URL. Emulates the object-store role (ranged GETs per
    reference downloader.h); ``require_token`` additionally demands an
    ``Authorization`` header equal to it (for exercising the s3://
    BEACON_S3_TOKEN path).
    """
    import contextlib
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    root = Path(directory)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if require_token and (
                self.headers.get("Authorization", "") != require_token
            ):
                self.send_response(403)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            target = (root / self.path.lstrip("/")).resolve()
            if not str(target).startswith(str(root.resolve())) or (
                not target.is_file()
            ):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            data = target.read_bytes()
            rng = self.headers.get("Range")
            if rng and rng.startswith("bytes="):
                spec = rng[len("bytes="):]
                start_s, _, end_s = spec.partition("-")
                start = int(start_s) if start_s else 0
                end = int(end_s) + 1 if end_s else len(data)
                end = min(end, len(data))
                if start >= len(data):
                    self.send_response(416)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = data[start:end]
                self.send_response(206)
                self.send_header(
                    "Content-Range", f"bytes {start}-{end - 1}/{len(data)}"
                )
            else:
                body = data
                self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()
            self.wfile.write(body)

    @contextlib.contextmanager
    def _cm():
        server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            yield f"http://127.0.0.1:{server.server_address[1]}"
        finally:
            server.shutdown()
            server.server_close()

    return _cm()
