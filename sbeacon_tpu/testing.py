"""Synthetic data generation for tests, benchmarks and simulations.

Plays the role of the reference's simulation generator (reference:
simulations/simulate.py — synthetic populations from a template VCF), but
generates structured-random VCF records directly, covering every branch of
the variant-matching semantics: SNPs, indels, multi-alt records, symbolic
alleles (<DEL>, <DUP>, <CN0>...), records with and without INFO AC/AN, and
genotype columns.
"""

from __future__ import annotations

import random
from pathlib import Path

from .genomics.vcf import VcfRecord, write_vcf

BASES = "ACGT"

SYMBOLIC_ALTS = [
    "<DEL>",
    "<INS>",
    "<DUP>",
    "<DUP:TANDEM>",
    "<CN0>",
    "<CN1>",
    "<CN2>",
    "<CN3>",
    "<INV>",
]


def _random_seq(rng: random.Random, lo: int, hi: int) -> str:
    return "".join(rng.choice(BASES) for _ in range(rng.randint(lo, hi)))


def random_records(
    rng: random.Random,
    chrom: str = "1",
    n: int = 500,
    start: int = 1000,
    spacing: int = 30,
    n_samples: int = 8,
    p_multiallelic: float = 0.15,
    p_symbolic: float = 0.08,
    p_no_acan: float = 0.2,
    p_indel: float = 0.2,
) -> list[VcfRecord]:
    """Generate sorted synthetic records exercising all matcher branches."""
    records = []
    pos = start
    for _ in range(n):
        pos += rng.randint(1, spacing)
        ref = _random_seq(rng, 1, 1) if rng.random() > p_indel else _random_seq(rng, 1, 6)
        n_alts = 2 if rng.random() < p_multiallelic else 1
        alts = []
        for _ in range(n_alts):
            r = rng.random()
            if r < p_symbolic:
                alts.append(rng.choice(SYMBOLIC_ALTS))
            elif r < p_symbolic + 0.1 and len(ref) <= 3:
                # duplication-shaped alt: ref repeated k times
                alts.append(ref * rng.randint(2, 3))
            else:
                alt = _random_seq(rng, 1, 6)
                while alt == ref:
                    alt = _random_seq(rng, 1, 6)
                alts.append(alt)
        # genotypes: diploid calls over alleles 0..n_alts
        genotypes = []
        for _ in range(n_samples):
            a = rng.randint(0, n_alts)
            b = rng.randint(0, n_alts)
            sep = rng.choice("|/")
            genotypes.append(f"{a}{sep}{b}")
        vt = rng.choice(["SNP", "INDEL", "SV", "N/A"])
        rec = VcfRecord(
            chrom=chrom,
            pos=pos,
            ref=ref,
            alts=alts,
            ac=None,
            an=None,
            vt=vt,
            genotypes=genotypes,
        )
        if rng.random() >= p_no_acan:
            # derive INFO AC/AN through the one shared implementation
            rec.ac = rec.effective_ac()
            rec.an = rec.effective_an()
        records.append(rec)
    return records


def make_test_vcf(
    path: str | Path,
    seed: int = 0,
    chroms: tuple[str, ...] = ("1",),
    n_per_chrom: int = 500,
    n_samples: int = 8,
    **kw,
) -> list[VcfRecord]:
    """Write a synthetic bgzipped VCF; returns its records."""
    rng = random.Random(seed)
    records: list[VcfRecord] = []
    for chrom in chroms:
        records.extend(
            random_records(rng, chrom=chrom, n=n_per_chrom, n_samples=n_samples, **kw)
        )
    write_vcf(path, records, sample_names=[f"S{i:04d}" for i in range(n_samples)])
    return records


# ---------------------------------------------------------------------------
# Range-supporting HTTP object server (tests + demos of the object-store
# data plane; stdlib http.server does not honour Range)
# ---------------------------------------------------------------------------


def range_server(directory: str | Path, *, require_token: str = ""):
    """Context manager serving ``directory`` over HTTP with Range support.

    Yields the base URL. Emulates the object-store role (ranged GETs per
    reference downloader.h); ``require_token`` additionally demands an
    ``Authorization`` header equal to it (for exercising the s3://
    BEACON_S3_TOKEN path).
    """
    import contextlib
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    root = Path(directory)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if require_token and (
                self.headers.get("Authorization", "") != require_token
            ):
                self.send_response(403)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            target = (root / self.path.lstrip("/")).resolve()
            if not str(target).startswith(str(root.resolve())) or (
                not target.is_file()
            ):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            data = target.read_bytes()
            rng = self.headers.get("Range")
            if rng and rng.startswith("bytes="):
                spec = rng[len("bytes="):]
                start_s, _, end_s = spec.partition("-")
                start = int(start_s) if start_s else 0
                end = int(end_s) + 1 if end_s else len(data)
                end = min(end, len(data))
                if start >= len(data):
                    self.send_response(416)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = data[start:end]
                self.send_response(206)
                self.send_header(
                    "Content-Range", f"bytes {start}-{end - 1}/{len(data)}"
                )
            else:
                body = data
                self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()
            self.wfile.write(body)

    @contextlib.contextmanager
    def _cm():
        server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            yield f"http://127.0.0.1:{server.server_address[1]}"
        finally:
            server.shutdown()
            server.server_close()

    return _cm()


# ---------------------------------------------------------------------------
# Vectorised large-scale synthetic index (1000-Genomes-shaped corpora)
# ---------------------------------------------------------------------------


def synthetic_shard(
    n_rows: int,
    *,
    n_samples: int = 0,
    seed: int = 0,
    dataset_id: str = "synth",
    chroms: list[str] | None = None,
    position_model: str = "uniform",
    p_multiallelic: float = 0.08,
    p_indel: float = 0.12,
    p_symbolic: float = 0.01,
    with_gt_planes: bool = False,
    plane_density: float = 0.01,
):
    """Directly-constructed ``VariantIndexShard`` at arbitrary scale.

    Pure vectorised numpy — no VCF text, no per-record Python — so a
    2e7-row 1000-Genomes-shaped index builds in seconds. This is the
    query-side scale corpus for benchmarks (the ingest pipeline is
    proven separately through real VCF text); the column *contents* are
    semantically valid (sorted positions per chromosome, contiguous
    multi-alt records sharing pos/AN, correct flags/hashes/prefixes for
    every allele string, AC drawn from a 1/x allele-frequency spectrum,
    blobs materialisable), so host-matcher parity and response
    materialisation work exactly as on ingested data.

    ``position_model``: 'uniform' spreads rows evenly across each
    chromosome's real GRCh38 length; 'clustered' mixes 70% uniform with
    30% hotspot-clustered positions (real genomes are not uniform —
    BENCH skew configs, VERDICT r2 #8).

    All rows carry AC_INFO/AN_INFO (INFO-sourced counts, the common
    case for cohort VCFs), so genotype planes — generated when
    ``with_gt_planes`` with ~``plane_density`` bits set — affect only
    sample extraction, exactly as for bcftools-INFO data.
    """
    import numpy as np

    from .index.columnar import (
        FLAG,
        N_CHROM_CODES,
        VariantIndexShard,
        _alt_flags,
        _ref_repeat_k,
        fnv1a32,
        pack_prefix16,
    )
    from .utils.chrom import CHROMOSOME_LENGTHS, chromosome_code

    rng = np.random.default_rng(seed)
    chroms = chroms or [str(i) for i in range(1, 23)]
    lengths = np.array([CHROMOSOME_LENGTHS[c] for c in chroms], np.float64)
    weights = lengths / lengths.sum()

    # records -> rows: multi-allelic records carry 2-3 alts. Generate
    # one candidate record per requested row (always enough, each
    # record yields >= 1 row), cut at the record whose rows reach
    # n_rows.
    n_rec_est = n_rows + 8
    n_alts = np.where(
        rng.random(n_rec_est) < p_multiallelic,
        rng.integers(2, 4, n_rec_est),
        1,
    ).astype(np.int64)
    total = np.cumsum(n_alts)
    n_rec = min(int(np.searchsorted(total, n_rows, side="left")) + 1, n_rec_est)
    n_alts = n_alts[:n_rec]
    n = int(n_alts.sum())

    # per-record chromosome + position (sorted within chrom)
    rec_chrom = rng.choice(len(chroms), size=n_rec, p=weights)
    u = rng.random(n_rec)
    if position_model == "clustered":
        hot = rng.random(n_rec) < 0.3
        centers = rng.random(64)
        c_idx = rng.integers(0, 64, n_rec)
        spread = rng.normal(0.0, 0.004, n_rec)
        u = np.where(hot, np.clip(centers[c_idx] + spread, 0.0, 1.0), u)
    rec_pos = (u * (lengths[rec_chrom] - 1)).astype(np.int64) + 1

    # sort records by (chromosome CODE, pos) — shard layout is ordered
    # by code, which need not match the chroms list's order
    codes = np.array([chromosome_code(c) for c in chroms], np.int32)
    order = np.lexsort((rec_pos, codes[rec_chrom]))
    rec_chrom = rec_chrom[order]
    rec_pos = rec_pos[order]
    n_alts = n_alts[order]
    row_rec = np.repeat(np.arange(n_rec, dtype=np.int64), n_alts)

    # allele vocabulary: single bases, short indel strings, symbolic
    vocab = ["A", "C", "G", "T"]
    indel_rng = random.Random(seed + 1)
    for _ in range(60):
        vocab.append(_random_seq(indel_rng, 2, 24))
    vocab += ["<DEL>", "<DUP>", "<CN0>", "<CN2>", "<INS>", "."]
    V = len(vocab)
    v_bytes = [v.encode() for v in vocab]
    v_len = np.array([len(v) for v in vocab], np.int64)
    v_hash = np.array([fnv1a32(v.upper().encode()) for v in vocab], np.int32)
    v_flags = np.array([_alt_flags(v) for v in vocab], np.int32)
    v_prefix = np.stack([pack_prefix16(b) for b in v_bytes]).astype(np.uint32)

    kind = rng.random(n)
    is_sym = kind < p_symbolic
    is_indel = (~is_sym) & (kind < p_symbolic + p_indel)
    alt_id = np.where(
        is_sym,
        rng.integers(64, 64 + 6, n),
        np.where(is_indel, rng.integers(4, 64, n), rng.integers(0, 4, n)),
    )
    ref_id = np.repeat(
        np.where(
            rng.random(n_rec) < p_indel / 2,
            rng.integers(4, 64, n_rec),
            rng.integers(0, 4, n_rec),
        ),
        n_alts,
    )

    pos_row = rec_pos[row_rec].astype(np.int32)
    ref_len = v_len[ref_id].astype(np.int32)
    alt_len = v_len[alt_id].astype(np.int32)

    # AC from a heavy-tailed spectrum; AN constant per record
    an_val = 2 * n_samples if n_samples else 5008
    ac = np.minimum(
        (1.0 / np.maximum(rng.random(n), 1e-6)).astype(np.int64), an_val
    ).astype(np.int32)
    ac[rng.random(n) < 0.02] = 0  # monomorphic-in-subset rows

    # repeat-k: vocab pair lookup (cached per unique pair id)
    pair = ref_id * V + alt_id
    uniq_pair, inv = np.unique(pair, return_inverse=True)
    k_u = np.array(
        [
            _ref_repeat_k(vocab[int(p) // V], vocab[int(p) % V])
            for p in uniq_pair
        ],
        np.int32,
    )
    flags = (
        v_flags[alt_id]
        | np.int32(FLAG.AC_INFO)
        | np.int32(FLAG.AN_INFO)
    )

    cols = {
        "pos": pos_row,
        "rec_end": (pos_row.astype(np.int64) + ref_len - 1).astype(np.int32),
        "ref_len": ref_len,
        "alt_len": alt_len,
        "ref_hash": v_hash[ref_id],
        "alt_hash": v_hash[alt_id],
        "ref_repeat_k": k_u[inv],
        "flags": flags,
        "ac": ac,
        "an": np.full(n, an_val, np.int32),
        "rec_id": row_rec.astype(np.int32),
        "alt_prefix": v_prefix[alt_id],
    }

    row_code = codes[rec_chrom[row_rec]]
    chrom_offsets = np.zeros(N_CHROM_CODES + 1, np.int32)
    for c in range(N_CHROM_CODES + 1):
        chrom_offsets[c] = np.searchsorted(row_code, c, side="left")

    # blobs: fixed-width vocab matrix -> masked flatten (vectorised)
    maxw = int(v_len.max())
    v_mat = np.zeros((V, maxw), np.uint8)
    for i, b in enumerate(v_bytes):
        v_mat[i, : len(b)] = np.frombuffer(b, np.uint8)
    lane = np.arange(maxw)

    def blob_of(ids, lens):
        mat = v_mat[ids]
        mask = lane[None, :] < lens[:, None]
        off = np.zeros(n + 1, np.uint32)
        np.cumsum(lens, out=off[1:] if n else None)
        return mat[mask], off

    ref_blob, ref_off = blob_of(ref_id, v_len[ref_id])
    alt_blob, alt_off = blob_of(alt_id, v_len[alt_id])

    planes = {}
    if n_samples and with_gt_planes:
        words = (n_samples + 31) // 32
        # ~plane_density bits set: AND of k random words thins 2^-k
        k_and = max(1, int(round(-np.log2(max(plane_density, 2**-16)))))
        g = rng.integers(0, 2**32, (n, words), dtype=np.uint32)
        for _ in range(k_and - 1):
            g &= rng.integers(0, 2**32, (n, words), dtype=np.uint32)
        tail = n_samples % 32
        if tail:
            g[:, -1] &= np.uint32((1 << tail) - 1)
        planes = {
            "gt_bits": g,
            "gt_bits2": (
                g & rng.integers(0, 2**32, (n, words), dtype=np.uint32)
            ),
            "tok_bits1": np.full(
                (n, words), 0xFFFFFFFF, np.uint32
            ),
            "tok_bits2": np.full((n, words), 0xFFFFFFFF, np.uint32),
            "gt_overflow": np.zeros((0, 3), np.int64),
            "tok_overflow": np.zeros((0, 3), np.int64),
        }
        if tail:
            planes["tok_bits1"][:, -1] = np.uint32((1 << tail) - 1)
            planes["tok_bits2"][:, -1] = np.uint32((1 << tail) - 1)

    meta = {
        "dataset_id": dataset_id,
        "vcf_location": f"synthetic://{dataset_id}",
        "sample_names": [f"S{i}" for i in range(n_samples)],
        "vt_vocab": ["N/A"],
        "n_rows": n,
        "n_records": n_rec,
        "dropped_records": 0,
        "variant_count": n,
        "call_count": int(an_val) * n_rec,
        "sample_count": n_samples,
        "chrom_native": {c: c for c in chroms},
        "format_version": 1,
        "synthetic": True,
        "position_model": position_model,
    }
    return VariantIndexShard(
        meta=meta,
        cols=cols,
        chrom_offsets=chrom_offsets,
        ref_blob=ref_blob.astype(np.uint8),
        ref_off=ref_off,
        alt_blob=alt_blob.astype(np.uint8),
        alt_off=alt_off,
        vt_codes=np.zeros(n, np.int16),
        **planes,
    )
