"""sbeacon_tpu — TPU-native GA4GH Beacon v2 framework.

A ground-up rebuild of the capabilities of CSIRO's serverless Beacon
(reference: terraform-aws-serverless-beacon) designed for TPU hardware:

- VCF ingestion (BGZF/CSI/TBI machinery, C++ hot path) into an HBM-resident
  columnar variant index (sorted (contig,pos) keys, packed alleles, AC/AN).
- Batched Beacon region queries answered by a jit/vmap'd sorted-interval
  search kernel instead of per-region ``bcftools`` subprocess scans
  (reference: lambda/performQuery/search_variants.py).
- Dataset-sharded execution over a ``jax.sharding.Mesh`` with psum/all_gather
  fan-in replacing the SNS + DynamoDB-atomic-counter fan-out/fan-in
  (reference: shared_resources/variantutils/search_variants.py).
- A host-side metadata engine (sqlite) playing the Athena/Glue role, with the
  Beacon filtering-terms compiler and ontology term-closure store.
- The full Beacon v2 REST surface served by a stdlib HTTP server.
"""

__version__ = "0.1.0"
