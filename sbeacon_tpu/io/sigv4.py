"""AWS Signature Version 4 request signing — pure stdlib (hmac/hashlib).

Closes the reference's last infra-capability hole: the reference reads
private S3 buckets through IAM roles attached to every lambda
(reference: iam.tf:4-868; performQuery/search_variants.py:42-50 runs
bcftools directly against ``s3://`` with ambient credentials). Our data
plane (`io/sources.py`) previously supported only anonymous / bearer /
presigned access; this module adds real SigV4 so ``s3://`` URLs work
against private AWS buckets (and SigV4-enforcing S3-compatibles like
MinIO) with nothing beyond stdlib.

The algorithm follows the AWS SigV4 spec exactly:

  1. canonical request  = method \n uri \n query \n headers \n
                          signed-header-names \n payload-hash
  2. string to sign     = AWS4-HMAC-SHA256 \n timestamp \n scope \n
                          sha256(canonical request)
  3. signing key        = HMAC chain over date/region/service
  4. Authorization      = credential + signed headers + signature

S3 specifics honoured: the canonical URI is single-percent-encoded
(S3 is the one service that must NOT double-encode), and the payload
hash for streamed ranged GETs is ``UNSIGNED-PAYLOAD`` carried in
``x-amz-content-sha256`` (required by S3 for every signed request).

Verified against the AWS-published test vectors (see
tests/test_sigv4.py): the documented signing-key derivation example and
the ``get-vanilla`` suite request.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import time
from urllib.parse import quote, unquote, urlparse

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()

_ALGORITHM = "AWS4-HMAC-SHA256"


def _uri_encode(value: str, *, encode_slash: bool) -> str:
    """AWS canonical URI-encoding: RFC 3986 unreserved chars stay, space
    becomes %20 (never '+'), and '/' is kept only for path encoding."""
    safe = "-._~" + ("" if encode_slash else "/")
    return quote(value, safe=safe)


def _canonical_query(query: str) -> str:
    if not query:
        return ""
    pairs = []
    for part in query.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        # re-encode from the decoded form so pre-encoded and raw inputs
        # canonicalise identically. unquote, NOT unquote_plus: '+' is a
        # literal character in an RFC 3986 query — decoding it to space
        # would make the canonical form diverge from the wire request
        # and guarantee SignatureDoesNotMatch for any value with a raw
        # '+'.
        pairs.append(
            (
                _uri_encode(unquote(k), encode_slash=True),
                _uri_encode(unquote(v), encode_slash=True),
            )
        )
    pairs.sort()
    return "&".join(f"{k}={v}" for k, v in pairs)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def derive_signing_key(
    secret_key: str, date: str, region: str, service: str
) -> bytes:
    """The SigV4 key-derivation HMAC chain (AWS docs 'Deriving the
    signing key'); exposed for the published test vector."""
    k_date = _hmac(("AWS4" + secret_key).encode(), date)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    return _hmac(k_service, "aws4_request")


class SigV4Signer:
    """Signs individual HTTP requests for one (credentials, region,
    service) triple. Stateless per call — safe to share across threads
    (the concurrent chunked-GET pool signs each Range request)."""

    def __init__(
        self,
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        service: str = "s3",
        session_token: str | None = None,
    ):
        if not access_key or not secret_key:
            raise ValueError("SigV4Signer needs both access and secret keys")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service
        self.session_token = session_token or None

    def sign(
        self,
        method: str,
        url: str,
        headers: dict[str, str] | None = None,
        *,
        payload_hash: str = UNSIGNED_PAYLOAD,
        now: time.struct_time | None = None,
    ) -> dict[str, str]:
        """Return ``headers`` plus ``Host``/``X-Amz-Date``/
        ``X-Amz-Content-Sha256``(/'X-Amz-Security-Token')/
        ``Authorization`` for the given request.

        Every header present in the result is signed (AWS only mandates
        host + x-amz-date, but signing all of them — including Range —
        protects the whole request from tampering and is what the SDKs
        do for S3)."""
        parsed = urlparse(url)
        if now is None:
            now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        date = amz_date[:8]

        # a caller-supplied Authorization header can never survive (the
        # SigV4 value replaces it); folding it into the canonical header
        # set would guarantee SignatureDoesNotMatch, so drop it first
        out = {
            k: v
            for k, v in (headers or {}).items()
            if k.lower() != "authorization"
        }
        host = parsed.netloc
        out.setdefault("Host", host)
        out["X-Amz-Date"] = amz_date
        if self.service == "s3":
            out.setdefault("X-Amz-Content-Sha256", payload_hash)
        if self.session_token:
            out["X-Amz-Security-Token"] = self.session_token

        lowered = {k.lower().strip(): " ".join(str(v).split()) for k, v in out.items()}
        signed_names = ";".join(sorted(lowered))
        canonical_headers = "".join(
            f"{k}:{lowered[k]}\n" for k in sorted(lowered)
        )
        # canonical URI: S3 signs the request path EXACTLY as sent on
        # the wire, single-encoded (never double-encoded) — callers
        # (resolve_s3) percent-encode the key once, and we use that
        # same encoded path verbatim so the wire and canonical forms
        # can never diverge for keys containing reserved characters
        path = parsed.path or "/"
        canonical = "\n".join(
            (
                method.upper(),
                path,
                _canonical_query(parsed.query),
                canonical_headers,
                signed_names,
                lowered.get("x-amz-content-sha256", payload_hash),
            )
        )
        scope = f"{date}/{self.region}/{self.service}/aws4_request"
        string_to_sign = "\n".join(
            (
                _ALGORITHM,
                amz_date,
                scope,
                hashlib.sha256(canonical.encode()).hexdigest(),
            )
        )
        key = derive_signing_key(
            self.secret_key, date, self.region, self.service
        )
        signature = hmac.new(
            key, string_to_sign.encode(), hashlib.sha256
        ).hexdigest()
        out["Authorization"] = (
            f"{_ALGORITHM} Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_names}, Signature={signature}"
        )
        return out


def signer_from_env(environ: dict | None = None) -> SigV4Signer | None:
    """Build a signer from BEACON_S3_ACCESS_KEY / BEACON_S3_SECRET_KEY
    (+ optional BEACON_S3_REGION, BEACON_S3_SESSION_TOKEN); None when no
    credentials are configured (anonymous / bearer-token access)."""
    env = os.environ if environ is None else environ
    access = env.get("BEACON_S3_ACCESS_KEY", "")
    secret = env.get("BEACON_S3_SECRET_KEY", "")
    if not access or not secret:
        return None
    return SigV4Signer(
        access,
        secret,
        region=env.get("BEACON_S3_REGION", "us-east-1"),
        service="s3",
        session_token=env.get("BEACON_S3_SESSION_TOKEN") or None,
    )
