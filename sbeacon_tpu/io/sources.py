"""Pluggable ranged-read byte sources: the object-store data plane.

The reference's defining I/O pattern is VCFs and index slices living in
object storage, read by concurrent ranged GETs (reference:
lambda/summariseSlice/source/downloader.h:70-91 one ranged GET per
thread; vcf_chunk_reader.h:69-105 4-thread download ring;
performQuery/search_variants.py:42-50 ``bcftools query s3://...``).
This module re-homes that capability behind one small interface:

    source = open_source("http://host/cohort/chr1.vcf.gz")
    source.read_range(start, end)          # one ranged GET
    source.read_range(start, end, workers=4)  # chunked concurrent GETs

Supported schemes:

- local paths (no scheme or ``file://``) — mmap-free plain reads;
- ``http(s)://`` — HTTP Range requests with retries; servers that ignore
  Range fall back to a cached whole-object GET;
- ``s3://bucket/key`` — mapped onto the HTTP backend against an
  S3-compatible endpoint (``BEACON_S3_ENDPOINT``, path-style; defaults
  to the real AWS endpoint for the configured region when unset and
  SigV4 credentials are present), with per-request **AWS SigV4
  signing** (``io/sigv4.py``) when ``BEACON_S3_ACCESS_KEY`` /
  ``BEACON_S3_SECRET_KEY`` are configured — private buckets work
  without a gateway, re-homing the reference's IAM-role data plane
  (reference: iam.tf:4-868; performQuery/search_variants.py:42-50).
  A static ``Authorization`` header (``BEACON_S3_TOKEN``) remains for
  bearer-authenticating S3-compatibles, and presigned/anonymous URLs
  keep working with neither configured.

Every read retries transient failures (the reference wraps each S3 GET
in a retry loop, shared/awsutils.cpp:62-65).
"""

from __future__ import annotations

import os
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from urllib.parse import urlparse


class RemoteIOError(IOError):
    """A remote object is unreachable/missing (400/404 at the API edge).

    ``status`` carries the HTTP code when one was received (None for
    transport failures) so callers can tell "definitively absent" (404)
    from "store said no / store unreachable" — conflating them turns an
    auth or endpoint problem into a misleading missing-file report."""

    def __init__(self, message: str, status: int | None = None):
        super().__init__(message)
        self.status = status


_SCHEMES = ("http://", "https://", "s3://")


def is_remote(location: str | Path) -> bool:
    return str(location).startswith(_SCHEMES)


def resolve_s3(url: str):
    """s3://bucket/key -> (http url, headers, signer|None) via the
    configured S3-compatible endpoint. With SigV4 credentials in the
    environment and no explicit endpoint, the real AWS regional
    endpoint is assumed (path-style)."""
    from .sigv4 import signer_from_env

    signer = signer_from_env()
    endpoint = os.environ.get("BEACON_S3_ENDPOINT", "")
    if not endpoint:
        if signer is None:
            raise RemoteIOError(
                f"cannot read {url}: set BEACON_S3_ENDPOINT to an "
                "S3-compatible HTTP endpoint (path-style), or configure "
                "BEACON_S3_ACCESS_KEY/BEACON_S3_SECRET_KEY for AWS SigV4"
            )
        endpoint = f"https://s3.{signer.region}.amazonaws.com"
    # split bucket/key WITHOUT urlparse: a '#' or '?' in an object key is
    # literal key material for S3, not a fragment/query delimiter
    rest = url[len("s3://"):]
    bucket, _, key = rest.partition("/")
    from urllib.parse import quote

    # percent-encode the key exactly once; the signer uses this same
    # encoded wire path verbatim as the canonical URI, so wire and
    # canonical forms cannot diverge for reserved characters
    enc_key = quote(key, safe="/-._~")
    headers = {}
    token = os.environ.get("BEACON_S3_TOKEN", "")
    if token and signer is None:
        # a static Authorization header would collide with the SigV4
        # Authorization; credentials take precedence when both are set
        headers["Authorization"] = token
    return (
        f"{endpoint.rstrip('/')}/{bucket}/{enc_key}",
        headers,
        signer,
    )


class ByteSource:
    """Random-access byte reads over one object."""

    location: str

    def exists(self) -> bool:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def read_range(self, start: int, end: int, *, workers: int = 1) -> bytes:
        """Bytes in [start, end) (clamped to the object's size)."""
        raise NotImplementedError

    def read_all(self) -> bytes:
        return self.read_range(0, self.size())


class LocalFileSource(ByteSource):
    def __init__(self, path: str | Path):
        self.location = str(path)
        self._path = Path(path)

    def exists(self) -> bool:
        return self._path.exists()

    def size(self) -> int:
        return self._path.stat().st_size

    def read_range(self, start: int, end: int, *, workers: int = 1) -> bytes:
        with open(self._path, "rb") as fh:
            fh.seek(start)
            return fh.read(max(0, end - start))

    def read_all(self) -> bytes:
        return self._path.read_bytes()


class HttpRangeSource(ByteSource):
    """HTTP(S) object with Range reads, retries, and concurrent chunking.

    The ``workers`` path is the downloader.h role: [start, end) split into
    ``chunk_bytes`` pieces fetched by a thread pool, reassembled in order.
    A server that answers 200 to a Range request (no range support) gets
    one whole-object GET whose body is cached for later reads.
    """

    def __init__(
        self,
        url: str,
        *,
        headers: dict | None = None,
        retries: int = 3,
        timeout_s: float = 60.0,
        chunk_bytes: int = 8 * 1024 * 1024,
        max_object_bytes: int | None = None,
    ):
        self.location = url
        self._signer = None
        if url.startswith("s3://"):
            url, s3_headers, self._signer = resolve_s3(url)
            headers = {**s3_headers, **(headers or {})}
        self._url = url
        self._headers = dict(headers or {})
        self._retries = retries
        self._timeout_s = timeout_s
        self._chunk_bytes = chunk_bytes
        # budget for whole-body reads (Range-less servers): a hostile or
        # misconfigured endpoint streaming an unbounded 200 body must be
        # cut off at the cap, not read into memory first
        self._max_object_bytes = max_object_bytes
        self._size: int | None = None
        self._whole: bytes | None = None  # cache when Range is unsupported

    def _read_capped(self, resp) -> bytes:
        cap = self._max_object_bytes
        if cap is None:
            return resp.read()
        cl = resp.headers.get("Content-Length")
        if cl and cl.isdigit() and int(cl) > cap:
            raise RemoteIOError(
                f"{self.location}: object is {cl} bytes (limit {cap})"
            )
        body = resp.read(cap + 1)
        if len(body) > cap:
            raise RemoteIOError(
                f"{self.location}: object exceeds {cap} bytes"
            )
        return body

    # -- low-level ----------------------------------------------------------

    def _request(self, extra_headers: dict, method: str = "GET"):
        headers = {**self._headers, **extra_headers}
        if self._signer is not None:
            # per-request SigV4: the signature covers every header sent
            # (incl. this request's Range), so each chunked GET signs
            # itself — signer is stateless/thread-safe for the pool
            headers = self._signer.sign(method, self._url, headers)
        req = urllib.request.Request(
            self._url, headers=headers, method=method
        )
        return urllib.request.urlopen(req, timeout=self._timeout_s)

    def _with_retries(self, fn):
        last: Exception | None = None
        for attempt in range(self._retries + 1):
            try:
                return fn()
            except urllib.error.HTTPError as e:
                if e.code in (404, 403, 401, 416):
                    raise RemoteIOError(
                        f"{self.location}: HTTP {e.code}", status=e.code
                    ) from e
                last = e
            except Exception as e:  # connection resets, timeouts
                last = e
            if attempt < self._retries:
                time.sleep(min(0.2 * (attempt + 1), 1.0))
        raise RemoteIOError(f"{self.location}: {last}") from last

    # -- ByteSource ---------------------------------------------------------

    def exists(self) -> bool:
        """True/False only for a definitive verdict; auth rejections and
        transport failures RAISE so callers never mistake a broken token
        or endpoint for a missing object."""
        try:
            self.size()
            return True
        except RemoteIOError as e:
            if e.status == 404:
                return False
            raise

    def size(self) -> int:
        if self._size is not None:
            return self._size
        if self._whole is not None:
            self._size = len(self._whole)
            return self._size

        def probe():
            # a 1-byte ranged GET beats HEAD: it also tells us whether the
            # server honours Range at all
            with self._request({"Range": "bytes=0-0"}) as resp:
                if resp.status == 206:
                    cr = resp.headers.get("Content-Range", "")
                    if "/" in cr:
                        return int(cr.rsplit("/", 1)[1]), None
                    # 206 without a parseable Content-Range: the 1-byte
                    # body must NOT be cached as the whole object —
                    # fall through to a plain full GET below
                else:
                    # 200: server ignored Range — body is the whole object
                    body = self._read_capped(resp)
                    return len(body), body
            with self._request({}) as resp:
                body = self._read_capped(resp)
                return len(body), body

        n, body = self._with_retries(probe)
        self._size = n
        if body is not None:
            self._whole = body
        return n

    def _get_range(self, start: int, end: int) -> bytes:
        def fetch():
            hdr = {"Range": f"bytes={start}-{end - 1}"}
            with self._request(hdr) as resp:
                if resp.status == 206:
                    return resp.read()
                # 200: server ignored Range — body is the whole object
                body = self._read_capped(resp)
                self._whole = body
                self._size = len(body)
                return body[start:end]

        return self._with_retries(fetch)

    def read_range(self, start: int, end: int, *, workers: int = 1) -> bytes:
        end = min(end, self.size())
        start = min(start, end)
        if end <= start:
            return b""
        if self._whole is not None:
            return self._whole[start:end]
        n = end - start
        if workers <= 1 or n <= self._chunk_bytes:
            return self._get_range(start, end)
        bounds = list(range(start, end, self._chunk_bytes)) + [end]
        with ThreadPoolExecutor(min(workers, len(bounds) - 1)) as pool:
            parts = list(
                pool.map(
                    lambda se: self._get_range(*se),
                    zip(bounds[:-1], bounds[1:]),
                )
            )
        return b"".join(parts)


def open_source(location: str | Path, **kwargs) -> ByteSource:
    loc = str(location)
    if loc.startswith(("http://", "https://", "s3://")):
        return HttpRangeSource(loc, **kwargs)
    if loc.startswith("file://"):
        return LocalFileSource(loc[len("file://"):])
    return LocalFileSource(loc)


def read_bytes(location: str | Path) -> bytes:
    """Whole-object read for any supported scheme (small control files:
    .tbi/.csi indexes, portable region files)."""
    return open_source(location).read_all()
