from .sources import (
    ByteSource,
    HttpRangeSource,
    LocalFileSource,
    RemoteIOError,
    is_remote,
    open_source,
    read_bytes,
)

__all__ = [
    "ByteSource",
    "HttpRangeSource",
    "LocalFileSource",
    "RemoteIOError",
    "is_remote",
    "open_source",
    "read_bytes",
]
