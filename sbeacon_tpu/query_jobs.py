"""Async variant-query job table: the VariantQuery state machine re-homed.

The reference tracks each distributed variant query in two DynamoDB tables
(reference: dynamodb.tf:100-149): ``VariantQueries`` — one row per query
with an atomic ``fanOut`` counter, start/end/elapsed times and a 5-minute
TTL (shared_resources/dynamodb/variant_queries.py:29-59) — and
``VariantQueryResponses`` — one row per worker result, spilling any body
over 300 KB to ``variant-queries/{uuid}.json`` in S3 with a 24-hour TTL
(performQuery/search_variants.py:282-300; s3.tf:22-28). Queries are keyed
by an md5 of the request (apiutils/request_hash.py:6-13) and a stubbed
``get_job_status`` (variant_queries.py:94-103 — always ``NEW``, "TODO
implement caching") decides whether to recompute.

Here the fan-out/fan-in apparatus is gone — one compiled program answers
the whole query (SURVEY.md §2.5) — but the *job* semantics remain useful
and are implemented for real rather than stubbed: request-hash keyed
jobs, RUNNING detection (concurrent identical queries coalesce), COMPLETE
result caching with TTL, spill-to-file for oversized response sets, and a
crash-surviving sqlite ledger (same pattern as ``ingest.ledger``). The
``fan_out``/``responses`` counters are kept per job for observability
parity with the reference's table schema.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import sqlite3
import threading
import time
import uuid
from collections import deque
from enum import Enum
from pathlib import Path

from concurrent.futures import ThreadPoolExecutor

from .config import ResilienceConfig
from .harness.faults import fault_point
from .payloads import VariantSearchResponse
from .resilience import (
    AdmissionController,
    Overloaded,
    current_deadline,
    deadline_scope,
)
from .telemetry import (
    annotate,
    current_context,
    percentiles,
    request_context,
)
from .utils.trace import span


class JobStatus(Enum):
    """reference: variant_queries.py:88-92 (EXPIRED is implicit there via
    the DynamoDB TTL delete; explicit here)."""

    COMPLETED = 1
    RUNNING = 2
    NEW = 3
    EXPIRED = 4


def hash_query(doc: dict | str) -> str:
    """Stable md5 of a request document — reference
    apiutils/request_hash.py:6-13 (sorted-key json of the event)."""
    if not isinstance(doc, str):
        doc = json.dumps(doc, sort_keys=True, default=str)
    return hashlib.md5(doc.encode()).hexdigest()


class QueryJobTable:
    """Sqlite-backed VariantQueries + VariantQueryResponses equivalent.

    Thread-safe within a process (one lock around the shared connection,
    matching ``ingest.ledger``); durable across restarts.
    """

    def __init__(
        self,
        path: str | Path = ":memory:",
        *,
        spill_dir: str | Path | None = None,
        query_ttl_s: float = 300.0,  # VariantQuery timeToExist: 5 min
        response_ttl_s: float = 24 * 3600.0,  # VariantQueryResponses: 24 h
        inline_limit: int = 300 * 1024,  # performQuery spill threshold
    ):
        self._conn = sqlite3.connect(str(path), check_same_thread=False)
        # WAL + NORMAL sync: commit cost drops from per-commit fsync to
        # WAL append — right durability trade for a TTL'd cache table (the
        # reference's DynamoDB was eventually consistent too); harmless
        # no-op for :memory:
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        # NO auto-checkpoint: whichever commit crosses the page
        # threshold absorbs the full checkpoint fsync — on the serving
        # thread that was a >1 s p99 outlier with warm kernels. The
        # runner's background purge sweep calls checkpoint() instead
        # (WAL growth bounded by one sweep interval of TTL'd cache
        # traffic).
        self._conn.execute("PRAGMA wal_autocheckpoint=0")
        self._lock = threading.Lock()
        self.spill_dir = Path(spill_dir) if spill_dir else None
        if self.spill_dir:
            self.spill_dir.mkdir(parents=True, exist_ok=True)
        self.query_ttl_s = query_ttl_s
        self.response_ttl_s = response_ttl_s
        self.inline_limit = inline_limit
        with self._lock:
            self._conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS variant_queries (
                    id TEXT PRIMARY KEY,
                    claim TEXT NOT NULL,
                    complete INTEGER NOT NULL DEFAULT 0,
                    fan_out INTEGER NOT NULL DEFAULT 0,
                    responses INTEGER NOT NULL DEFAULT 0,
                    responses_counter INTEGER NOT NULL DEFAULT 0,
                    start_time REAL NOT NULL,
                    end_time REAL,
                    elapsed_time REAL NOT NULL DEFAULT -1,
                    expires_at REAL NOT NULL
                );
                CREATE TABLE IF NOT EXISTS variant_query_responses (
                    query_id TEXT NOT NULL,
                    response_number INTEGER NOT NULL,
                    body TEXT,
                    spill_path TEXT,
                    expires_at REAL NOT NULL,
                    PRIMARY KEY (query_id, response_number)
                );
                """
            )
            self._conn.commit()
        # crash recovery: incomplete rows are claims held by workers of a
        # dead process — no thread in this (or any new) process will ever
        # complete them, so identical queries would stall on RUNNING for
        # up to the full TTL. Drop them (and their partial responses) now;
        # the reference analogue is the TTL delete, just not lazily.
        with self._lock, self._conn:
            stale = [
                qid
                for (qid,) in self._conn.execute(
                    "SELECT id FROM variant_queries WHERE complete = 0"
                )
            ]
            spilled = []
            for qid in stale:
                spilled += self._conn.execute(
                    "SELECT spill_path FROM variant_query_responses"
                    " WHERE query_id = ? AND spill_path IS NOT NULL",
                    (qid,),
                ).fetchall()
                self._conn.execute(
                    "DELETE FROM variant_queries WHERE id = ?", (qid,)
                )
                self._conn.execute(
                    "DELETE FROM variant_query_responses WHERE query_id = ?",
                    (qid,),
                )
        for (p,) in spilled:
            Path(p).unlink(missing_ok=True)

    # -- job lifecycle -------------------------------------------------------

    def get_job_status(self, query_id: str) -> JobStatus:
        """The un-stubbed version of reference variant_queries.py:94-103."""
        now = time.time()
        with self._lock:
            row = self._conn.execute(
                "SELECT complete, expires_at FROM variant_queries"
                " WHERE id = ?",
                (query_id,),
            ).fetchone()
        if row is None:
            return JobStatus.NEW
        complete, expires_at = row
        if now >= expires_at:
            return JobStatus.EXPIRED
        return JobStatus.COMPLETED if complete else JobStatus.RUNNING

    def start(self, query_id: str, *, fan_out: int = 0) -> str | None:
        """Claim a query id for execution; returns an opaque claim token,
        or None when an unexpired job already holds the claim (the
        concurrent-identical-query coalescing the reference's stub never
        delivered). All subsequent writes require the token, so a worker
        whose claim was reclaimed after TTL expiry cannot corrupt the new
        owner's job (the reference's conditional-expression ownership,
        summariseSlice/main.cpp:367-368, re-expressed)."""
        now = time.time()
        claim = uuid.uuid4().hex
        with self._lock, self._conn:
            spilled = self._conn.execute(
                "SELECT r.spill_path FROM variant_query_responses r"
                " JOIN variant_queries q ON q.id = r.query_id"
                " WHERE q.id = ? AND q.expires_at <= ?"
                " AND r.spill_path IS NOT NULL",
                (query_id, now),
            ).fetchall()
            purged = self._conn.execute(
                "DELETE FROM variant_queries WHERE id = ? AND expires_at <= ?",
                (query_id, now),
            )
            if purged.rowcount:
                self._conn.execute(
                    "DELETE FROM variant_query_responses WHERE query_id = ?",
                    (query_id,),
                )
            try:
                self._conn.execute(
                    "INSERT INTO variant_queries"
                    " (id, claim, fan_out, start_time, expires_at)"
                    " VALUES (?,?,?,?,?)",
                    (query_id, claim, fan_out, now, now + self.query_ttl_s),
                )
            except sqlite3.IntegrityError:
                return None
        for (p,) in spilled:
            Path(p).unlink(missing_ok=True)
        return claim

    def _owns(self, query_id: str, claim: str) -> bool:
        row = self._conn.execute(
            "SELECT 1 FROM variant_queries WHERE id = ? AND claim = ?",
            (query_id, claim),
        ).fetchone()
        return row is not None

    def next_response_number(self, query_id: str, claim: str) -> int:
        """Atomic increment — reference VariantQuery.getResponseNumber
        (variant_queries.py:45-50). 0 when the claim has been lost."""
        with self._lock, self._conn:
            if not self._owns(query_id, claim):
                return 0
            self._conn.execute(
                "UPDATE variant_queries SET responses_counter ="
                " responses_counter + 1 WHERE id = ?",
                (query_id,),
            )
            (n,) = self._conn.execute(
                "SELECT responses_counter FROM variant_queries WHERE id = ?",
                (query_id,),
            ).fetchone()
        return int(n)

    def put_response(
        self,
        query_id: str,
        response_number: int,
        resp: VariantSearchResponse,
        claim: str,
    ) -> bool:
        """Store one worker response, spilling past ``inline_limit`` —
        reference performQuery/search_variants.py:282-300. Refused (False)
        when the claim is no longer held."""
        body = resp.dumps()
        spill_path = None
        if len(body) > self.inline_limit and self.spill_dir is not None:
            spill_path = str(self.spill_dir / f"{uuid.uuid4()}.json")
            Path(spill_path).write_text(body)
            body = None
        fault_point("sqlite.commit", "put_response")
        now = time.time()
        with self._lock, self._conn:
            if not self._owns(query_id, claim):
                ok = False
            else:
                ok = True
                self._conn.execute(
                    "INSERT OR REPLACE INTO variant_query_responses"
                    " (query_id, response_number, body, spill_path,"
                    " expires_at) VALUES (?,?,?,?,?)",
                    (
                        query_id,
                        response_number,
                        body,
                        spill_path,
                        now + self.response_ttl_s,
                    ),
                )
        if not ok and spill_path:
            Path(spill_path).unlink(missing_ok=True)
        return ok

    def mark_finished(self, query_id: str, claim: str) -> int:
        """Atomic fan-in decrement; returns remaining fan_out — reference
        VariantQuery.markFinished (variant_queries.py:53-59)."""
        with self._lock, self._conn:
            if not self._owns(query_id, claim):
                return -1
            self._conn.execute(
                "UPDATE variant_queries SET responses = responses + 1,"
                " fan_out = fan_out - 1, end_time = ? WHERE id = ?",
                (time.time(), query_id),
            )
            (remaining,) = self._conn.execute(
                "SELECT fan_out FROM variant_queries WHERE id = ?",
                (query_id,),
            ).fetchone()
        return int(remaining)

    def complete(self, query_id: str, claim: str) -> bool:
        fault_point("sqlite.commit", "complete")
        now = time.time()
        with self._lock, self._conn:
            if not self._owns(query_id, claim):
                return False
            self._conn.execute(
                "UPDATE variant_queries SET complete = 1, end_time = ?,"
                " elapsed_time = ? - start_time WHERE id = ?",
                (now, now, query_id),
            )
        return True

    def abandon(self, query_id: str, claim: str) -> None:
        """Drop a failed job so its id reads NEW again — a crashed worker
        must not cache an empty result set as the answer (the reference's
        analogue: a lost slice simply stays pending and is re-run)."""
        with self._lock, self._conn:
            if not self._owns(query_id, claim):
                return
            spilled = self._conn.execute(
                "SELECT spill_path FROM variant_query_responses"
                " WHERE query_id = ? AND spill_path IS NOT NULL",
                (query_id,),
            ).fetchall()
            self._conn.execute(
                "DELETE FROM variant_queries WHERE id = ?", (query_id,)
            )
            self._conn.execute(
                "DELETE FROM variant_query_responses WHERE query_id = ?",
                (query_id,),
            )
        for (p,) in spilled:
            Path(p).unlink(missing_ok=True)

    def wait(self, query_id: str, timeout_s: float = 600.0) -> bool:
        """Poll fan_out==0 / complete — the reference's fan-in loop
        (variantutils/search_variants.py:130-141), REQUEST_TIMEOUT 600 s.
        Clamped by the caller's ambient request deadline: a 600 s poll
        budget never outlives the request it serves."""
        timeout_s = current_deadline().clamp(timeout_s)
        deadline = time.time() + timeout_s
        delay = 0.002
        while time.time() < deadline:
            status = self.get_job_status(query_id)
            if status is JobStatus.COMPLETED:
                return True
            if status in (JobStatus.NEW, JobStatus.EXPIRED):
                return False
            time.sleep(delay)
            delay = min(delay * 2, 0.1)
        return False

    # -- results -------------------------------------------------------------

    def get_responses(self, query_id: str) -> list[VariantSearchResponse]:
        """Rehydrate all responses (spilled bodies read back from disk) —
        reference search_variants.py:142-155 batch_get + S3 fetch."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT body, spill_path FROM variant_query_responses"
                " WHERE query_id = ? ORDER BY response_number",
                (query_id,),
            ).fetchall()
        out = []
        for body, spill_path in rows:
            if body is None and spill_path:
                body = Path(spill_path).read_text()
            if body is not None:
                out.append(VariantSearchResponse.loads(body))
        return out

    def info(self, query_id: str) -> dict | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, complete, fan_out, responses, responses_counter,"
                " start_time, end_time, elapsed_time, expires_at"
                " FROM variant_queries WHERE id = ?",
                (query_id,),
            ).fetchone()
        if row is None:
            return None
        keys = (
            "id",
            "complete",
            "fan_out",
            "responses",
            "responses_counter",
            "start_time",
            "end_time",
            "elapsed_time",
            "expires_at",
        )
        return dict(zip(keys, row))

    def purge_expired(self) -> int:
        """TTL enforcement — the DynamoDB TTL delete + S3 lifecycle rule
        (dynamodb.tf:111-115,144-148; s3.tf:22-28)."""
        now = time.time()
        with self._lock, self._conn:
            spilled = self._conn.execute(
                "SELECT spill_path FROM variant_query_responses"
                " WHERE expires_at <= ? AND spill_path IS NOT NULL",
                (now,),
            ).fetchall()
            n = self._conn.execute(
                "DELETE FROM variant_queries WHERE expires_at <= ?", (now,)
            ).rowcount
            n += self._conn.execute(
                "DELETE FROM variant_query_responses WHERE expires_at <= ?",
                (now,),
            ).rowcount
        for (p,) in spilled:
            Path(p).unlink(missing_ok=True)
        return n

    def checkpoint(self) -> None:
        """WAL checkpoint + truncate — called from the runner's
        background sweep so no serving-thread commit ever absorbs the
        checkpoint fsync (auto-checkpoint is disabled)."""
        with self._lock:
            self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class AsyncQueryRunner:
    """Background execution + result caching over a :class:`QueryJobTable`.

    ``submit`` hashes the payload, coalesces concurrent identical queries,
    runs ``engine.search`` on a worker thread, stores the per-(dataset,vcf)
    response set through the job table (spill included), and completes the
    job; ``poll``/``result`` give the async API surface the reference's
    RUNNING/COMPLETED envelope switch needs
    (route_g_variants.py:199-214 elif status == JobStatus.RUNNING).
    """

    #: seconds between opportunistic TTL sweeps piggybacked on submit()
    PURGE_INTERVAL_S = 60.0
    #: in-memory lifetime of a PARTIAL (replicas-down, degraded) result:
    #: long enough to hand to the waiters coalesced onto the job, far
    #: too short to serve as a cached answer after the routes heal
    PARTIAL_HANDOFF_TTL_S = 5.0

    def __init__(
        self,
        engine,
        table: QueryJobTable,
        *,
        workers: int | None = None,
        max_pending: int | None = None,
    ):
        self.engine = engine
        self.table = table
        res = getattr(
            getattr(engine, "config", None), "resilience", None
        )
        # explicit None checks, not `or`: a configured 0 must fail
        # loudly (ThreadPoolExecutor / AdmissionController raise), not
        # silently coerce to the default. Fallback defaults read the
        # ResilienceConfig field declarations — ONE source, so an env
        # override (BEACON_SHED_RETRY_AFTER_S etc.) can never diverge
        # between the server gate and this runner gate.
        if workers is None:
            workers = getattr(
                res, "runner_workers", ResilienceConfig.runner_workers
            )
        if max_pending is None:
            max_pending = getattr(
                res, "runner_max_pending", ResilienceConfig.runner_max_pending
            )
        self.workers = workers
        self.max_pending = max_pending
        self.shed_retry_after_s = getattr(
            res, "shed_retry_after_s", ResilienceConfig.shed_retry_after_s
        )
        # lane-aware admission (shaping.py lanes): the bulk lane may
        # hold at most this share of the pending slots, so a record-
        # retrieval flood saturates its share while interactive
        # submissions keep admitting
        bulk_share = getattr(
            res, "runner_bulk_share", ResilienceConfig.runner_bulk_share
        )
        self._bulk_cap = max(1, int(self.max_pending * bulk_share))
        self._bulk_active = 0
        # single-flight observability: identical in-flight queries
        # collapsed onto a leader's pending result
        self._coalesced = 0
        # bounded pool, NOT thread-per-query: a flood of distinct
        # queries used to spawn one unbounded thread each — under
        # adversarial load that is a fork bomb with extra steps. The
        # pool bounds concurrency; the admission gate bounds the queue
        # behind it (excess submissions shed 429, never silently pile
        # up) — same mechanism as the server-level gate, acquired here
        # and released from the pool thread.
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="query-runner"
        )
        self._gate = AdmissionController(
            self.max_pending, retry_after_s=self.shed_retry_after_s
        )
        # in-process completion events: waiters block on these instead of
        # polling sqlite; cross-process (or post-restart) waiters fall
        # back to the table's poll loop
        self._done: dict[str, threading.Event] = {}
        # in-process result handoff: (responses, expiry) — waiters read
        # these directly, skipping the sqlite round-trip + re-parse
        self._results: dict[str, tuple[list, float]] = {}
        self._lock = threading.Lock()
        self._last_purge = time.time()
        self._sweeper: threading.Thread | None = None
        # admission-wait decomposition: submit -> execution start on
        # the bounded pool (the stage BEFORE the batcher's queue wait).
        # Ring for exact percentiles; the runner.queue_wait_ms
        # histogram feeds once an app registry wires it
        self._wait_ms: deque = deque(maxlen=4096)
        self._wait_hist = None

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def metrics(self) -> dict:
        gate = self._gate.metrics()
        with self._lock:
            coalesced, bulk_active = self._coalesced, self._bulk_active
        return {
            "workers": self.workers,
            "max_pending": self.max_pending,
            "active": gate["in_flight"],
            "shed": gate["shed"],
            "coalesced": coalesced,
            "bulk_active": bulk_active,
            "bulk_cap": self._bulk_cap,
        }

    def register_metrics(self, registry) -> None:
        """The runner pool's typed instruments (its slice of the old
        hand-assembled ``/metrics`` dict, now stable named series)."""
        registry.gauge(
            "runner.workers",
            "async query runner pool size",
            fn=lambda: self.workers,
        )
        registry.gauge(
            "runner.max_pending",
            "runner admission cap",
            fn=lambda: self.max_pending,
        )
        registry.gauge(
            "runner.active",
            "queries executing or queued in the runner",
            fn=lambda: self._gate.metrics()["in_flight"],
        )
        registry.counter(
            "runner.shed",
            "runner submissions shed with 429",
            fn=lambda: self._gate.metrics()["shed"],
        )
        registry.counter(
            "runner.coalesced",
            "identical in-flight queries collapsed onto a leader",
            fn=lambda: self._coalesced,
        )
        registry.gauge(
            "runner.bulk_active",
            "bulk-lane submissions holding runner slots",
            fn=lambda: self._bulk_active,
        )
        # the admission-wait slice of the queue-wait decomposition
        # (/debug/status composes it ahead of the batcher stages)
        self._wait_hist = registry.histogram(
            "runner.queue_wait_ms",
            "async-runner submit -> execution-start wait",
        )

    def _note_coalesced(self) -> None:
        with self._lock:
            self._coalesced += 1
        annotate(query_job="coalesced")

    def _release_bulk(self, bulk_slot: bool) -> None:
        if bulk_slot:
            with self._lock:
                self._bulk_active -= 1

    def _note_queue_wait(self, wait_ms: float) -> None:
        with self._lock:
            self._wait_ms.append(wait_ms)
        h = self._wait_hist
        if h is not None:
            h.observe(wait_ms)

    def queue_wait_summary(self) -> dict:
        """Percentiles of the runner's admission wait over the bounded
        ring (empty dict before any async execution) — same summary
        semantics as every other stage in /debug/status."""
        with self._lock:
            xs = list(self._wait_ms)
        return percentiles(xs)

    def _maybe_purge(self) -> None:
        now = time.time()
        with self._lock:
            if now - self._last_purge < self.PURGE_INTERVAL_S:
                return
            # one sweeper at a time: a slow sweep (WAL checkpoint on a
            # busy disk) must not stack a fresh thread every interval
            if self._sweeper is not None and self._sweeper.is_alive():
                self._last_purge = now  # re-check next interval, not
                return  # on every submit meanwhile

            # the sweep DELETEs + commits — run it off the serving
            # thread (piggybacked purges used to stall ~1 request per
            # minute by a full fsync; the r5 soak tail caught it)
            def sweep():
                self.table.purge_expired()
                self.table.checkpoint()
                with self._lock:
                    dead = [
                        q
                        for q, (_, exp) in self._results.items()
                        if exp <= now
                    ]
                    for q in dead:
                        del self._results[q]

            self._last_purge = now
            t = threading.Thread(
                target=sweep, name="query-jobs-purge", daemon=True
            )
            self._sweeper = t
        t.start()

    def submit(
        self, payload, *, fingerprint: str | None = None
    ) -> tuple[str, JobStatus]:
        """``fingerprint`` (e.g. the engine's index fingerprint) is folded
        into the query hash so cached results die with the data they were
        computed from."""
        self._maybe_purge()
        query_id = hash_query(
            {"payload": dataclasses.asdict(payload), "fp": fingerprint}
        )
        # in-memory results are authoritative the moment the search
        # finished — the table may still be mid-persistence (background)
        with self._lock:
            hit = self._results.get(query_id)
        if hit is not None and hit[1] > time.time():
            # job-layer outcome notes (telemetry): a repeat served here
            # never reaches engine.search, so the slow-query log would
            # otherwise show an unexplained fast request
            annotate(query_job="memory_hit")
            return query_id, JobStatus.COMPLETED
        status = self.table.get_job_status(query_id)
        if status is JobStatus.COMPLETED:
            annotate(query_job="table_hit")
            return query_id, status
        if status is JobStatus.RUNNING:
            # single-flight: coalesce onto the in-flight execution —
            # consumes no pool slot, so it must happen before the
            # capacity gate (and before the bulk-lane cap: a follower
            # attaches to the leader's pending result, it adds no work)
            self._note_coalesced()
            return query_id, status
        # lane-aware admission: the ambient lane note (set by the API
        # layer's classifier) decides whether this submission draws
        # from the bulk share of the pending slots
        ctx = current_context()
        lane = (ctx.notes.get("lane") if ctx is not None else None) or (
            "interactive"
        )
        bulk_slot = False
        if lane == "bulk":
            with self._lock:
                if self._bulk_active >= self._bulk_cap:
                    raise Overloaded(
                        f"query runner bulk lane at capacity "
                        f"({self._bulk_cap} of {self.max_pending} slots)",
                        retry_after_s=self.shed_retry_after_s,
                    )
                self._bulk_active += 1
                bulk_slot = True
        # reserve a pool slot BEFORE claiming: shedding after a claim
        # would leave the job RUNNING with nobody executing it, stalling
        # coalesced waiters for the full TTL. Coalescing onto an
        # existing claim consumes no slot and is never shed.
        if not self._gate.try_acquire():
            self._release_bulk(bulk_slot)
            raise Overloaded(
                f"query runner at capacity ({self.max_pending} pending)",
                retry_after_s=self.shed_retry_after_s,
            )
        try:
            claim = self.table.start(query_id, fan_out=1)
        except BaseException:
            # a failed claim (sqlite locked, disk full) must release
            # the reserved slot, or leaks accumulate until every
            # submit sheds 429 against an idle pool
            self._gate.release()
            self._release_bulk(bulk_slot)
            raise
        if claim is None:
            # someone else holds an unexpired claim: coalesce
            self._gate.release()
            self._release_bulk(bulk_slot)
            self._note_coalesced()
            return query_id, JobStatus.RUNNING

        pl = dataclasses.replace(payload, query_id=query_id)
        done = threading.Event()
        with self._lock:
            self._done[query_id] = done
            self._results.pop(query_id, None)
        # the SPAWNING request's deadline rides into the worker thread
        # (thread-locals don't cross): the search abandons at its next
        # check-point once the deadline lapses — worker calls clamp,
        # expired batches refuse to launch. A coalescer with a longer
        # deadline simply sees the abandoned job and falls back to a
        # direct search under its own deadline. The request context
        # (trace id + outcome notes) crosses the same way, so spans
        # recorded on the pool thread — and the trace header on any
        # coordinator->worker hop — keep the ingress trace id.
        job_deadline = current_deadline()
        job_ctx = current_context()
        t_enqueue = time.perf_counter()

        def run():
            self._note_queue_wait(
                (time.perf_counter() - t_enqueue) * 1e3
            )
            with request_context(job_ctx), span(
                "query_jobs.run", query_id=query_id
            ):
                try:
                    with deadline_scope(job_deadline):
                        responses = self.engine.search(pl)
                    # a DEGRADED answer (some datasets had no reachable
                    # replica — dispatch annotated unavailable_datasets
                    # on the request context) must not be cached as THE
                    # answer for the query TTL: it is handed to the
                    # waiters coalesced onto this job, then the job is
                    # dropped so later identical queries re-execute
                    # against the (possibly healed) routes instead of
                    # replaying a stale empty result
                    unavailable = tuple(
                        job_ctx.notes.get("unavailable_datasets") or ()
                        if job_ctx is not None
                        else ()
                    )
                    partial = bool(unavailable)
                    ttl = (
                        self.PARTIAL_HANDOFF_TTL_S
                        if partial
                        else self.table.query_ttl_s
                    )
                    # the unavailable set rides WITH the cached handoff:
                    # a coalesced waiter (different request context)
                    # must get the partial marking too, not a silently
                    # incomplete answer
                    with self._lock:
                        self._results[query_id] = (
                            responses,
                            time.time() + ttl,
                            unavailable,
                        )
                    # waiters are served from the in-memory handoff the
                    # moment the search finishes; the sqlite persistence
                    # below exists for cross-process/restart consumers
                    # and must not sit on the request's critical path
                    # (a WAL checkpoint fsync here was a >1 s soak-tail
                    # outlier with the kernels fully warm)
                    done.set()
                    if partial:
                        self.table.abandon(query_id, claim)
                    else:
                        for resp in responses:
                            n = self.table.next_response_number(
                                query_id, claim
                            )
                            if n:
                                self.table.put_response(
                                    query_id, n, resp, claim
                                )
                        self.table.mark_finished(query_id, claim)
                        self.table.complete(query_id, claim)
                except Exception:
                    # never cache a failure as an empty result: drop the
                    # job so pollers fall back to a direct search (which
                    # surfaces the real error to the caller)
                    logging.getLogger(__name__).exception(
                        "async query %s failed", query_id
                    )
                    with self._lock:
                        self._results.pop(query_id, None)
                    self.table.abandon(query_id, claim)
                finally:
                    done.set()
                    self._gate.release()
                    self._release_bulk(bulk_slot)
                    with self._lock:
                        self._done.pop(query_id, None)

        try:
            self._pool.submit(run)
        except RuntimeError:
            # pool shut down (close() raced a late submit): release
            # everything so the job doesn't read RUNNING forever
            self._gate.release()
            self._release_bulk(bulk_slot)
            with self._lock:
                self._done.pop(query_id, None)
            self.table.abandon(query_id, claim)
            raise
        return query_id, JobStatus.RUNNING

    def poll(self, query_id: str) -> JobStatus:
        return self.table.get_job_status(query_id)

    def result(
        self, query_id: str, *, wait_s: float = 0.0
    ) -> list[VariantSearchResponse] | None:
        """Responses if COMPLETED (optionally waiting), else None.
        The wait is clamped by the caller's ambient request deadline."""
        if wait_s > 0:
            wait_s = current_deadline().clamp(wait_s)
            with self._lock:
                ev = self._done.get(query_id)
                handed_off = query_id in self._results
            if ev is not None:
                # in-process job: block on its completion event (no poll)
                ev.wait(wait_s)
            elif not handed_off and not self.table.wait(
                query_id, timeout_s=wait_s
            ):
                # no in-memory handoff either; the table never
                # completed (a PARTIAL job is abandoned there by
                # design, so the handoff check must come first)
                return None
        # in-memory handoff FIRST: for in-process jobs the results exist
        # the moment the search finishes, before (and regardless of) the
        # background sqlite persistence
        with self._lock:
            hit = self._results.get(query_id)
        if hit is not None and hit[1] > time.time():
            if len(hit) > 2 and hit[2]:
                # replay the partial marking onto THIS caller's request
                # context — the job thread annotated the submitter's,
                # and a coalesced waiter has its own
                annotate(unavailable_datasets=hit[2])
            return hit[0]
        if self.table.get_job_status(query_id) is not JobStatus.COMPLETED:
            return None
        return self.table.get_responses(query_id)
