"""Typed configuration for the whole framework.

The reference spreads configuration over three tiers — terraform variables,
per-lambda environment variables assembled from shared locals, and in-code
constants (reference: variables.tf:1-54, main.tf:24-63, splitQuery
SPLIT_SIZE=10000, variantutils THREADS=500, main.tf:16-17 data ceilings).
Here the same three semantic groups live in one typed config object; env vars
can still override (``BeaconConfig.from_env``) so deployments keep the same
knob surface.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path

#: THE falsy spellings for boolean env knobs — ``from_env`` and every
#: module that reads a BEACON_* flag directly (parallel/mesh.py's
#: BEACON_MESH_SLICE default) share this one set, so an env value can
#: never mean "off" to one reader and "on" to another
ENV_OFF = ("0", "false", "no", "off")


@dataclasses.dataclass(frozen=True)
class BeaconInfo:
    """Beacon identity — reference: variables.tf + getInfo env block."""

    beacon_id: str = "org.tpu.beacon"
    beacon_name: str = "TPU Native Beacon"
    api_version: str = "v2.0.0"
    environment: str = "dev"
    description: str = "TPU-native GA4GH Beacon v2 implementation"
    version: str = "v2.0"
    welcome_url: str = ""
    alternative_url: str = ""
    org_id: str = "TPU"
    org_name: str = "TPU Beacon"
    org_description: str = ""
    org_address: str = ""
    org_welcome_url: str = ""
    org_contact_url: str = ""
    org_logo_url: str = ""
    default_granularity: str = "boolean"
    uri: str = "http://localhost:5000"


@dataclasses.dataclass(frozen=True)
class StorageConfig:
    """On-disk layout re-homing the reference's S3/DynamoDB/Athena stores.

    Every stateful contract in the reference maps to an explicit local path
    (SURVEY.md section 2.4): the variants bucket's ``vcf-summaries/`` index
    prefix -> ``index_dir``; the metadata bucket's ORC tables + Athena
    database -> ``metadata_db`` (sqlite); the DynamoDB control tables
    (Datasets, VcfSummaries, VariantQueries, ...) -> ``ledger_db`` (sqlite);
    ontology tables (Ontologies/Anscestors/Descendants/OntoIndex) ->
    ``ontology_db``.
    """

    root: Path = Path("./beacon_data")

    @property
    def index_dir(self) -> Path:
        return self.root / "variant-index"

    @property
    def metadata_db(self) -> Path:
        return self.root / "metadata.sqlite"

    @property
    def ledger_db(self) -> Path:
        return self.root / "ledger.sqlite"

    @property
    def ontology_db(self) -> Path:
        return self.root / "ontology.sqlite"

    @property
    def query_results_dir(self) -> Path:
        """Async query result spill (reference: variant-queries/ S3 prefix)."""
        return self.root / "query-results"

    def ensure(self) -> "StorageConfig":
        for p in (self.root, self.index_dir, self.query_results_dir):
            p.mkdir(parents=True, exist_ok=True)
        return self


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Query/ingest engine tuning — the reference's in-code constants tier.

    window_cap: max candidate rows gathered per query around the searchsorted
      hit range (replaces the reference's 10kb-window x unbounded-scan shape,
      splitQuery SPLIT_SIZE=10000, with a fixed-shape gather the XLA compiler
      can tile).
    record_cap: max matched rows returned per query for record granularity
      (two-pass host fallback on overflow).
    ingest_shard_bytes: target uncompressed bytes per ingest slice
      (reference: summariseVcf cost-model; ABS_MAX_DATA_SPLIT 750MB,
      main.tf:16).
    max_index_rows_per_shard: device-side padding unit for index shards.
    """

    window_cap: int = 2048
    record_cap: int = 1024
    batch_size: int = 1024
    # mesh serving (SURVEY.md §2.5 fan-in mapping): when >1 device is
    # visible, multi-dataset queries run as ONE pjit program over the
    # dataset-sharded stack with psum fan-in (parallel/mesh.py) instead
    # of per-shard thread scatter; single-device falls back to scatter
    use_mesh: bool = True
    # pod-local SPMD dispatch (parallel/dispatch.py MeshDispatchTier):
    # a DistributedEngine with a local engine consults the tier per
    # query — dataset groups resolvable on the local device mesh ride
    # ONE compiled launch (mesh-sharded fused index, on-device fan-in
    # + hit-row gather) instead of the thread/HTTP scatter.
    # mesh_min_shards is the smallest per-query target count worth the
    # mesh path (below it, per-shard dispatch is already one launch).
    mesh_dispatch: bool = True
    mesh_min_shards: int = 2
    # per-device query-batch slicing on the mesh tier (ISSUE 13): the
    # encoded batch is sharded by owning device (owner-sorted permute,
    # per-device counts padded to a shared tier) so each device
    # evaluates only the queries targeting its shards — ~1/n_dev the
    # per-device work — instead of the full replicated batch masked by
    # ownership. Off restores the replicated layout.
    mesh_slice: bool = True
    # owner-sharded mesh outputs (ISSUE 17, the output diet): under
    # the sliced layout every query is answered by exactly ONE owning
    # device, so the launch returns its outputs owner-sharded
    # (out_specs P('d')) — no psum fan-in, no ring row-gather, and the
    # fetch pulls each owner's real rows directly instead of one
    # full-size replicated buffer (~1/n_dev the fetched bytes). Off
    # restores the replicated-output reassembly. No effect on the
    # replicated batch layout (mesh_slice off), which genuinely needs
    # the cross-device combine.
    mesh_owner_outputs: bool = True
    # stack the genotype planes with their datasets on the mesh tier
    # when every shard has them and the per-device slice fits the
    # plane_hbm_budget_gb headroom: selected-samples / sample-
    # extraction shapes then ride the same single launch (per-query
    # sample masks reduced on the owning device) instead of falling
    # back to per-dataset dispatch.
    mesh_planes: bool = True
    ingest_shard_bytes: int = 64 * 1024 * 1024
    ingest_workers: int = 8
    max_response_inline_bytes: int = 300 * 1024  # performQuery spill threshold
    request_timeout_s: float = 600.0  # variantutils REQUEST_TIMEOUT
    mesh_axis: str = "d"
    use_tpu: bool = True
    # serving micro-batcher (SURVEY.md §7): with wait=0 the leader runs
    # immediately and batches form from requests queuing behind an
    # in-flight kernel launch (continuous batching); raise wait_ms to
    # trade single-query latency for fuller batches
    microbatch: bool = True
    microbatch_max: int = 512
    microbatch_wait_ms: float = 0.0
    # launched-but-unfetched kernel batches allowed per accumulator:
    # the launch/fetch overlap window (serving.py pipeline). 1 = fully
    # serial launch->fetch (pre-fusion behavior); 2 double-buffers so
    # host encode of batch i+1 overlaps device execution of batch i
    fetch_pipeline_depth: int = 2
    # entries kept per timing ring (MicroBatcher wait/exec/stage
    # decompositions) — bounds a long soak's memory, timing_summary()
    # reports percentiles over this window
    timing_window: int = 65536
    # cross-shard fused dispatch: stack every warm device shard into
    # ONE device index (ops.kernel.FusedDeviceIndex) so a k-dataset
    # query costs one launch and concurrent queries against DIFFERENT
    # datasets coalesce into the same micro-batch. Costs a second
    # device-resident copy of the stacked columns (~48 B/row), so the
    # stack is skipped beyond fused_max_rows total rows (~3 GB at the
    # default).
    fused_dispatch: bool = True
    fused_max_rows: int = 64_000_000
    # response cache (response_cache.py): LRU in front of
    # engine.search keyed on (index fingerprint, normalized query,
    # response shaping); negative results cache too. size<=0 or
    # enabled=False disables; ttl_s=0 means no expiry.
    response_cache: bool = True
    response_cache_size: int = 4096
    response_cache_ttl_s: float = 300.0
    # chunk size for staged genotype-plane H2D uploads (plane_kernel):
    # planes larger than one chunk upload as pre-staged contiguous
    # chunks whose transfers overlap, instead of one giant synchronous
    # copy (the 28 MB/s config7 upload wall). <=0 disables chunking.
    plane_upload_chunk_mb: int = 256
    # device-resident genotype planes (selected-samples leaf): upload a
    # shard's bit planes to HBM when their padded size fits the budget;
    # oversized plane sets stay host-resident (round-3 numpy path). The
    # budget leaves room for the column tiles + kernel workspace on a
    # 16 GB v5e.
    device_planes: bool = True
    plane_hbm_budget_gb: float = 11.0
    # region/dataset-scoped response-cache invalidation (ingest-while-
    # serving): a publish evicts only cached entries whose dataset set
    # AND coordinate bracket overlap the new rows, instead of dropping
    # the whole cache. Off restores the wholesale clear-on-publish.
    scoped_invalidation: bool = True
    # L0 delta-tail mini-index (ISSUE 15, the LSM memtable->L0 tier):
    # past EITHER threshold — tail depth in shards, or total tail rows
    # — a key's standing delta tail is stacked into a secondary fused
    # device index served by ONE batched launch, so deep tails stop
    # paying a per-shard host scan per query. 0 disables that trigger;
    # both 0 disables the L0 tier outright (every tail shard host-
    # scans, the pre-ISSUE-15 behaviour).
    l0_min_shards: int = 4
    l0_min_rows: int = 4096


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Slice-planning cost model (reference: summariseVcf constants
    :21-25 and the ABS_MAX_DATA_SPLIT / VCF_S3_OUTPUT_SIZE_LIMIT terraform
    ceilings, main.tf:16-17). The planner minimises total_time x cost over
    slice size — here 'dispatch' is a thread-pool task instead of an SNS
    message + lambda cold start, so the constants default far cheaper, but
    the optimiser itself is the same math."""

    min_task_time: float = 0.005  # MIN_SS_TIME (s)
    scan_rate: float = 200_000_000  # SS_RATE (compressed B/s, host parse)
    dispatch_cost: float = 0.0005  # SNS_TIME equivalent (s/task)
    max_concurrency: int = 64  # MAX_CONCURRENCY
    workers: int = 8  # parallel slice workers
    max_range_bytes: int = 750 * 1024 * 1024  # ABS_MAX_DATA_SPLIT
    # also materialise reference-layout binary region files per VCF
    # (vcf-summaries/ portable exchange format, index/portable.py)
    export_portable: bool = True
    # remote slice-scan workers (the reference's <=1000-lambda
    # summariseSlice fan-out): slice jobs scatter round-robin across
    # these worker URLs; empty = scan on this host's thread pool
    scan_worker_urls: tuple[str, ...] = ()
    scan_timeout_s: float = 120.0  # per-slice worker call budget
    scan_retries: int = 1  # extra workers tried before local fallback
    # ingest-while-serving (delta shards + background compaction):
    # stream_deltas publishes each completed slice of a FIRST-TIME
    # summarisation to the engine immediately as a queryable delta
    # shard (read-your-writes before the merge barrier); the base
    # publish is deferred to the compactor so the fused/mesh stacks and
    # the response cache are not demolished per submit. delta_max_shards
    # is the per-(dataset, vcf) delta-tail depth that kicks an early
    # compaction; compact_interval_s is the background compactor's
    # cadence (<=0 disables the thread — folds then only run on the
    # depth trigger or an explicit run_once()).
    stream_deltas: bool = True
    delta_max_shards: int = 8
    compact_interval_s: float = 30.0
    # size-tiered compaction (ISSUE 15): >0 arms the tiered fold
    # policy — raw delta tails fold into intermediate L1 artifacts
    # (persisted, epoch-ranged, adoptable after a crash) and the full
    # base merge only runs once the accumulated L1 bytes reach this
    # ratio of the base's bytes, so per-fold write amplification stops
    # scaling with base size. <=0 selects the legacy policy: every
    # fold is a full base merge. Tiered is the DEFAULT since ISSUE 20
    # (the config22 churn soak in BENCH_wirespeed: sustained multi-key
    # ingest folds L1 per trigger, base merges only at the ratio, GC
    # stays bounded); set BEACON_COMPACT_BASE_RATIO=0 to get the
    # legacy merge-every-fold behaviour back.
    compact_base_ratio: float = 0.35
    # superseded base/L1 artifacts are parked in a per-key .retired/
    # dir at each base merge and the newest N generations are kept;
    # older ones are GC'd (ingest.gc_bytes counts the reclaim). GC
    # only ever touches .retired/ — a serving artifact can never be
    # deleted.
    artifact_retain: int = 2
    # defer the end-of-summarisation BASE publish to the compactor
    # cadence as well (continuous-ingest mode): submits then never pay
    # a fingerprint bump / stack rebuild inline — the standing deltas
    # serve until the next fold. Off (default) keeps the base publish
    # at the end of each summarisation (identical post-submit state to
    # the pre-delta write path; slices still stream mid-scan).
    defer_base_publish: bool = False


# canonical external-service endpoints (reference indexer:40-42); the
# resolver clients in metadata/resolvers.py import these — single source
DEFAULT_OLS_URL = "https://www.ebi.ac.uk/ols/api/ontologies"
DEFAULT_ONTOSERVER_URL = (
    "https://r4.ontoserver.csiro.au/fhir/ValueSet/$expand"
)


@dataclasses.dataclass(frozen=True)
class ResolverConfig:
    """External ontology resolution (the indexer's OLS/Ontoserver calls,
    reference indexer/lambda_function.py:40-42). Off by default: an
    air-gapped deployment must not stall submissions on network timeouts;
    closures can also be loaded offline via OntologyStore."""

    enabled: bool = False
    ols_url: str = DEFAULT_OLS_URL
    ontoserver_url: str = DEFAULT_ONTOSERVER_URL
    workers: int = 8


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Failure envelope (resilience.py) — the knobs the reference got
    from the platform tier: API Gateway's 29 s hard timeout ->
    ``default_deadline_s``; Lambda reserved concurrency / API-GW
    throttling -> ``max_in_flight``; invoke retry + backoff ->
    the circuit breaker triple.

    default_deadline_s: request deadline when the client sends no
      ``X-Beacon-Deadline`` header; 0 disables. Ingest (``/submit``)
      is exempt from the *default* — a bulk VCF scan is a batch job,
      not a request — but an explicit header still applies there.
    batch_timeout_s: micro-batch submit bound — even deadline-less
      callers cannot block on a wedged kernel launch forever.
    max_in_flight: admission cap; excess requests answer 429 +
      Retry-After instead of queueing.
    runner_workers / runner_max_pending: the async query runner's
      bounded pool (replaces thread-per-query) and its shed threshold.
    breaker_*: consecutive-failure circuit breaker on per-worker routes.
    failover_retries: extra replicas a failed worker-search leg may
      re-route to (never the same copy twice) before its datasets fall
      to the partial-results path.
    partial_results: when no replica of a dataset is reachable, answer
      with the datasets that responded and mark the rest in the
      envelope (``meta.unavailableDatasets`` + a warning) instead of
      failing the whole request; off restores fail-the-query semantics.
    """

    default_deadline_s: float = 60.0
    batch_timeout_s: float = 60.0
    max_in_flight: int = 256
    shed_retry_after_s: float = 1.0
    runner_workers: int = 8
    runner_max_pending: int = 64
    # share of runner_max_pending the bulk lane may hold (lane-aware
    # admission, shaping.py lanes): record-retrieval floods saturate at
    # this fraction while interactive submissions keep the rest
    runner_bulk_share: float = 0.5
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 30.0
    breaker_half_open_probes: int = 1
    failover_retries: int = 2
    partial_results: bool = True


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Coordinator->worker data-plane knobs (parallel/transport.py).

    The reference's fan-out rode SNS + Lambda invokes, paying per-call
    setup at the platform tier; here the same costs are explicit TCP
    handshakes and JSON bytes, and each has a knob:

    pool_size: keep-alive connections kept per worker host. Not a
      concurrency cap — a scatter burst beyond it opens extra
      connections that are closed, not pooled, on return.
    idle_ttl_s: pooled connections idle longer than this are closed on
      next touch (workers reap their side slightly later).
    gzip_min_bytes: request bodies at or over this size are
      gzip-compressed on the wire (0 disables).
    hedge_delay_s: request hedging (Dean & Barroso, The Tail at
      Scale): if a call's primary worker has not answered within this
      delay, the same call is raced on a second worker and the first
      response wins. >0 = fixed delay; 0 = adaptive (the p95 of recent
      RTTs, once enough samples exist); <0 disables. Governs both
      ingest slice scans and (with ``replica_hedge``) full /search
      calls across replicas.
    bool_short_circuit: boolean-granularity fan-outs return as soon as
      any worker reports a hit, abandoning the rest of the scatter.
    replica_hedge: hedge slow /search primaries with a second replica
      of the same datasets (``hedge_delay_s`` semantics unchanged);
      single-replica fleets never hedge.
    """

    pool_size: int = 4
    idle_ttl_s: float = 60.0
    gzip_min_bytes: int = 32 * 1024
    hedge_delay_s: float = 0.0
    bool_short_circuit: bool = True
    replica_hedge: bool = True


@dataclasses.dataclass(frozen=True)
class ShapingConfig:
    """Traffic shaping & brownout (shaping.py) — the explicit version
    of the reference's platform tier (API Gateway usage-plan throttling
    + Lambda reserved concurrency): weighted fair queueing across
    tenants, priority lanes, adaptive Retry-After, and an SLO-driven
    brownout ladder.

    enabled: the whole layer on/off (off restores the PR-1 global-gate
      behaviour).
    tenant_header: header carrying an explicit tenant id; requests
      without it bucket by Authorization hash, else ``anon``.
    tenant_weights: ``tenant=weight`` comma list for the DRR drain
      ratio (``gold=4,free=1``); unlisted tenants get
      ``default_weight``.
    tenant_max_in_flight / tenant_queue_depth: per-tenant running cap
      and per-tenant per-lane queue bound; a full queue sheds 429 with
      the adaptive Retry-After.
    max_queue_wait_s: a queued request not granted within this bound
      sheds (its request deadline may cut earlier -> 504).
    bulk_starvation_ms: a bulk waiter older than this is served ahead
      of the interactive lane (one per dispatch pass) — the escape
      hatch that keeps strict lane precedence from starving bulk.
    retry_after_floor_s / retry_after_ceil_s: clamp on the adaptive
      Retry-After (p90 of the shed lane's measured queue wait).
    max_tenants: distinct tenant states (and metric label values)
      tracked before new ids share the ``overflow`` bucket.
    brownout*: the ladder — sustained SLO breach steps up
      (hedge off -> bulk pause -> AIMD cap squeeze -> global shed)
      after ``up_hold_s``; sustained recovery steps down after
      ``down_hold_s`` (hysteresis), restoring squeezed caps by
      ``ai_step`` per tick (additive increase over ``md_factor``
      multiplicative decrease).
    """

    enabled: bool = True
    tenant_header: str = "X-Beacon-Tenant"
    tenant_weights: str = ""
    default_weight: float = 1.0
    tenant_max_in_flight: int = 64
    tenant_queue_depth: int = 128
    max_queue_wait_s: float = 10.0
    bulk_starvation_ms: float = 500.0
    retry_after_floor_s: float = 1.0
    retry_after_ceil_s: float = 60.0
    max_tenants: int = 64
    brownout: bool = True
    brownout_up_hold_s: float = 3.0
    brownout_down_hold_s: float = 15.0
    brownout_md_factor: float = 0.5
    brownout_ai_step: float = 0.25
    brownout_min_scale: float = 0.125
    # cost-aware DRR (accounting.py scheduling seam): the fair queue
    # charges a grant the MEASURED mean cost of its query shape
    # (normalized to the lane mean, clamped [0.25, 2.0]) instead of
    # the flat 1-per-request deficit. Off (default) keeps the flat
    # charge byte-identical — observability first, scheduling proven
    # in the config15 bench probe before it defaults on.
    cost_drr: bool = False


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Telemetry-plane knobs (telemetry.py). Tracing itself stays
    env-gated (``SBEACON_TRACE=1``, utils/trace.py) like the
    reference's ``#define INCLUDE_STOP_WATCH``; these knobs cover the
    always-on surfaces built on top of it.

    slow_query_ms: any request slower than this emits one structured
      JSON line (trace id, route, stage notes) to the
      ``sbeacon.slowquery`` logger and the in-memory ring served at
      ``/_trace``. 0 records every request (debug); negative disables.
    slow_query_log: optional file the slow-query JSON lines append to.
    profile_dir: arms ``jax.profiler`` capture of kernel launch/fetch
      regions into this directory (the ``SBEACON_PROFILE`` env var).

    SLO engine (slo.py, served at ``/slo`` + ``slo.*`` gauges):
    slo_availability_target: default max-good-ratio objective per route
      (0.999 = at most 0.1% 5xx within budget).
    slo_latency_ms / slo_latency_target: default latency objective —
      at least ``slo_latency_target`` of non-5xx requests under
      ``slo_latency_ms`` milliseconds.
    slo_routes: per-route overrides, compact
      ``route:field=value[:field=value...]`` comma list (e.g.
      ``g_variants:latency_ms=50,info:availability=0.99``).
    slo_alert_burn_rate: burn factor that, sustained on BOTH the fast
      (5m) and slow (1h) windows, marks a route breached (14.4 is the
      SRE-workbook fast-page factor).

    Flight recorder (telemetry.EventJournal, served at ``/ops/events``):
    event_journal: enables control-plane event publication.
    event_journal_size: events kept in the bounded ring.

    Cost accounting (accounting.py, served at ``/ops/costs``):
    cost_accounting: fold every tracked request's CostVector into the
      per-(tenant, lane, query-shape) table + the ``cost.*`` series.
    cost_window_s: the decaying window the per-shape mean cost (and
      the DRR charge hook) is computed over.
    Tenant cardinality reuses shaping's ``max_tenants`` cap.

    Fleet observability & canaries (ISSUE 12):
    fleet_digest_interval_s: minimum seconds between worker
      ``/ops/digest`` collection passes behind ``/fleet/status``
      (digests are polled lazily, at most once per interval).
    canary_enabled / canary_interval_s: the known-answer canary prober
      (canary.py) — background expected-answer probes per dataset x
      query shape x dispatch path; interval <= 0 disables the thread
      (explicit ``run_once()`` still works).
    canary_latency_ms: a correct probe slower than this ticks
      ``canary.slow_probes``.

    Device-plane flight recorder (telemetry.DeviceFlightRecorder,
    served at ``/device/status``; ISSUE 14):
    device_ring_size: per-launch records kept in the bounded launch
      ring (``BEACON_DEVICE_RING_SIZE``).
    compile_tracking: track first-seen (program, shape) compile keys;
      a compile outside warmup emits a ``device.compile`` journal
      event and ticks ``device.mid_request_compiles``
      (``BEACON_COMPILE_TRACKING``).

    Live shard migration (parallel/migration.py; ISSUE 16):
    migration_enabled: serve ``POST /fleet/migrate``
      (``BEACON_MIGRATION_ENABLED``; ``GET /fleet/migrations`` always
      answers — observing history is never disabled).
    migration_verify_rounds: consecutive CLEAN canary-verify rounds
      the target must answer before cut-over
      (``BEACON_MIGRATION_VERIFY_ROUNDS``, floor 1).
    migration_copy_timeout_s: wall budget for the copy phase; also
      the base of the stuck-migration diagnosis
      (``BEACON_MIGRATION_COPY_TIMEOUT_S``).

    Execution-plan plane (plan.py, served at ``GET /ops/plans``;
    ISSUE 19):
    explain_enabled: serve ``?explain=1`` inline execution plans under
      ``meta.executionPlan`` (``BEACON_EXPLAIN_ENABLED``; worker-token
      protected when one is set — 404 when disabled, 401/403 on a
      missing/bad token). The sampled plan store and drift sentinel
      run regardless; this gates only the inline surface.
    plan_sample_n: retain the full stage document for every Nth
      observation per (query-shape, plan-shape) aggregate
      (``BEACON_PLAN_SAMPLE_N``; counting is always exact — sampling
      bounds only the retained exemplar documents).
    plan_drift_windows: closed observation windows retained per
      query-shape for the dominant-plan-shape comparison
      (``BEACON_PLAN_DRIFT_WINDOWS``, floor 2: newest vs previous).
    """

    slow_query_ms: float = 1000.0
    slow_query_log: str = ""
    profile_dir: str = ""
    slo_availability_target: float = 0.999
    slo_latency_ms: float = 250.0
    slo_latency_target: float = 0.99
    slo_routes: str = ""
    slo_alert_burn_rate: float = 14.4
    event_journal: bool = True
    event_journal_size: int = 1024
    cost_accounting: bool = True
    cost_window_s: float = 300.0
    fleet_digest_interval_s: float = 10.0
    canary_enabled: bool = True
    canary_interval_s: float = 30.0
    canary_latency_ms: float = 1000.0
    device_ring_size: int = 256
    compile_tracking: bool = True
    migration_enabled: bool = True
    migration_verify_rounds: int = 3
    migration_copy_timeout_s: float = 120.0
    explain_enabled: bool = False
    plan_sample_n: int = 16
    plan_drift_windows: int = 2


@dataclasses.dataclass(frozen=True)
class AuthConfig:
    """Authentication for the two trust boundaries the reference gates
    with IAM: the mutating ``/submit`` route (reference: api.tf:120-149,
    AWS_IAM authorizer) and the worker-invoke boundary (reference: direct
    Lambda invoke / SNS, IAM-authenticated).

    Empty token = open (dev mode, matches round-1 behavior). Set
    ``submit_token`` to require ``Authorization: Bearer <token>`` on
    POST/PATCH ``/submit``; set ``worker_token`` to require the same on
    every coordinator->worker HTTP call (except ``/health``). Workers
    should additionally only be reachable on a private network — the
    token is defense-in-depth, not a substitute for network isolation.
    """

    submit_token: str = ""
    worker_token: str = ""


@dataclasses.dataclass(frozen=True)
class BeaconConfig:
    info: BeaconInfo = dataclasses.field(default_factory=BeaconInfo)
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    ingest: IngestConfig = dataclasses.field(default_factory=IngestConfig)
    resolvers: ResolverConfig = dataclasses.field(
        default_factory=ResolverConfig
    )
    auth: AuthConfig = dataclasses.field(default_factory=AuthConfig)
    resilience: ResilienceConfig = dataclasses.field(
        default_factory=ResilienceConfig
    )
    observability: ObservabilityConfig = dataclasses.field(
        default_factory=ObservabilityConfig
    )
    transport: TransportConfig = dataclasses.field(
        default_factory=TransportConfig
    )
    shaping: ShapingConfig = dataclasses.field(
        default_factory=ShapingConfig
    )

    @staticmethod
    def from_env(root: str | os.PathLike | None = None) -> "BeaconConfig":
        """Build config with env-var overrides (reference env-var tier)."""
        env = os.environ
        info = BeaconInfo(
            beacon_id=env.get("BEACON_ID", BeaconInfo.beacon_id),
            beacon_name=env.get("BEACON_NAME", BeaconInfo.beacon_name),
            api_version=env.get("BEACON_API_VERSION", BeaconInfo.api_version),
            environment=env.get("BEACON_ENVIRONMENT", BeaconInfo.environment),
            uri=env.get("BEACON_URL", BeaconInfo.uri),
        )
        storage = StorageConfig(
            root=Path(root or env.get("BEACON_DATA_ROOT", "./beacon_data"))
        )
        eng_over = {}
        if "BEACON_WINDOW_CAP" in env:
            eng_over["window_cap"] = int(env["BEACON_WINDOW_CAP"])
        if "BEACON_RECORD_CAP" in env:
            eng_over["record_cap"] = int(env["BEACON_RECORD_CAP"])
        _off = ENV_OFF
        if "BEACON_USE_TPU" in env:
            eng_over["use_tpu"] = env["BEACON_USE_TPU"].lower() not in _off
        if "BEACON_USE_MESH" in env:
            eng_over["use_mesh"] = (
                env["BEACON_USE_MESH"].lower() not in _off
            )
        if "BEACON_MESH_DISPATCH" in env:
            eng_over["mesh_dispatch"] = (
                env["BEACON_MESH_DISPATCH"].lower() not in _off
            )
        if "BEACON_MESH_MIN_SHARDS" in env:
            eng_over["mesh_min_shards"] = int(env["BEACON_MESH_MIN_SHARDS"])
        if "BEACON_MESH_SLICE" in env:
            eng_over["mesh_slice"] = (
                env["BEACON_MESH_SLICE"].lower() not in _off
            )
        if "BEACON_MESH_OWNER_OUTPUTS" in env:
            eng_over["mesh_owner_outputs"] = (
                env["BEACON_MESH_OWNER_OUTPUTS"].lower() not in _off
            )
        if "BEACON_MESH_PLANES" in env:
            eng_over["mesh_planes"] = (
                env["BEACON_MESH_PLANES"].lower() not in _off
            )
        if "BEACON_PLANE_HBM_BUDGET_GB" in env:
            eng_over["plane_hbm_budget_gb"] = float(
                env["BEACON_PLANE_HBM_BUDGET_GB"]
            )
        if "BEACON_FUSED_DISPATCH" in env:
            eng_over["fused_dispatch"] = (
                env["BEACON_FUSED_DISPATCH"].lower() not in _off
            )
        if "BEACON_FUSED_MAX_ROWS" in env:
            eng_over["fused_max_rows"] = int(env["BEACON_FUSED_MAX_ROWS"])
        if "BEACON_RESPONSE_CACHE" in env:
            eng_over["response_cache"] = (
                env["BEACON_RESPONSE_CACHE"].lower() not in _off
            )
        if "BEACON_RESPONSE_CACHE_SIZE" in env:
            eng_over["response_cache_size"] = int(
                env["BEACON_RESPONSE_CACHE_SIZE"]
            )
        if "BEACON_RESPONSE_CACHE_TTL_S" in env:
            eng_over["response_cache_ttl_s"] = float(
                env["BEACON_RESPONSE_CACHE_TTL_S"]
            )
        if "BEACON_SCOPED_INVALIDATION" in env:
            eng_over["scoped_invalidation"] = (
                env["BEACON_SCOPED_INVALIDATION"].lower() not in _off
            )
        if "BEACON_L0_MIN_SHARDS" in env:
            eng_over["l0_min_shards"] = int(env["BEACON_L0_MIN_SHARDS"])
        if "BEACON_L0_MIN_ROWS" in env:
            eng_over["l0_min_rows"] = int(env["BEACON_L0_MIN_ROWS"])
        if "BEACON_FETCH_PIPELINE_DEPTH" in env:
            eng_over["fetch_pipeline_depth"] = int(
                env["BEACON_FETCH_PIPELINE_DEPTH"]
            )
        if "BEACON_PLANE_UPLOAD_CHUNK_MB" in env:
            eng_over["plane_upload_chunk_mb"] = int(
                env["BEACON_PLANE_UPLOAD_CHUNK_MB"]
            )
        engine = EngineConfig(**eng_over)
        resolvers = ResolverConfig(
            enabled=env.get("BEACON_RESOLVE_ONTOLOGIES", "").lower()
            in ("1", "true", "yes", "on"),
            ols_url=env.get("BEACON_OLS_URL", DEFAULT_OLS_URL),
            ontoserver_url=env.get(
                "BEACON_ONTOSERVER_URL", DEFAULT_ONTOSERVER_URL
            ),
            workers=int(env.get("BEACON_RESOLVER_WORKERS", "8")),
        )
        ingest_over = {}
        if "BEACON_SCAN_WORKERS" in env:
            ingest_over["scan_worker_urls"] = tuple(
                u.strip()
                for u in env["BEACON_SCAN_WORKERS"].split(",")
                if u.strip()
            )
        if "BEACON_INGEST_WORKERS" in env:
            ingest_over["workers"] = int(env["BEACON_INGEST_WORKERS"])
        if "BEACON_STREAM_DELTAS" in env:
            ingest_over["stream_deltas"] = (
                env["BEACON_STREAM_DELTAS"].lower() not in _off
            )
        if "BEACON_DELTA_MAX_SHARDS" in env:
            ingest_over["delta_max_shards"] = int(
                env["BEACON_DELTA_MAX_SHARDS"]
            )
        if "BEACON_COMPACT_INTERVAL_S" in env:
            ingest_over["compact_interval_s"] = float(
                env["BEACON_COMPACT_INTERVAL_S"]
            )
        if "BEACON_COMPACT_BASE_RATIO" in env:
            ingest_over["compact_base_ratio"] = float(
                env["BEACON_COMPACT_BASE_RATIO"]
            )
        if "BEACON_ARTIFACT_RETAIN" in env:
            ingest_over["artifact_retain"] = int(
                env["BEACON_ARTIFACT_RETAIN"]
            )
        if "BEACON_DEFER_BASE_PUBLISH" in env:
            ingest_over["defer_base_publish"] = (
                env["BEACON_DEFER_BASE_PUBLISH"].lower() not in _off
            )
        ingest = IngestConfig(**ingest_over)
        auth = AuthConfig(
            submit_token=env.get("BEACON_SUBMIT_TOKEN", ""),
            worker_token=env.get("BEACON_WORKER_TOKEN", ""),
        )
        res_over: dict = {}
        _res_env = {
            "BEACON_DEADLINE_S": ("default_deadline_s", float),
            "BEACON_BATCH_TIMEOUT_S": ("batch_timeout_s", float),
            "BEACON_MAX_IN_FLIGHT": ("max_in_flight", int),
            "BEACON_SHED_RETRY_AFTER_S": ("shed_retry_after_s", float),
            "BEACON_RUNNER_WORKERS": ("runner_workers", int),
            "BEACON_RUNNER_MAX_PENDING": ("runner_max_pending", int),
            "BEACON_BREAKER_THRESHOLD": ("breaker_failure_threshold", int),
            "BEACON_BREAKER_RESET_S": ("breaker_reset_s", float),
            "BEACON_BREAKER_PROBES": ("breaker_half_open_probes", int),
            "BEACON_FAILOVER_RETRIES": ("failover_retries", int),
            "BEACON_RUNNER_BULK_SHARE": ("runner_bulk_share", float),
        }
        for var, (field, conv) in _res_env.items():
            if var in env:
                res_over[field] = conv(env[var])
        if "BEACON_PARTIAL_RESULTS" in env:
            res_over["partial_results"] = (
                env["BEACON_PARTIAL_RESULTS"].lower() not in _off
            )
        resilience = ResilienceConfig(**res_over)
        tr_over: dict = {}
        _tr_env = {
            "BEACON_POOL_SIZE": ("pool_size", int),
            "BEACON_POOL_IDLE_S": ("idle_ttl_s", float),
            "BEACON_GZIP_MIN_BYTES": ("gzip_min_bytes", int),
            "BEACON_HEDGE_DELAY_S": ("hedge_delay_s", float),
        }
        for var, (field, conv) in _tr_env.items():
            if var in env:
                tr_over[field] = conv(env[var])
        if "BEACON_BOOL_SHORT_CIRCUIT" in env:
            tr_over["bool_short_circuit"] = (
                env["BEACON_BOOL_SHORT_CIRCUIT"].lower() not in _off
            )
        if "BEACON_REPLICA_HEDGE" in env:
            tr_over["replica_hedge"] = (
                env["BEACON_REPLICA_HEDGE"].lower() not in _off
            )
        transport = TransportConfig(**tr_over)
        obs_over: dict = {}
        if "SBEACON_SLOW_QUERY_MS" in env:
            obs_over["slow_query_ms"] = float(env["SBEACON_SLOW_QUERY_MS"])
        if "SBEACON_SLOW_QUERY_LOG" in env:
            obs_over["slow_query_log"] = env["SBEACON_SLOW_QUERY_LOG"]
        if "SBEACON_PROFILE" in env:
            obs_over["profile_dir"] = env["SBEACON_PROFILE"]
        _obs_env = {
            "BEACON_SLO_AVAILABILITY": ("slo_availability_target", float),
            "BEACON_SLO_LATENCY_MS": ("slo_latency_ms", float),
            "BEACON_SLO_LATENCY_TARGET": ("slo_latency_target", float),
            "BEACON_SLO_ROUTES": ("slo_routes", str),
            "BEACON_SLO_ALERT_BURN": ("slo_alert_burn_rate", float),
            "BEACON_EVENT_JOURNAL_SIZE": ("event_journal_size", int),
            "BEACON_FLEET_DIGEST_INTERVAL_S": (
                "fleet_digest_interval_s",
                float,
            ),
            "BEACON_CANARY_INTERVAL_S": ("canary_interval_s", float),
            "BEACON_CANARY_LATENCY_MS": ("canary_latency_ms", float),
            "BEACON_DEVICE_RING_SIZE": ("device_ring_size", int),
            "BEACON_MIGRATION_VERIFY_ROUNDS": (
                "migration_verify_rounds",
                int,
            ),
            "BEACON_MIGRATION_COPY_TIMEOUT_S": (
                "migration_copy_timeout_s",
                float,
            ),
            "BEACON_PLAN_SAMPLE_N": ("plan_sample_n", int),
            "BEACON_PLAN_DRIFT_WINDOWS": ("plan_drift_windows", int),
        }
        for var, (field, conv) in _obs_env.items():
            if var in env:
                obs_over[field] = conv(env[var])
        if "BEACON_EVENT_JOURNAL_ENABLED" in env:
            obs_over["event_journal"] = (
                env["BEACON_EVENT_JOURNAL_ENABLED"].lower() not in _off
            )
        if "BEACON_CANARY_ENABLED" in env:
            obs_over["canary_enabled"] = (
                env["BEACON_CANARY_ENABLED"].lower() not in _off
            )
        if "BEACON_COMPILE_TRACKING" in env:
            obs_over["compile_tracking"] = (
                env["BEACON_COMPILE_TRACKING"].lower() not in _off
            )
        if "BEACON_MIGRATION_ENABLED" in env:
            obs_over["migration_enabled"] = (
                env["BEACON_MIGRATION_ENABLED"].lower() not in _off
            )
        if "BEACON_EXPLAIN_ENABLED" in env:
            obs_over["explain_enabled"] = (
                env["BEACON_EXPLAIN_ENABLED"].lower() not in _off
            )
        if "BEACON_COST_ACCOUNTING" in env:
            obs_over["cost_accounting"] = (
                env["BEACON_COST_ACCOUNTING"].lower() not in _off
            )
        if "BEACON_COST_WINDOW_S" in env:
            obs_over["cost_window_s"] = float(env["BEACON_COST_WINDOW_S"])
        observability = ObservabilityConfig(**obs_over)
        sh_over: dict = {}
        _sh_env = {
            "BEACON_TENANT_HEADER": ("tenant_header", str),
            "BEACON_TENANT_WEIGHTS": ("tenant_weights", str),
            "BEACON_TENANT_DEFAULT_WEIGHT": ("default_weight", float),
            "BEACON_TENANT_MAX_IN_FLIGHT": ("tenant_max_in_flight", int),
            "BEACON_TENANT_QUEUE_DEPTH": ("tenant_queue_depth", int),
            "BEACON_MAX_QUEUE_WAIT_S": ("max_queue_wait_s", float),
            "BEACON_BULK_STARVATION_MS": ("bulk_starvation_ms", float),
            "BEACON_RETRY_AFTER_FLOOR_S": ("retry_after_floor_s", float),
            "BEACON_RETRY_AFTER_CEIL_S": ("retry_after_ceil_s", float),
            "BEACON_MAX_TENANTS": ("max_tenants", int),
            "BEACON_BROWNOUT_UP_S": ("brownout_up_hold_s", float),
            "BEACON_BROWNOUT_DOWN_S": ("brownout_down_hold_s", float),
        }
        for var, (field, conv) in _sh_env.items():
            if var in env:
                sh_over[field] = conv(env[var])
        if "BEACON_SHAPING" in env:
            sh_over["enabled"] = env["BEACON_SHAPING"].lower() not in _off
        if "BEACON_BROWNOUT" in env:
            sh_over["brownout"] = env["BEACON_BROWNOUT"].lower() not in _off
        if "BEACON_COST_DRR" in env:
            sh_over["cost_drr"] = env["BEACON_COST_DRR"].lower() not in _off
        shaping = ShapingConfig(**sh_over)
        return BeaconConfig(
            info=info,
            storage=storage,
            engine=engine,
            ingest=ingest,
            resolvers=resolvers,
            auth=auth,
            resilience=resilience,
            observability=observability,
            transport=transport,
            shaping=shaping,
        )

    def dumps(self) -> str:
        d = dataclasses.asdict(self)
        d["storage"]["root"] = str(d["storage"]["root"])
        return json.dumps(d, indent=2)


def enable_persistent_compile_cache(storage_root) -> None:
    """Point XLA's persistent compilation cache under the storage root:
    the warmed kernel programs (2-3 min of tunnel compiles on a cold
    chip) compile once per index/config shape EVER, not once per
    process start. Shared by BOTH deployment entries — the coordinator
    (api.server) and the worker host (parallel.dispatch) — so a worker
    container restart doesn't re-pay the compiles either. Best-effort:
    the cache is an optimisation, never a dependency."""
    import logging
    from pathlib import Path

    try:
        import jax

        cache_dir = Path(storage_root) / "jax-cache"
        cache_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    except Exception:
        logging.getLogger(__name__).exception(
            "persistent compilation cache unavailable"
        )
