"""HBM-resident columnar variant index.

This is the TPU-native replacement for the reference's on-S3 binary variant
index (reference: lambda/summariseSlice/source/write_data_to_s3.h —
(pos:u64, len:u16, "ref_alt") records with 4-bit packed bases, sharded into
region files). That format exists to be re-scanned by more lambdas; ours
exists to be *queried on-device*, so the layout is struct-of-arrays with one
row per (record, alt) pair, sorted by (chrom_code, pos), every
variable-length/regex-ish predicate of the matcher pre-computed into
fixed-width columns at ingest:

- allele identity: fnv1a32 hash of uppercased sequence + length (exact
  compare on device), 16 raw prefix bytes (symbolic-allele prefix matching),
- symbolic-allele structure: flag bits for '<', '<CN', literal '<CN0>'/
  '<CN1>'/'<CN2>', '<DEL'/'<DUP' prefixes, '.' and single-base alts,
- duplication structure: ref_repeat_k (alt == ref*k) covering the
  reference's DUP/DUP:TANDEM/CNV regexes (performQuery/search_variants.py:
  124-158) without any per-query string work,
- counts: AC materialised per alt and AN per record (INFO values when
  present, genotype-derived otherwise — the AC/AN-vs-genotype duality of
  performQuery :205-226 collapses at ingest),
- genotype bitsets per row (sample hit extraction, selected-samples path).

Host-only blobs keep the original REF/ALT bytes for materialising Beacon
variant strings from matched row ids.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

from ..genomics.vcf import VcfRecord, _calls_for
from ..utils.chrom import chromosome_code

N_CHROM_CODES = 26  # codes 1..25 valid; offsets array has 27 entries

INT32_MAX = np.int32(2**31 - 1)


class FLAG:
    SYMBOLIC = 1  # alt starts with '<'
    CN_PREFIX = 2  # alt starts with '<CN'
    CN0 = 4  # alt == '<CN0>'
    CN1 = 8  # alt == '<CN1>'
    CN2 = 16  # alt == '<CN2>'
    DOT = 32  # alt == '.'
    DEL_PREFIX = 64  # alt starts with '<DEL'
    DUP_PREFIX = 128  # alt starts with '<DUP'
    SINGLE_BASE = 256  # alt.upper() in {A,C,G,T,N}
    AC_INFO = 512  # row's ac came from INFO AC (not genotype tally)
    AN_INFO = 1024  # row's an came from INFO AN (not genotype tally)


def fnv1a32(data: bytes) -> int:
    """FNV-1a 32-bit, returned as int32 bit pattern."""
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return int(np.uint32(h).view(np.int32))


def pack_prefix16(data: bytes) -> np.ndarray:
    """First 16 bytes as 4 big-endian uint32 words (zero padded)."""
    buf = data[:16].ljust(16, b"\x00")
    return np.frombuffer(buf, dtype=">u4").astype(np.uint32)


def prefix_mask(length: int) -> np.ndarray:
    """uint32[4] mask selecting the first ``length`` bytes of a prefix16."""
    out = np.zeros(4, dtype=np.uint32)
    for w in range(4):
        covered = max(0, min(4, length - 4 * w))
        if covered == 4:
            out[w] = 0xFFFFFFFF
        elif covered > 0:
            out[w] = np.uint32(0xFFFFFFFF) << np.uint32(8 * (4 - covered))
    return out


def _ref_repeat_k(ref: str, alt: str) -> int:
    """k such that alt == ref * k (k >= 1), else -1. Covers the DUP
    '(ref){2,}' / DUP:TANDEM 'ref+ref' / CNV '(ref)*' regex family."""
    lr, la = len(ref), len(alt)
    if lr == 0 or la == 0 or la % lr != 0:
        return -1
    k = la // lr
    if alt == ref * k:
        return min(k, 120)
    return -1


def _alt_flags(alt: str) -> int:
    f = 0
    if alt.startswith("<"):
        f |= FLAG.SYMBOLIC
        if alt.startswith("<CN"):
            f |= FLAG.CN_PREFIX
        if alt == "<CN0>":
            f |= FLAG.CN0
        elif alt == "<CN1>":
            f |= FLAG.CN1
        elif alt == "<CN2>":
            f |= FLAG.CN2
        if alt.startswith("<DEL"):
            f |= FLAG.DEL_PREFIX
        if alt.startswith("<DUP"):
            f |= FLAG.DUP_PREFIX
    else:
        if alt == ".":
            f |= FLAG.DOT
        if len(alt) == 1 and alt.upper() in "ACGTN":
            f |= FLAG.SINGLE_BASE
    return f


# Device-bound columns: name -> dtype
DEVICE_COLUMNS = {
    "pos": np.int32,
    "rec_end": np.int32,  # pos + ref_len - 1
    "ref_len": np.int32,
    "alt_len": np.int32,
    "ref_hash": np.int32,  # fnv1a32(ref.upper())
    "alt_hash": np.int32,  # fnv1a32(alt.upper())
    "ref_repeat_k": np.int32,
    "flags": np.int32,
    "ac": np.int32,
    "an": np.int32,
    "rec_id": np.int32,
}


@dataclass
class VariantIndexShard:
    """One dataset+VCF's worth of index rows (a shard of the global index)."""

    meta: dict
    cols: dict[str, np.ndarray]  # DEVICE_COLUMNS + alt_prefix uint32[n,4]
    chrom_offsets: np.ndarray  # int32[27]: row span per chrom code
    # host-only materialisation data
    ref_blob: np.ndarray  # uint8
    ref_off: np.ndarray  # uint32[n+1]
    alt_blob: np.ndarray
    alt_off: np.ndarray
    vt_codes: np.ndarray  # int16[n] into meta['vt_vocab']
    gt_bits: np.ndarray | None = None  # uint32[n, ceil(n_samples/32)]
    # extra genotype planes for the selected-samples restricted path
    # (reference search_variants_in_samples.py genotype-derived counting):
    # gt_bits2 — sample carries >=2 copies of the row's alt;
    # tok_bits1/tok_bits2 — sample's GT has >=1/>=2 numeric allele tokens
    # (per record, duplicated across its alt rows).
    gt_bits2: np.ndarray | None = None
    tok_bits1: np.ndarray | None = None
    tok_bits2: np.ndarray | None = None
    # exact values where the 2-bit planes saturate (ploidy > 2):
    # int64[k, 3] rows of (row, sample, copies) / (row, sample, tokens)
    gt_overflow: np.ndarray | None = None
    tok_overflow: np.ndarray | None = None

    @property
    def has_count_planes(self) -> bool:
        """All three restricted-counting planes present — THE predicate
        every consumer shares (plane upload gates, StackedIndex statics,
        mesh/materialise exactness checks) so they can never drift."""
        return (
            self.gt_bits2 is not None
            and self.tok_bits1 is not None
            and self.tok_bits2 is not None
        )

    def overflow_map(self, which: str) -> dict[int, list[tuple[int, int]]]:
        """{row: [(sample, exact_value), ...]} for 'gt' or 'tok' overflow
        entries; cached."""
        attr = f"_{which}_overflow_map"
        cached = getattr(self, attr, None)
        if cached is not None:
            return cached
        arr = self.gt_overflow if which == "gt" else self.tok_overflow
        out: dict[int, list[tuple[int, int]]] = {}
        if arr is not None:
            for row, sample, value in arr.tolist():
                out.setdefault(int(row), []).append((int(sample), int(value)))
        object.__setattr__(self, attr, out)
        return out

    @property
    def n_rows(self) -> int:
        return len(self.cols["pos"])

    def row_ref(self, i: int) -> str:
        return bytes(
            self.ref_blob[self.ref_off[i] : self.ref_off[i + 1]]
        ).decode()

    def row_alt(self, i: int) -> str:
        return bytes(
            self.alt_blob[self.alt_off[i] : self.alt_off[i + 1]]
        ).decode()

    def row_vt(self, i: int) -> str:
        return self.meta["vt_vocab"][self.vt_codes[i]]

    def row_chrom(self, i: int) -> str:
        # recover canonical chromosome from the offsets table
        code = int(np.searchsorted(self.chrom_offsets, i, side="right")) - 1
        from ..utils.chrom import CODE_TO_CHROMOSOME

        return CODE_TO_CHROMOSOME.get(code, "?")

    def row_samples(self, i: int) -> list[int]:
        if self.gt_bits is None:
            return []
        bits = self.gt_bits[i]
        out = []
        for w, word in enumerate(bits):
            word = int(word)
            while word:
                b = (word & -word).bit_length() - 1
                out.append(w * 32 + b)
                word &= word - 1
        return out

    def variant_string(self, i: int, chrom_label: str | None = None) -> str:
        """'{chrom}\\t{pos}\\t{ref}\\t{alt}\\t{vt}' — the wire form the
        route aggregation layer consumes (reference route_g_variants.py:163).
        """
        chrom = chrom_label if chrom_label is not None else self.row_chrom(i)
        return (
            f"{chrom}\t{self.cols['pos'][i]}\t{self.row_ref(i)}"
            f"\t{self.row_alt(i)}\t{self.row_vt(i)}"
        )


def build_index(
    records,
    *,
    dataset_id: str = "",
    vcf_location: str = "",
    sample_names: list[str] | None = None,
    with_genotypes: bool = True,
) -> VariantIndexShard:
    """Explode VcfRecords into sorted columnar rows.

    Records may arrive in any chromosome order (rows are stably re-sorted by
    (chrom_code, pos) so per-record row groups stay contiguous); unknown
    contigs are dropped (they are unreachable through Beacon's canonical
    referenceName anyway — reference chrom_matching returns None for them).
    """
    sample_names = sample_names or []
    n_samples = len(sample_names)
    gt_words = (n_samples + 31) // 32 if n_samples else 0

    rows: list[tuple] = []  # (chrom_code, pos, rec_ord, alt_ord, record)
    vt_vocab: list[str] = ["N/A"]
    vt_index = {"N/A": 0}
    records = list(records)
    dropped = 0
    chrom_native: dict[str, str] = {}  # canonical -> native spelling in file
    for rec_ord, rec in enumerate(records):
        code = chromosome_code(rec.chrom)
        if code == 0:
            dropped += 1
            continue
        from ..utils.chrom import normalize_chromosome

        canon = normalize_chromosome(rec.chrom)
        chrom_native.setdefault(canon, rec.chrom)
        for alt_ord in range(len(rec.alts)):
            rows.append((code, rec.pos, rec_ord, alt_ord, rec))

    # stable sort keeps a record's alts adjacent and in file order
    rows.sort(key=lambda r: (r[0], r[1], r[2], r[3]))

    n = len(rows)
    cols = {name: np.zeros(n, dtype=dt) for name, dt in DEVICE_COLUMNS.items()}
    alt_prefix = np.zeros((n, 4), dtype=np.uint32)
    vt_codes = np.zeros(n, dtype=np.int16)
    gt_bits = (
        np.zeros((n, gt_words), dtype=np.uint32) if gt_words else None
    )
    gt_bits2 = np.zeros_like(gt_bits) if gt_bits is not None else None
    tok_bits1 = np.zeros_like(gt_bits) if gt_bits is not None else None
    tok_bits2 = np.zeros_like(gt_bits) if gt_bits is not None else None
    gt_overflow: list[tuple[int, int, int]] = []
    tok_overflow: list[tuple[int, int, int]] = []
    ref_parts: list[bytes] = []
    alt_parts: list[bytes] = []
    chrom_offsets = np.zeros(N_CHROM_CODES + 1, dtype=np.int32)

    # rec_id must be nondecreasing in row order for the windowed
    # first-match-per-record scan on device; re-number by first appearance.
    rec_renumber: dict[int, int] = {}
    used_records: list = []  # record object per renumbered id
    # cache per-record derived values
    an_cache: dict[int, int] = {}
    ac_cache: dict[int, list[int]] = {}
    # per-row plane inputs, filled in the main loop and resolved in one
    # pass afterwards (native sbn_gt_planes when available)
    row_rec = np.zeros(n, dtype=np.int32)
    row_allele = np.zeros(n, dtype=np.int32)

    # per-build memoization (functools.cache scoped to this call):
    # cohort alleles repeat massively (refs are mostly single bases), so
    # hashing/prefix-packing per UNIQUE string instead of per row
    # removes the loop's main Python cost
    import functools

    allele_hash = functools.cache(lambda s: fnv1a32(s.upper().encode()))
    alt_prefix_of = functools.cache(lambda s: pack_prefix16(s.encode()))
    alt_flags_of = functools.cache(_alt_flags)
    repeat_k_of = functools.cache(_ref_repeat_k)

    for i, (code, pos, rec_ord, alt_ord, rec) in enumerate(rows):
        alt = rec.alts[alt_ord]
        ref = rec.ref
        if rec_ord not in rec_renumber:
            rec_renumber[rec_ord] = len(rec_renumber)
            used_records.append(rec)
            ac_cache[rec_ord] = rec.effective_ac()
            an_cache[rec_ord] = rec.effective_an()
        cols["pos"][i] = pos
        cols["rec_end"][i] = pos + len(ref) - 1
        cols["ref_len"][i] = len(ref)
        cols["alt_len"][i] = len(alt)
        cols["ref_hash"][i] = allele_hash(ref)
        cols["alt_hash"][i] = allele_hash(alt)
        cols["ref_repeat_k"][i] = repeat_k_of(ref, alt)
        cols["flags"][i] = (
            alt_flags_of(alt)
            | (FLAG.AC_INFO if rec.ac is not None else 0)
            | (FLAG.AN_INFO if rec.an is not None else 0)
        )
        cols["ac"][i] = ac_cache[rec_ord][alt_ord]
        cols["an"][i] = an_cache[rec_ord]
        cols["rec_id"][i] = rec_renumber[rec_ord]
        alt_prefix[i] = alt_prefix_of(alt)
        if rec.vt not in vt_index:
            vt_index[rec.vt] = len(vt_vocab)
            vt_vocab.append(rec.vt)
        vt_codes[i] = vt_index[rec.vt]
        ref_parts.append(ref.encode())
        alt_parts.append(alt.encode())
        row_rec[i] = rec_renumber[rec_ord]
        row_allele[i] = alt_ord + 1

    if gt_bits is not None and n:
        _fill_gt_planes(
            used_records,
            n_samples,
            gt_words,
            row_rec,
            row_allele,
            gt_bits,
            gt_bits2,
            tok_bits1,
            tok_bits2,
            gt_overflow,
            tok_overflow,
        )

    # chrom offsets: chrom_offsets[c] = first row of code c
    codes = np.array([r[0] for r in rows], dtype=np.int32)
    for c in range(N_CHROM_CODES + 1):
        chrom_offsets[c] = np.searchsorted(codes, c, side="left")

    ref_off = np.zeros(n + 1, dtype=np.uint32)
    alt_off = np.zeros(n + 1, dtype=np.uint32)
    np.cumsum([len(p) for p in ref_parts], out=ref_off[1:] if n else None)
    np.cumsum([len(p) for p in alt_parts], out=alt_off[1:] if n else None)

    n_records = len(rec_renumber)
    meta = {
        "dataset_id": dataset_id,
        "vcf_location": vcf_location,
        "sample_names": sample_names,
        "vt_vocab": vt_vocab,
        "n_rows": n,
        "n_records": n_records,
        "dropped_records": dropped,
        # dataset summary stats (reference summariseSlice counts:
        # variantCount = #alts, callCount = sum AN, sampleCount)
        "variant_count": n,
        "call_count": int(
            sum(an_cache[r] for r in rec_renumber)
        ),
        "sample_count": n_samples,
        "chrom_native": chrom_native,
        "format_version": 1,
    }
    shard = VariantIndexShard(
        meta=meta,
        cols={**cols, "alt_prefix": alt_prefix},
        chrom_offsets=chrom_offsets,
        ref_blob=np.frombuffer(b"".join(ref_parts), dtype=np.uint8).copy(),
        ref_off=ref_off,
        alt_blob=np.frombuffer(b"".join(alt_parts), dtype=np.uint8).copy(),
        alt_off=alt_off,
        vt_codes=vt_codes,
        gt_bits=gt_bits,
        gt_bits2=gt_bits2,
        tok_bits1=tok_bits1,
        tok_bits2=tok_bits2,
        gt_overflow=(
            np.array(gt_overflow, dtype=np.int64).reshape(-1, 3)
            if gt_bits is not None
            else None
        ),
        tok_overflow=(
            np.array(tok_overflow, dtype=np.int64).reshape(-1, 3)
            if gt_bits is not None
            else None
        ),
    )
    return shard


# GT tokenization is shared with the oracle path (genomics/vcf._calls_for,
# the reference's get_all_calls regex semantics) so the plane builder and
# the CPU oracle can never drift apart on genotype spellings. The native
# digit-run scan in gt_planes.cpp implements the same semantics.


def _fill_gt_planes(
    used_records,
    n_samples: int,
    gt_words: int,
    row_rec: np.ndarray,
    row_allele: np.ndarray,
    gt_bits: np.ndarray,
    gt_bits2: np.ndarray,
    tok_bits1: np.ndarray,
    tok_bits2: np.ndarray,
    gt_overflow: list,
    tok_overflow: list,
) -> None:
    """Resolve the genotype planes for all rows — native single pass when
    the C++ library is available, vectorised Python otherwise.

    Genotype columns are normalised to exactly n_samples entries (extra
    entries dropped, missing padded empty) identically on both paths, so
    index contents never depend on whether the native library is built.
    """
    from .. import native

    if not any(rec.genotypes for rec in used_records):
        return  # all-zero planes; skip the whole pass

    def norm_gts(rec) -> list[str]:
        gts = list(rec.genotypes[:n_samples]) if rec.genotypes else []
        return gts + [""] * (n_samples - len(gts))

    if native.available():
        parts: list[bytes] = []
        offs = np.zeros(len(used_records) * n_samples + 1, dtype=np.uint64)
        k = 0
        total = 0
        for rec in used_records:
            for gt in norm_gts(rec):
                b = gt.encode()
                parts.append(b)
                total += len(b)
                k += 1
                offs[k] = total
        try:
            g1, g2, t1, t2, g_over, t_over = native.gt_planes(
                b"".join(parts),
                offs,
                len(used_records),
                n_samples,
                row_rec,
                row_allele,
                gt_words,
            )
        except native.NativeUnavailable:
            pass
        else:
            gt_bits[:] = g1
            gt_bits2[:] = g2
            tok_bits1[:] = t1
            tok_bits2[:] = t2
            gt_overflow.extend(map(tuple, g_over.tolist()))
            tok_overflow.extend(map(tuple, t_over.tolist()))
            return

    calls_cache: dict[int, tuple] = {}
    for i in range(len(row_rec)):
        rid = int(row_rec[i])
        rec = used_records[rid]
        if not rec.genotypes:
            continue
        if rid not in calls_cache:
            calls_cache[rid] = _gt_matrix(norm_gts(rec), gt_words)
        M, ntok, tok1, tok2, tok_over = calls_cache[rid]
        allele = int(row_allele[i])
        copies = (M == allele).sum(axis=1).astype(np.int32)
        gt_bits[i] = _pack_bits(copies >= 1, gt_words)
        gt_bits2[i] = _pack_bits(copies >= 2, gt_words)
        for s_idx in np.nonzero(copies > 2)[0]:
            # ploidy > 2: keep the exact count
            gt_overflow.append((i, int(s_idx), int(copies[s_idx])))
        tok_bits1[i] = tok1
        tok_bits2[i] = tok2
        for s_idx, t in tok_over:
            tok_overflow.append((i, s_idx, t))


def _pack_bits(mask: np.ndarray, words: int) -> np.ndarray:
    """bool[n_samples] -> uint32[words], bit s = sample s (little-bit
    order within each word, matching the scalar ``1 << (s % 32)``)."""
    padded = np.zeros(words * 32, dtype=np.uint32)
    padded[: len(mask)] = mask
    return (padded.reshape(words, 32) << np.arange(32, dtype=np.uint32)).sum(
        axis=1, dtype=np.uint32
    )


def _gt_matrix(genotypes: list[str], gt_words: int):
    """Per-record genotype parse, done once and shared by all alt rows:
    (calls matrix [n_samples, max_ploidy] with -1 padding, token counts,
    packed tok>=1 / tok>=2 planes, [(sample, tokens)] overflow)."""
    calls = [_calls_for(gt) for gt in genotypes]
    n = len(calls)
    lens = [len(c) for c in calls]
    ploidy = max(lens, default=0)
    if ploidy and min(lens) == ploidy:
        # uniform ploidy (the overwhelmingly common case): one array call
        M = np.array(calls, dtype=np.int32)
        ntok = np.full(n, ploidy, dtype=np.int32)
    else:
        M = np.full((n, max(ploidy, 1)), -1, dtype=np.int32)
        ntok = np.zeros(n, dtype=np.int32)
        for s, toks in enumerate(calls):
            ntok[s] = len(toks)
            M[s, : len(toks)] = toks
    tok1 = _pack_bits(ntok >= 1, gt_words)
    tok2 = _pack_bits(ntok >= 2, gt_words)
    tok_over = [
        (int(s), int(ntok[s])) for s in np.nonzero(ntok > 2)[0]
    ]
    return M, ntok, tok1, tok2, tok_over


_SHARD_PLANES = (
    "gt_bits",
    "gt_bits2",
    "tok_bits1",
    "tok_bits2",
    "gt_overflow",
    "tok_overflow",
)


def _shard_arrays(shard: VariantIndexShard) -> dict:
    arrays = {f"col_{k}": v for k, v in shard.cols.items()}
    arrays["chrom_offsets"] = shard.chrom_offsets
    arrays["ref_blob"] = shard.ref_blob
    arrays["ref_off"] = shard.ref_off
    arrays["alt_blob"] = shard.alt_blob
    arrays["alt_off"] = shard.alt_off
    arrays["vt_codes"] = shard.vt_codes
    for plane in _SHARD_PLANES:
        arr = getattr(shard, plane)
        if arr is not None:
            arrays[plane] = arr
    return arrays


def _shard_from(data, meta: dict) -> VariantIndexShard:
    cols = {k[4:]: data[k] for k in data.files if k.startswith("col_")}
    return VariantIndexShard(
        meta=meta,
        cols=cols,
        chrom_offsets=data["chrom_offsets"],
        ref_blob=data["ref_blob"],
        ref_off=data["ref_off"],
        alt_blob=data["alt_blob"],
        alt_off=data["alt_off"],
        vt_codes=data["vt_codes"],
        **{
            plane: (data[plane] if plane in data.files else None)
            for plane in _SHARD_PLANES
        },
    )


def save_index(
    shard: VariantIndexShard, path: str | Path, *, compress: bool = True
) -> None:
    """Persist a shard as one npz + json meta sidecar.

    Writes are atomic (tmp + rename) so a crash mid-save can never leave a
    truncated shard that bricks the resume path. ``compress=False`` skips
    the zlib pass — right for short-lived intermediates (per-slice shards
    are merged and deleted moments later; compressing them was a
    measurable slice of ingest wall time)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    arrays = _shard_arrays(shard)
    import os

    tmp = path.with_name(path.name + ".tmp.npz")
    (np.savez_compressed if compress else np.savez)(tmp, **arrays)
    os.replace(tmp, path if path.suffix == ".npz" else str(path) + ".npz")
    meta_tmp = Path(str(path) + ".meta.json.tmp")
    meta_tmp.write_text(json.dumps(shard.meta))
    os.replace(meta_tmp, str(path) + ".meta.json")


def load_index(path: str | Path) -> VariantIndexShard:
    path = Path(path)
    data = np.load(path if path.suffix == ".npz" else str(path) + ".npz")
    meta = json.loads(Path(str(path) + ".meta.json").read_text())
    return _shard_from(data, meta)


def dumps_index(shard: VariantIndexShard) -> bytes:
    """One self-contained npz blob (meta embedded) — the wire form slice
    shards travel in from scan workers to the coordinator (the role S3
    partial-result keys play for the reference's summariseSlice)."""
    import io as _io

    arrays = _shard_arrays(shard)
    arrays["meta_json"] = np.frombuffer(
        json.dumps(shard.meta).encode(), dtype=np.uint8
    )
    buf = _io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def loads_index(blob: bytes) -> VariantIndexShard:
    import io as _io

    data = np.load(_io.BytesIO(blob), allow_pickle=False)
    meta = json.loads(bytes(data["meta_json"]))
    return _shard_from(data, meta)


def save_index_blob(blob: bytes, path: str | Path) -> dict:
    """Persist a ``dumps_index`` blob as a standard on-disk shard (npz +
    meta sidecar) WITHOUT re-encoding the arrays, returning the embedded
    meta. np.load is lazy, so only the tiny meta_json entry is inflated —
    the coordinator never pays decompress+recompress for slice shards it
    merely relays from scan workers to disk."""
    import io as _io
    import os

    data = np.load(_io.BytesIO(blob), allow_pickle=False)
    meta = json.loads(bytes(data["meta_json"]))
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp.npz")
    tmp.write_bytes(blob)
    os.replace(tmp, path if path.suffix == ".npz" else str(path) + ".npz")
    meta_tmp = Path(str(path) + ".meta.json.tmp")
    meta_tmp.write_text(json.dumps(meta))
    os.replace(meta_tmp, str(path) + ".meta.json")
    return meta


def stack_shard_columns(
    shards: list[VariantIndexShard],
) -> tuple[dict[str, np.ndarray], np.ndarray, np.ndarray]:
    """Stacked-shard device-column representation for fused dispatch.

    Unlike :func:`merge_shards` (which interleaves rows into ONE globally
    sorted order, destroying per-shard row identity), this keeps every
    shard's rows contiguous and in their original order and adds a
    per-shard segment table: the fused kernel answers a (shard, query)
    pair by bisecting inside ``chrom_offsets[shard]`` exactly as the
    single-shard kernel bisects inside its own offsets — one launch
    covers specs against *any* warm shard.

    Returns ``(cols, chrom_offsets, shard_base)``:

    - ``cols``: every device column (incl. ``alt_prefix``) concatenated
      in shard order,
    - ``chrom_offsets``: int32[k, 27] — shard i's chromosome segment
      table rebased to absolute stacked row ids,
    - ``shard_base``: int64[k+1] — shard i's rows live at
      ``[shard_base[i], shard_base[i+1])``; stacked row ids map back to
      shard-local ids by subtracting ``shard_base[i]``.
    """
    if not shards:
        raise ValueError("stack_shard_columns needs at least one shard")
    base = np.zeros(len(shards) + 1, dtype=np.int64)
    for i, s in enumerate(shards):
        base[i + 1] = base[i] + s.n_rows
    if base[-1] > int(INT32_MAX):
        raise ValueError(
            f"stacked index exceeds int32 row ids ({int(base[-1])} rows)"
        )
    names = list(DEVICE_COLUMNS) + ["alt_prefix"]
    cols = {
        name: np.concatenate([s.cols[name] for s in shards])
        for name in names
    }
    chrom_offsets = np.stack(
        [
            s.chrom_offsets.astype(np.int64) + base[i]
            for i, s in enumerate(shards)
        ]
    ).astype(np.int32)
    return cols, chrom_offsets, base


def merge_shards(shards: list[VariantIndexShard]) -> VariantIndexShard:
    """Merge per-VCF shards into one globally sorted shard (vectorised).

    Used when a dataset has multiple VCFs pinned to the same device, and by
    the distinct-variant counter. Genotype bitsets are dropped if sample
    universes differ.
    """
    if len(shards) == 1:
        return shards[0]

    # per-shard chrom codes, concatenated
    codes_parts, shard_ord_parts = [], []
    for s_ord, s in enumerate(shards):
        codes_parts.append(
            (
                np.searchsorted(
                    s.chrom_offsets, np.arange(s.n_rows), side="right"
                )
                - 1
            ).astype(np.int32)
        )
        shard_ord_parts.append(np.full(s.n_rows, s_ord, dtype=np.int32))
    codes_all = np.concatenate(codes_parts)
    shard_all = np.concatenate(shard_ord_parts)
    pos_all = np.concatenate([s.cols["pos"] for s in shards])
    row_all = np.concatenate(
        [np.arange(s.n_rows, dtype=np.int64) for s in shards]
    )
    # stable order by (code, pos), shard then original row as tiebreakers —
    # keeps each record's alt rows adjacent (lexsort: last key is primary)
    order = np.lexsort((row_all, shard_all, pos_all, codes_all))

    n = len(order)
    out_cols = {}
    for name in DEVICE_COLUMNS:
        out_cols[name] = np.concatenate([s.cols[name] for s in shards])[order]
    out_prefix = np.concatenate([s.cols["alt_prefix"] for s in shards])[order]

    # rec_id renumber: records stay contiguous after the stable sort, so a
    # change-flag cumsum yields nondecreasing ids
    old_rec = np.concatenate([s.cols["rec_id"] for s in shards])[order]
    old_shard = shard_all[order]
    if n:
        change = np.ones(n, dtype=np.int64)
        change[1:] = (old_rec[1:] != old_rec[:-1]) | (
            old_shard[1:] != old_shard[:-1]
        )
        out_cols["rec_id"] = (np.cumsum(change) - 1).astype(np.int32)
        n_records = int(change.sum())
    else:
        n_records = 0

    # vt vocab union + per-shard remap
    vt_vocab: list[str] = ["N/A"]
    vt_idx = {"N/A": 0}
    vt_parts = []
    for s in shards:
        lut = np.zeros(len(s.meta["vt_vocab"]), dtype=np.int16)
        for j, vt in enumerate(s.meta["vt_vocab"]):
            if vt not in vt_idx:
                vt_idx[vt] = len(vt_vocab)
                vt_vocab.append(vt)
            lut[j] = vt_idx[vt]
        vt_parts.append(lut[s.vt_codes])
    vt_codes = np.concatenate(vt_parts)[order]

    same_samples = all(
        s.meta["sample_names"] == shards[0].meta["sample_names"] for s in shards
    )
    planes: dict[str, np.ndarray | None] = {}
    for plane in ("gt_bits", "gt_bits2", "tok_bits1", "tok_bits2"):
        planes[plane] = None
        if same_samples and all(
            getattr(s, plane) is not None for s in shards
        ):
            planes[plane] = np.concatenate(
                [getattr(s, plane) for s in shards]
            )[order]
    # overflow side-tables: remap old per-shard rows to merged positions
    inv_order = np.empty(n, dtype=np.int64)
    inv_order[order] = np.arange(n)
    row_base = np.cumsum([0] + [s.n_rows for s in shards[:-1]])
    for plane in ("gt_overflow", "tok_overflow"):
        planes[plane] = None
        if same_samples and all(
            getattr(s, plane) is not None for s in shards
        ):
            parts = []
            for base, s in zip(row_base, shards):
                arr = getattr(s, plane)
                if len(arr):
                    remapped = arr.copy()
                    remapped[:, 0] = inv_order[arr[:, 0] + base]
                    parts.append(remapped)
            planes[plane] = (
                np.concatenate(parts)
                if parts
                else np.zeros((0, 3), dtype=np.int64)
            )

    # blobs: offset each shard's row ids into the concatenated blob space
    ref_blob_cat = np.concatenate([s.ref_blob for s in shards])
    alt_blob_cat = np.concatenate([s.alt_blob for s in shards])

    def _cat_offsets(get_off):
        parts = []
        base = 0
        for s in shards:
            off = get_off(s).astype(np.int64)
            parts.append(off[:-1] + base)
            base += int(off[-1])
        ends = []
        base = 0
        for s in shards:
            off = get_off(s).astype(np.int64)
            ends.append(off[1:] + base)
            base += int(off[-1])
        return np.concatenate(parts), np.concatenate(ends)

    ref_starts, ref_ends = _cat_offsets(lambda s: s.ref_off)
    alt_starts, alt_ends = _cat_offsets(lambda s: s.alt_off)

    def _regather(blob, starts, ends, order):
        off2 = np.zeros(n + 1, dtype=np.int64)
        lens = (ends - starts)[order]
        np.cumsum(lens, out=off2[1:])
        total = int(off2[-1])
        idx = np.repeat(starts[order] - off2[:-1], lens) + np.arange(
            total, dtype=np.int64
        )
        return blob[idx] if total else np.zeros(0, np.uint8), off2.astype(
            np.uint32
        )

    ref_blob, ref_off = _regather(ref_blob_cat, ref_starts, ref_ends, order)
    alt_blob, alt_off = _regather(alt_blob_cat, alt_starts, alt_ends, order)

    chrom_offsets = np.zeros(N_CHROM_CODES + 1, dtype=np.int32)
    sorted_codes = codes_all[order]
    for c in range(N_CHROM_CODES + 1):
        chrom_offsets[c] = np.searchsorted(sorted_codes, c, side="left")

    chrom_native: dict[str, str] = {}
    for s in shards:
        for canon, native in s.meta.get("chrom_native", {}).items():
            chrom_native.setdefault(canon, native)

    meta = dict(shards[0].meta)
    meta.update(
        n_rows=n,
        n_records=n_records,
        vt_vocab=vt_vocab,
        variant_count=n,
        call_count=int(sum(s.meta["call_count"] for s in shards)),
        dropped_records=int(
            sum(s.meta.get("dropped_records", 0) for s in shards)
        ),
        chrom_native=chrom_native,
        merged_from=[s.meta.get("vcf_location", "") for s in shards],
    )
    return VariantIndexShard(
        meta=meta,
        cols={**out_cols, "alt_prefix": out_prefix},
        chrom_offsets=chrom_offsets,
        ref_blob=ref_blob,
        ref_off=ref_off,
        alt_blob=alt_blob,
        alt_off=alt_off,
        vt_codes=vt_codes,
        **planes,
    )


# ---------------------------------------------------------------------------
# Native-tokenized fast build path
# ---------------------------------------------------------------------------


def _span_contents(text_np: np.ndarray, off: np.ndarray, length: np.ndarray):
    """(unique_bytes_list, inverse) content-deduplicating span arrays.

    Spans are (offset, length) into ``text_np``; rows are grouped by
    length and uniqued as fixed-width byte matrices (fully vectorised),
    so downstream per-allele work (hashing, flag classification) runs
    once per UNIQUE string instead of once per row. Lengths never
    collide across groups, so ids are globally unique by content."""
    n = len(off)
    inverse = np.zeros(n, dtype=np.int64)
    uniq: list[bytes] = []
    off = off.astype(np.int64)
    for L in np.unique(length):
        li = int(L)
        idx = np.flatnonzero(length == L)
        if li == 0:
            inverse[idx] = len(uniq)
            uniq.append(b"")
            continue
        if li <= 64:
            mat = text_np[off[idx][:, None] + np.arange(li)]
            u, inv = np.unique(mat, axis=0, return_inverse=True)
            base = len(uniq)
            raw = u.tobytes()
            uniq.extend(
                raw[k * li : (k + 1) * li] for k in range(len(u))
            )
            inverse[idx] = base + inv.ravel()
        else:  # rare long alleles
            seen: dict[bytes, int] = {}
            for i in idx:
                b = bytes(text_np[off[i] : off[i] + li])
                j = seen.get(b)
                if j is None:
                    j = seen[b] = len(uniq)
                    uniq.append(b)
                inverse[i] = j
    return uniq, inverse


def _first_appearance_ids(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(ids, order): dense ids by order of first appearance, plus the
    original values' first-appearance ordering (np.unique sorts by value;
    this restores encounter order, matching the python loop)."""
    u, first, inv = np.unique(arr, return_index=True, return_inverse=True)
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(u), dtype=np.int64)
    rank[order] = np.arange(len(u))
    return rank[inv], u[order]


def build_index_from_text(
    text: bytes,
    *,
    dataset_id: str = "",
    vcf_location: str = "",
    sample_names: list[str] | None = None,
) -> VariantIndexShard:
    """Columnar index straight from VCF body text via the native
    tokenizer — one C pass for record/field extraction plus vectorised
    numpy assembly, replacing the per-line ``parse_record`` + per-row
    python loop of :func:`build_index`. Produces BIT-IDENTICAL shards
    (parity-fuzzed in tests/test_tokenize_build.py); callers fall back
    to the python path when the native library is unavailable or the
    input uses a shape the fast path refuses (e.g. AC= arity mismatch).
    """
    from .. import native
    from ..utils.chrom import normalize_chromosome

    sample_names = sample_names or []
    n_samples = len(sample_names)
    gt_words = (n_samples + 31) // 32 if n_samples else 0

    # fused single-pass tokenizer+planes when available (r4 ingest hot
    # path: one scan instead of tokenize + gt_planes re-parse); the
    # unfused pair stays as fallback and as the parity cross-check
    fused = True
    try:
        tk = native.tokenize_planes(text, n_samples, gt_words)
    except native.NativeUnavailable:
        fused = False
        tk = native.tokenize(text, n_samples)
    n_rec = int(tk["n_rec"])
    text_np = np.frombuffer(text or b"\0", dtype=np.uint8)

    if n_rec == 0:
        return build_index(
            [],
            dataset_id=dataset_id,
            vcf_location=vcf_location,
            sample_names=sample_names,
        )

    # -- chromosome codes + native-spelling map (record level) -------------
    chrom_uniq, chrom_uid = _span_contents(
        text_np, tk["chrom_off"], tk["chrom_len"]
    )
    uid_code = np.asarray(
        [chromosome_code(b.decode()) for b in chrom_uniq], dtype=np.int32
    )
    rec_code = uid_code[chrom_uid]
    kept_rec = rec_code != 0
    chrom_native: dict[str, str] = {}
    _ids, uid_first_order = _first_appearance_ids(chrom_uid)
    for uid in uid_first_order:
        s = chrom_uniq[int(uid)]
        if uid_code[int(uid)] != 0:
            chrom_native.setdefault(normalize_chromosome(s.decode()), s.decode())

    # -- effective AC/AN (record level) ------------------------------------
    alt_start = tk["alt_start"].astype(np.int64)
    n_alts_per_rec = np.diff(alt_start)
    ac_start = tk["ac_start"].astype(np.int64)
    ac_len = np.diff(ac_start)
    has_ac = tk["has_ac"].astype(bool)
    if (has_ac & kept_rec & (ac_len != n_alts_per_rec)).any():
        # INFO AC arity disagrees with ALT arity: the python path would
        # fault on row materialisation — refuse so the caller falls back
        raise ValueError("AC= arity mismatch; fast path refused")
    eff_an_rec = np.where(
        tk["has_an"].astype(bool), tk["an"], tk["tok_total"]
    ).astype(np.int64)

    # -- row explosion (one row per alt of each kept record) ---------------
    rec_of_alt = np.repeat(np.arange(n_rec, dtype=np.int64), n_alts_per_rec)
    alt_ord = np.arange(len(rec_of_alt), dtype=np.int64) - np.repeat(
        alt_start[:-1], n_alts_per_rec
    )
    keep_row = kept_rec[rec_of_alt]
    rec_of_alt = rec_of_alt[keep_row]
    alt_ord_row = alt_ord[keep_row]
    flat_alt_idx = np.flatnonzero(keep_row)
    n = len(rec_of_alt)

    order = np.lexsort(
        (alt_ord_row, rec_of_alt, tk["pos"][rec_of_alt], rec_code[rec_of_alt])
    )
    rec_row = rec_of_alt[order]
    alt_ord_row = alt_ord_row[order]
    flat_alt_idx = flat_alt_idx[order]
    code_row = rec_code[rec_row]
    pos_row = tk["pos"][rec_row]

    rec_id_row, _ = _first_appearance_ids(rec_row)

    # -- per-row AC (INFO value or genotype tally) -------------------------
    ac_idx = np.clip(ac_start[rec_row] + alt_ord_row, 0,
                     max(len(tk["ac"]) - 1, 0))
    ac_info = tk["ac"][ac_idx] if len(tk["ac"]) else np.zeros(n, np.int64)
    ac_rows = np.where(
        has_ac[rec_row], ac_info, tk["ac_gt"][flat_alt_idx]
    ).astype(np.int64)

    # -- allele contents (unique-deduplicated) -----------------------------
    ref_uniq, ref_uid_rec = _span_contents(
        text_np, tk["ref_off"], tk["ref_len"]
    )
    ref_uid = ref_uid_rec[rec_row]
    alt_uniq, alt_uid_flat = _span_contents(
        text_np, tk["alt_off"], tk["alt_len"]
    )
    alt_uid = alt_uid_flat[flat_alt_idx]

    ref_hash_u = np.asarray(
        [fnv1a32(b.upper()) for b in ref_uniq], dtype=np.int32
    )
    alt_hash_u = np.asarray(
        [fnv1a32(b.upper()) for b in alt_uniq], dtype=np.int32
    )
    alt_strs = [b.decode() for b in alt_uniq]
    alt_flags_u = np.asarray([_alt_flags(s) for s in alt_strs], np.int32)
    alt_prefix_u = np.stack(
        [pack_prefix16(b) for b in alt_uniq]
    ).astype(np.uint32)
    ref_strs = [b.decode() for b in ref_uniq]
    pair_key = ref_uid * (len(alt_uniq) + 1) + alt_uid
    pair_ids, pair_vals = _first_appearance_ids(pair_key)
    repeat_u = np.asarray(
        [
            _ref_repeat_k(
                ref_strs[int(k) // (len(alt_uniq) + 1)],
                alt_strs[int(k) % (len(alt_uniq) + 1)],
            )
            for k in pair_vals
        ],
        dtype=np.int32,
    )

    # -- VT vocab (first appearance over sorted rows; off>0 = present) -----
    # vectorised: rows map to an effective uid (0 = absent -> "N/A",
    # else content uid + 1); codes assign per UNIQUE uid in row
    # first-appearance order, deduplicating by STRING so a literal
    # "VT=N/A" shares index 0 exactly like the python path's dict
    vt_present = tk["vt_off"] > 0
    vt_uniq, vt_uid_rec = _span_contents(text_np, tk["vt_off"], tk["vt_len"])
    eff_rec = np.where(vt_present, vt_uid_rec + 1, 0)
    row_eff = eff_rec[rec_row]
    _ids, eff_first_order = _first_appearance_ids(
        np.concatenate([np.zeros(1, np.int64), row_eff])  # "N/A" is code 0
    )
    vt_vocab = ["N/A"]
    vt_index = {"N/A": 0}
    eff_to_code = np.zeros(len(vt_uniq) + 1, dtype=np.int16)
    for v in eff_first_order:
        s = "N/A" if v == 0 else vt_uniq[int(v) - 1].decode()
        c = vt_index.get(s)
        if c is None:
            c = vt_index[s] = len(vt_vocab)
            vt_vocab.append(s)
        eff_to_code[int(v)] = c
    vt_codes = eff_to_code[row_eff]

    # -- columns -----------------------------------------------------------
    ref_len_row = tk["ref_len"][rec_row].astype(np.int64)
    alt_len_row = tk["alt_len"][flat_alt_idx].astype(np.int64)
    cols = {
        "pos": pos_row.astype(np.int32),
        "rec_end": (pos_row + ref_len_row - 1).astype(np.int32),
        "ref_len": ref_len_row.astype(np.int32),
        "alt_len": alt_len_row.astype(np.int32),
        "ref_hash": ref_hash_u[ref_uid],
        "alt_hash": alt_hash_u[alt_uid],
        "ref_repeat_k": repeat_u[pair_ids],
        "flags": (
            alt_flags_u[alt_uid]
            | np.where(has_ac[rec_row], FLAG.AC_INFO, 0)
            | np.where(tk["has_an"][rec_row].astype(bool), FLAG.AN_INFO, 0)
        ).astype(np.int32),
        "ac": ac_rows.astype(np.int32),
        "an": eff_an_rec[rec_row].astype(np.int32),
        "rec_id": rec_id_row.astype(np.int32),
    }
    alt_prefix = alt_prefix_u[alt_uid]

    chrom_offsets = np.zeros(N_CHROM_CODES + 1, dtype=np.int32)
    for c in range(N_CHROM_CODES + 1):
        chrom_offsets[c] = np.searchsorted(code_row, c, side="left")

    # -- blobs (ragged vectorised gather) ----------------------------------
    def ragged(offs: np.ndarray, lens: np.ndarray):
        total = int(lens.sum())
        out_off = np.zeros(n + 1, dtype=np.uint32)
        np.cumsum(lens, out=out_off[1:] if n else None)
        if total == 0:
            return np.zeros(0, np.uint8), out_off
        starts = np.repeat(offs.astype(np.int64), lens)
        intra = np.arange(total, dtype=np.int64) - np.repeat(
            out_off[:-1].astype(np.int64), lens
        )
        return text_np[starts + intra].copy(), out_off

    ref_blob, ref_off = ragged(tk["ref_off"][rec_row].astype(np.int64),
                               ref_len_row)
    alt_blob, alt_off = ragged(tk["alt_off"][flat_alt_idx].astype(np.int64),
                               alt_len_row)

    # -- genotype planes -----------------------------------------------
    gt_bits = gt_bits2 = tok_bits1 = tok_bits2 = None
    gt_over = tok_over = None
    if gt_words and fused:
        # planes came out of the same native pass in TEXT order; one
        # gather reorders them to final row order, and the overflow
        # triples remap through the same permutation
        gt_bits = tk["g1"][flat_alt_idx]
        gt_bits2 = tk["g2"][flat_alt_idx]
        tok_bits1 = tk["t1"][rec_row]
        tok_bits2 = tk["t2"][rec_row]
        inv = np.full(int(tk["n_alt"]), -1, np.int64)
        inv[flat_alt_idx] = np.arange(n, dtype=np.int64)
        g_o = tk["gt_over"]
        if len(g_o):
            rows_m = inv[g_o[:, 0]]
            keep = rows_m >= 0
            gt_over = np.stack(
                [rows_m[keep], g_o[keep, 1], g_o[keep, 2]], axis=1
            )
        else:
            gt_over = np.zeros((0, 3), np.int64)
        t_o = tk["tok_over"]
        trip = []
        if len(t_o):
            # replicate each (rec, sample, ntok) onto that record's rows
            order2 = np.argsort(rec_row, kind="stable")
            sorted_rec = rec_row[order2]
            for r, smp, ntok in t_o.tolist():
                lo = int(np.searchsorted(sorted_rec, r, side="left"))
                hi = int(np.searchsorted(sorted_rec, r, side="right"))
                for row in order2[lo:hi].tolist():
                    trip.append((row, smp, ntok))
        tok_over = (
            np.asarray(trip, np.int64).reshape(-1, 3)
            if trip
            else np.zeros((0, 3), np.int64)
        )
    elif gt_words:
        gt_over = np.zeros((0, 3), np.int64)
        tok_over = np.zeros((0, 3), np.int64)
        if n and len(tk["gt_blob"]):
            # bind the returned planes directly (gt_planes allocates
            # them); the zeros allocation below is only for the
            # no-genotype case
            (
                gt_bits, gt_bits2, tok_bits1, tok_bits2, g_o, t_o
            ) = native.gt_planes(
                tk["gt_blob"],
                tk["gt_off"],
                n_rec,
                n_samples,
                rec_row.astype(np.int32),
                (alt_ord_row + 1).astype(np.int32),
                gt_words,
            )
            gt_over = g_o.reshape(-1, 3)
            tok_over = t_o.reshape(-1, 3)
        else:
            gt_bits = np.zeros((n, gt_words), np.uint32)
            gt_bits2 = np.zeros_like(gt_bits)
            tok_bits1 = np.zeros_like(gt_bits)
            tok_bits2 = np.zeros_like(gt_bits)

    kept_ids = np.unique(rec_row)
    meta = {
        "dataset_id": dataset_id,
        "vcf_location": vcf_location,
        "sample_names": sample_names,
        "vt_vocab": vt_vocab,
        "n_rows": n,
        "n_records": int(len(kept_ids)),
        "dropped_records": int((~kept_rec).sum()),
        "variant_count": n,
        "call_count": int(eff_an_rec[kept_ids].sum()),
        "sample_count": n_samples,
        "chrom_native": chrom_native,
        "format_version": 1,
    }
    return VariantIndexShard(
        meta=meta,
        cols={**cols, "alt_prefix": alt_prefix},
        chrom_offsets=chrom_offsets,
        ref_blob=ref_blob,
        ref_off=ref_off,
        alt_blob=alt_blob,
        alt_off=alt_off,
        vt_codes=vt_codes,
        gt_bits=gt_bits,
        gt_bits2=gt_bits2,
        tok_bits1=tok_bits1,
        tok_bits2=tok_bits2,
        gt_overflow=gt_over,
        tok_overflow=tok_over,
    )
