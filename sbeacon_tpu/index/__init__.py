from .columnar import (
    FLAG,
    VariantIndexShard,
    build_index,
    fnv1a32,
    load_index,
    merge_shards,
    save_index,
)

__all__ = [
    "FLAG",
    "VariantIndexShard",
    "build_index",
    "fnv1a32",
    "load_index",
    "merge_shards",
    "save_index",
]
