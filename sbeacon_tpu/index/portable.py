"""Portable binary index files: the reference's on-S3 variant-index format.

The reference's ingest pipeline materialises, per VCF, a set of compact
binary region files under
``vcf-summaries/contig/{contig}/{escaped-location}/regions/{start}-{end}-{size}``
(reference: write_data_to_s3.h:98 key layout, parsed back at
initDuplicateVariantSearch.py:80-90), each a gzip stream of
``pos:u64 | len:u16 | packed_ref '_' packed_alt`` records with 4-bit base
packing, split at >100 kb position gaps (MAX_SLICE_GAP, main.tf:215) and
a 50 MB size ceiling (VCF_S3_OUTPUT_SIZE_LIMIT, main.tf:17). The
duplicate-variant search then reads ranges of these files and dedupes on
the ``{pos}{payload}`` key (duplicateVariantSearch.cpp:56-59).

Here the columnar shard (``columnar.py``) is the primary store; this
module provides the same portable exchange format — export from a shard,
range-filtered import, cross-dataset distinct-count — with the hot
encode/decode in C++ (``native/src/index_codec.cpp``) and a pure-Python
mirror used as fallback and as the round-trip oracle in tests.
"""

from __future__ import annotations

import struct
import zlib
from pathlib import Path

import numpy as np

from .. import native
from ..utils.chrom import CHROMOSOME_CODES
from .columnar import VariantIndexShard

#: reference terraform ceilings (main.tf:16-17,215)
MAX_SLICE_GAP = 100_000
MAX_FILE_RAW_BYTES = 50 * 1024 * 1024

_BASE_CODE = {
    65: 1, 97: 1,  # A a
    67: 2, 99: 2,  # C c
    71: 3, 103: 3,  # G g
    84: 4, 116: 4,  # T t
    78: 5, 110: 5,  # N n
    42: 6,  # *
    46: 7,  # .
}
_CODE_BASE = b"?ACGTN*."


def pack_seq(seq: bytes) -> bytes:
    """4-bit pack (first base of a pair in the high nibble, odd trailing
    base low-nibble alone); symbolic ``<...>`` and any unpackable text
    pass through raw (brackets stripped) — write_data_to_s3.h compressSeq."""
    n = len(seq)
    if n >= 2 and seq[0] == 0x3C and seq[-1] == 0x3E:  # <...>
        return seq[1:-1]
    codes = []
    for b in seq:
        c = _BASE_CODE.get(b)
        if c is None:
            return seq
        codes.append(c)
    if n == 1:
        return bytes(codes)
    out = bytearray()
    for i in range(0, n - 1, 2):
        out.append((codes[i] << 4) | codes[i + 1])
    if n % 2:
        out.append(codes[-1])
    return bytes(out)


def packed_len(seq: bytes) -> int:
    """len(pack_seq(seq)) computed arithmetically, without building the
    packed bytes (used by the export sizing pass)."""
    n = len(seq)
    if n >= 2 and seq[0] == 0x3C and seq[-1] == 0x3E:
        return n - 2
    if not all(b in _BASE_CODE for b in seq):
        return n  # raw passthrough
    return 1 if n == 1 else n // 2 + n % 2


_BASE_MEMBER = np.zeros(256, dtype=bool)
for _b in _BASE_CODE:
    _BASE_MEMBER[_b] = True


def packed_len_rows(blob: np.ndarray, off: np.ndarray) -> np.ndarray:
    """Vectorised :func:`packed_len` over every (blob, offsets) row —
    symbolic detection via first/last bytes, packability via a segment
    all() (cumsum-of-nonmembers difference), same arithmetic."""
    off = off.astype(np.int64)
    lens = np.diff(off)
    n = len(lens)
    starts, ends = off[:-1], off[1:]
    nz = lens > 0
    first = np.zeros(n, np.uint8)
    last = np.zeros(n, np.uint8)
    first[nz] = blob[starts[nz]]
    last[nz] = blob[ends[nz] - 1]
    symbolic = (lens >= 2) & (first == 0x3C) & (last == 0x3E)
    bad_cum = np.zeros(len(blob) + 1, np.int64)
    np.cumsum(~_BASE_MEMBER[blob], out=bad_cum[1:] if len(blob) else None)
    packable = (bad_cum[ends] - bad_cum[starts]) == 0
    packed = np.where(lens == 1, 1, lens // 2 + lens % 2)
    return np.where(symbolic, lens - 2, np.where(packable, packed, lens))


def unpack_seq(packed: bytes) -> bytes | None:
    """Inverse of :func:`pack_seq` for packed payloads; None when the
    bytes cannot be a packed sequence.

    HEURISTIC, exactly as ambiguous as the reference format itself: a
    raw/symbolic payload whose every byte happens to parse as valid
    nibble pairs (e.g. ``<GATA>`` stored raw as ``GATA``) decodes to a
    fabricated sequence. The format has no raw marker (the reference
    never decodes — it only compares packed payloads as opaque dedupe
    keys, duplicateVariantSearch.cpp:56-59); treat decoded text as
    display-only and use the payload bytes for identity."""
    out = bytearray()
    n = len(packed)
    for i, b in enumerate(packed):
        hi, lo = b >> 4, b & 0xF
        if lo == 0 or lo > 7 or hi > 7:
            return None
        if hi == 0:
            if i + 1 != n:
                return None
            out.append(_CODE_BASE[lo])
        else:
            out.append(_CODE_BASE[hi])
            out.append(_CODE_BASE[lo])
    return bytes(out)


def pack_records_py(pos, refs, alts, *, level: int = 9) -> bytes:
    """Pure-Python encoder (same wire format as the native codec)."""
    if not (len(pos) == len(refs) == len(alts)):
        raise ValueError("pos/refs/alts length mismatch")
    parts = []
    for p, ref, alt in zip(pos, refs, alts):
        payload = pack_seq(ref) + b"_" + pack_seq(alt)
        if len(payload) > 0xFFFF:
            raise ValueError("allele too long for u16 record length")
        parts.append(struct.pack("<QH", int(p), len(payload)) + payload)
    co = zlib.compressobj(level, zlib.DEFLATED, 15 + 16)
    return co.compress(b"".join(parts)) + co.flush()


def unpack_records_py(
    blob: bytes, range_start: int = 0, range_end: int = 2**63 - 1
):
    """Pure-Python decoder: (pos uint64 ndarray, payload list[bytes]).

    Inflates every concatenated gzip member: the reference writer emits
    multiple back-to-back members in one region object when its 50 MB raw
    ceiling is hit (write_data_to_s3.h saveOutputToS3:39-92), so a single
    ``zlib.decompress`` call would silently drop all records after the
    first member.
    """
    chunks = []
    rest = blob
    while rest:
        do = zlib.decompressobj(15 + 32)
        chunks.append(do.decompress(rest))
        chunks.append(do.flush())
        if not do.eof:
            raise ValueError("truncated gzip member")
        rest = do.unused_data
    raw = b"".join(chunks)
    positions, payloads = [], []
    i, n = 0, len(raw)
    while i + 10 <= n:
        p, ln = struct.unpack_from("<QH", raw, i)
        i += 10
        if i + ln > n:
            raise ValueError("truncated record")
        if range_start <= p <= range_end:
            positions.append(p)
            payloads.append(raw[i : i + ln])
        i += ln
    if i != n:
        raise ValueError("truncated record")
    return np.asarray(positions, dtype=np.uint64), payloads


def pack_records(pos, refs, alts, *, level: int = 9) -> bytes:
    if native.available():
        return native.pack_records(pos, list(refs), list(alts), level=level)
    return pack_records_py(pos, refs, alts, level=level)


def unpack_records(
    blob: bytes, range_start: int = 0, range_end: int = 2**63 - 1
):
    if native.available():
        return native.unpack_records(blob, range_start, range_end)
    return unpack_records_py(blob, range_start, range_end)


# -- region-file export / import ---------------------------------------------


def _escape_location(location: str) -> str:
    """Reference key escaping: '/' -> '%' (write_data_to_s3.h ctor)."""
    return str(location).replace("/", "%")


def export_region_files(
    shard: VariantIndexShard,
    out_dir: str | Path,
    *,
    max_gap: int = MAX_SLICE_GAP,
    max_raw_bytes: int = MAX_FILE_RAW_BYTES,
    level: int = 6,
) -> list[Path]:
    """Write the shard as reference-layout region files:
    ``contig/{chrom}/{escaped-location}/regions/{start}-{end}-{rawsize}``,
    new file at every >max_gap position gap or raw-size ceiling.

    ``level`` is zlib's standard default (6): exports were ~20% of ingest
    wall time at level 9 for low-single-digit % smaller files, and the
    wire format (and the {rawsize} suffix, which counts PRE-compression
    bytes) is identical at any level — importers never see the difference.
    """
    out_dir = Path(out_dir)
    location = _escape_location(shard.meta.get("vcf_location", "unknown"))
    pos = shard.cols["pos"]
    ref_off = shard.ref_off
    alt_off = shard.alt_off
    written: list[Path] = []

    # re-ingest must not leave stale region files from a previous export
    # of this VCF (the export is a full rewrite, like the npz shard);
    # glob-escape the location so [ ] * ? in file names match literally
    import glob as _glob
    import shutil

    for old in out_dir.glob(f"contig/*/{_glob.escape(location)}"):
        shutil.rmtree(old, ignore_errors=True)

    def row_ref_b(i: int) -> bytes:
        # python-fallback flush only (the native path slices blobs whole)
        return shard.ref_blob[ref_off[i] : ref_off[i + 1]].tobytes()

    def row_alt_b(i: int) -> bytes:
        return shard.alt_blob[alt_off[i] : alt_off[i + 1]].tobytes()


    for chrom, code in CHROMOSOME_CODES.items():
        lo = int(shard.chrom_offsets[code])
        hi = int(shard.chrom_offsets[code + 1])
        if hi <= lo:
            continue
        rdir = out_dir / "contig" / chrom / location / "regions"
        rdir.mkdir(parents=True, exist_ok=True)
        # raw record size = 10-byte header + packed ref + '_' + packed alt
        # (the reference's {size} suffix counts the pre-gzip packed stream,
        # write_data_to_s3.h bufferLength) — vectorised over JUST this
        # chromosome's blob span (whole-blob work per chromosome would be
        # O(n_chroms x blob))
        r0, a0 = int(ref_off[lo]), int(alt_off[lo])
        rec_raw = (
            10
            + packed_len_rows(
                shard.ref_blob[r0 : int(ref_off[hi])],
                ref_off[lo : hi + 1].astype(np.int64) - r0,
            )
            + 1
            + packed_len_rows(
                shard.alt_blob[a0 : int(alt_off[hi])],
                alt_off[lo : hi + 1].astype(np.int64) - a0,
            )
        )
        start = lo
        raw_bytes = 0

        def flush(start_row: int, end_row: int, raw: int):
            """[start_row, end_row) -> one region file."""
            if native.available():
                # zero-copy: shard blob slices + rebased offsets go
                # straight to the native packer (no per-row bytes)
                r0, r1 = int(ref_off[start_row]), int(ref_off[end_row])
                a0, a1 = int(alt_off[start_row]), int(alt_off[end_row])
                blob = native.pack_records_arrays(
                    pos[start_row:end_row].astype(np.uint64),
                    shard.ref_blob[r0:r1],
                    ref_off[start_row : end_row + 1] - r0,
                    shard.alt_blob[a0:a1],
                    alt_off[start_row : end_row + 1] - a0,
                    level=level,
                )
            else:
                blob = pack_records(
                    pos[start_row:end_row].astype(np.uint64),
                    [row_ref_b(i) for i in range(start_row, end_row)],
                    [row_alt_b(i) for i in range(start_row, end_row)],
                    level=level,
                )
            name = f"{int(pos[start_row])}-{int(pos[end_row - 1])}-{raw}"
            path = rdir / name
            path.write_bytes(blob)
            written.append(path)

        for i in range(lo, hi):
            gap_split = i > start and int(pos[i]) > int(pos[i - 1]) + max_gap
            size_split = (
                raw_bytes + int(rec_raw[i - lo]) > max_raw_bytes and i > start
            )
            if gap_split or size_split:
                flush(start, i, raw_bytes)
                start, raw_bytes = i, 0
            raw_bytes += int(rec_raw[i - lo])
        flush(start, hi, raw_bytes)
    _write_manifest(out_dir)
    return written


def _write_manifest(out_dir: Path) -> None:
    """Regenerate ``manifest.txt`` (one relative region path per line).

    Object stores have no directory listing over plain HTTP, so the
    manifest is the export's self-describing key list — the role S3
    ListObjects plays for the reference's vcf-summaries/ prefix
    (initDuplicateVariantSearch.py get_object_list)."""
    lines = sorted(
        str(p.relative_to(out_dir))
        for p in out_dir.glob("contig/*/*/regions/*")
    )
    (out_dir / "manifest.txt").write_text("\n".join(lines) + "\n")


def parse_region_filename(path: str | Path) -> tuple[int, int, int]:
    """(start, end, raw_size) from '{start}-{end}-{size}' — the parse at
    initDuplicateVariantSearch.py:80-90."""
    start, end, size = Path(path).name.rsplit("-", 2)
    return int(start), int(end), int(size)


def iter_region_files(root: str | Path):
    """Yield (chrom, location, path, start, end, raw_size) under an export
    root — a local directory, or a remote (http(s)/s3) root whose
    ``manifest.txt`` lists the region keys."""
    from ..io import is_remote, read_bytes

    if is_remote(root):
        base = str(root).rstrip("/")
        for rel in read_bytes(f"{base}/manifest.txt").decode().splitlines():
            rel = rel.strip()
            if not rel:
                continue
            parts = rel.split("/")
            chrom, location = parts[1], parts[2]
            start, end, size = parse_region_filename(parts[-1])
            yield chrom, location, f"{base}/{rel}", start, end, size
        return
    root = Path(root)
    for path in sorted(root.glob("contig/*/*/regions/*")):
        chrom = path.parts[-4]
        location = path.parts[-3]
        start, end, size = parse_region_filename(path)
        yield chrom, location, path, start, end, size


def distinct_variant_count_files(
    roots: list[str | Path],
    *,
    range_start: int = 0,
    range_end: int = 2**63 - 1,
) -> int:
    """Distinct (contig, pos, payload) across exported datasets — the
    duplicateVariantSearch tally (duplicateVariantSearch.cpp:31-84) over
    the portable files instead of live shards."""
    from ..io import read_bytes

    seen: set[tuple[str, int, bytes]] = set()
    for root in roots:
        for chrom, _loc, path, start, end, _size in iter_region_files(root):
            if end < range_start or start > range_end:
                continue
            positions, payloads = unpack_records(
                read_bytes(path), range_start, range_end
            )
            for p, pay in zip(positions.tolist(), payloads):
                seen.add((chrom, int(p), bytes(pay)))
    return len(seen)
