from .app import BeaconApp
from .server import make_server, serve

__all__ = ["BeaconApp", "make_server", "serve"]
