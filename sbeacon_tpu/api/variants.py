"""Variant query orchestration for the API layer.

Glues dataset resolution (metadata store), the variant engine, and the
Beacon aggregation loop (reference: getGenomicVariants/route_g_variants.py:
117-198) into one call used by every variant route: /g_variants,
/g_variants/{id} and each entity-scoped {id}/g_variants.
"""

from __future__ import annotations

import base64
import dataclasses

from ..metadata.filters import entity_search_conditions
from ..payloads import VariantQueryPayload
from ..plan import explain_active
from ..utils.chrom import normalize_chromosome
from .envelopes import variant_entry
from .requests import BeaconRequest, RequestError


def resolve_datasets(
    store,
    ontology,
    assembly_id: str | None,
    filters: list[dict],
    *,
    dataset_ids: list[str] | None = None,
):
    """(dataset_docs, samples_by_dataset) for a variant query.

    With filters the reference joins analyses->datasets and aggregates
    ``_vcfsampleid`` per dataset, which switches the search into
    selected-samples mode (reference route_g_variants.py:117-127
    datasets_query); without filters it is a plain assembly scan
    (datasets_query_fast).
    """
    if assembly_id is None:
        raise RequestError("assemblyId must be specified")
    samples_by_dataset: dict[str, list[str]] = {}
    if filters:
        conditions, params = entity_search_conditions(
            filters, "analyses", "analyses", ontology=ontology, id_modifier="A.id"
        )
        rows = store.query(
            f"SELECT A._datasetid, A._vcfsampleid FROM analyses A "
            f"{conditions}",
            params,
        )
        for ds, sample in rows:
            samples_by_dataset.setdefault(ds, [])
            if sample:
                samples_by_dataset[ds].append(sample)
        ids = sorted(samples_by_dataset)
        if dataset_ids:
            allowed = set(dataset_ids)
            ids = [i for i in ids if i in allowed]
        if not ids:
            return [], {}
        datasets = store.datasets_for_assembly(assembly_id, dataset_ids=ids)
    else:
        datasets = store.datasets_for_assembly(
            assembly_id, dataset_ids=dataset_ids
        )
    return datasets, samples_by_dataset


def encode_internal_id(
    assembly_id: str, chrom: str, pos: str | int, ref: str, alt: str
) -> str:
    internal = f"{assembly_id}\t{chrom}\t{pos}\t{ref}\t{alt}"
    return base64.b64encode(internal.encode()).decode()


def decode_internal_id(variant_id: str) -> tuple[str, str, int, str, str]:
    """(assembly, chrom, pos0, ref, alt); pos0 already 0-based (the
    reference decodes then does ``pos - 1``, route_g_variants_id.py:71-77).
    """
    try:
        decoded = base64.b64decode(variant_id.encode()).decode()
        assembly, chrom, pos, ref, alt = decoded.split("\t")
        return assembly, chrom, int(pos) - 1, ref, alt
    except Exception:
        raise RequestError(f"malformed variant id {variant_id!r}") from None


class VariantAggregation:
    """The cross-dataset aggregation accumulator of route_g_variants."""

    def __init__(self, assembly_id: str):
        self.assembly_id = assembly_id
        self.exists = False
        self.variants: set[str] = set()
        self.results: list[dict] = []
        self._found: set[str] = set()
        # sample hits per dataset (used by /g_variants/{id}/{entity} routes)
        self.sample_names_by_dataset: dict[str, list[str]] = {}

    def add(self, responses, *, granularity: str, check_all: bool) -> None:
        for qr in responses:
            self.exists = self.exists or qr.exists
            if not self.exists:
                continue
            if granularity == "boolean":
                return
            if qr.sample_names:
                seen = self.sample_names_by_dataset.setdefault(
                    qr.dataset_id, []
                )
                seen_set = set(seen)
                seen.extend(
                    s for s in qr.sample_names if s not in seen_set
                )
            if not check_all:
                continue
            self.variants.update(qr.variants)
            for variant in qr.variants:
                chrom, pos, ref, alt, typ = variant.split("\t")
                internal_id = f"{self.assembly_id}\t{chrom}\t{pos}\t{ref}\t{alt}"
                if internal_id not in self._found:
                    self._found.add(internal_id)
                    self.results.append(
                        variant_entry(
                            base64.b64encode(internal_id.encode()).decode(),
                            self.assembly_id,
                            ref,
                            alt,
                            int(pos),
                            int(pos) + len(alt),
                            typ,
                        )
                    )


def run_variant_search(
    engine,
    datasets: list[dict],
    req: BeaconRequest,
    *,
    start_min: int,
    start_max: int,
    end_min: int,
    end_max: int,
    reference_name: str | None = None,
    reference_bases: str | None = None,
    alternate_bases: str | None = None,
    variant_type: str | None = None,
    samples_by_dataset: dict[str, list[str]] | None = None,
    include_resultset_responses: str | None = None,
    runner=None,
) -> VariantAggregation:
    """Dispatch one search over the resolved datasets and aggregate.

    With ``runner`` (an ``AsyncQueryRunner``) the search goes through the
    query job table: concurrent identical queries coalesce onto one
    execution and completed results are served from the TTL'd cache — the
    caching the reference stubs out (variant_queries.py:94-103 "TODO
    implement caching"). Without it, a direct engine call."""
    reference_name = (
        reference_name if reference_name is not None else req.reference_name
    )
    if reference_name is None:
        raise RequestError("referenceName must be specified")
    include = (
        include_resultset_responses
        if include_resultset_responses is not None
        else req.include_resultset_responses
    )
    check_all = include in ("HIT", "ALL")
    samples_by_dataset = samples_by_dataset or {}
    # selected-samples mode iff every dataset came with samples
    # (reference search_variants.py:88-91 gates per dataset on
    # len(dataset_samples) == len(datasets))
    selected = bool(samples_by_dataset) and all(
        samples_by_dataset.get(d["id"]) for d in datasets
    )
    payload = VariantQueryPayload(
        dataset_ids=[d["id"] for d in datasets],
        reference_name=normalize_chromosome(reference_name),
        reference_bases=(
            reference_bases
            if reference_bases is not None
            else req.reference_bases
        ),
        alternate_bases=(
            alternate_bases
            if alternate_bases is not None
            else req.alternate_bases
        ),
        start_min=start_min,
        start_max=start_max,
        end_min=end_min,
        end_max=end_max,
        variant_type=(
            variant_type if variant_type is not None else req.variant_type
        ),
        variant_min_length=req.variant_min_length,
        variant_max_length=req.variant_max_length,
        requested_granularity=req.granularity,
        include_datasets=include,
        include_samples=True,
        sample_names=samples_by_dataset if selected else {},
        selected_samples_only=selected,
    )
    if explain_active():
        # an explained request must describe a LIVE execution of
        # exactly this query: never served from (or written to) the
        # response cache, and never coalesced onto a query job whose
        # plan belongs to some earlier request
        payload = dataclasses.replace(payload, no_response_cache=True)
        runner = None
    if runner is not None:
        from ..query_jobs import JobStatus
        from ..resilience import current_deadline

        query_id, _ = runner.submit(
            payload, fingerprint=engine.index_fingerprint()
        )
        responses = runner.result(
            query_id, wait_s=engine.config.engine.request_timeout_s
        )
        if responses is None:
            # the result wait is deadline-clamped: distinguish "the
            # request ran out of time" (504, retryable with a longer
            # deadline) from "the engine exceeded request_timeout_s"
            current_deadline().check("variant query")
            if runner.poll(query_id) is JobStatus.RUNNING:
                # still executing past request_timeout_s: starting a second
                # identical search would double device load exactly when
                # the engine is slowest — report the timeout instead (the
                # reference's REQUEST_TIMEOUT gives up the same way,
                # variantutils/search_variants.py:134-141)
                raise TimeoutError(
                    f"variant query {query_id} timed out after "
                    f"{engine.config.engine.request_timeout_s}s"
                )
            # job abandoned (worker failed): run directly so the real
            # error surfaces to this caller
            responses = engine.search(payload)
    else:
        responses = engine.search(payload)
    agg = VariantAggregation(req.assembly_id or "")
    agg.add(
        responses,
        granularity=req.granularity,
        check_all=check_all,
    )
    return agg
