"""Dataset submission: POST/PATCH /submit.

The reference's submitDataset lambda (reference: lambda/submitDataset/
lambda_function.py:191-261 submit_dataset/update_dataset + :79-188
create_dataset): validate against a JSON Schema, verify every VCF is
reachable and indexed, write the dataset + chromosome map, fan the metadata
entities out to the store, and optionally kick the indexer. Here the
summarisation pipeline hook replaces the commented-out SNS kick
(reference :216-218 — wired unconditionally, as SURVEY.md directs).
"""

from __future__ import annotations

import jsonschema

from ..metadata import ENTITY_KINDS  # noqa: F401  (re-export convenience)
from .requests import RequestError

_ENTITY_ARRAY = {"type": "array", "items": {"type": "object"}}

# compact schema with the same required surface as the reference's
# submitDataset-schema-new.json / -update.json pair
SUBMIT_SCHEMA_NEW = {
    "type": "object",
    "properties": {
        "datasetId": {"type": "string", "minLength": 1},
        "assemblyId": {"type": "string", "minLength": 1},
        "vcfLocations": {
            "type": "array",
            "items": {"type": "string", "minLength": 1},
        },
        "vcfGroups": {
            "type": "array",
            "items": {"type": "array", "items": {"type": "string"}},
        },
        "dataset": {"type": "object"},
        "cohortId": {"type": "string"},
        "cohort": {"type": "object"},
        "individuals": _ENTITY_ARRAY,
        "biosamples": _ENTITY_ARRAY,
        "runs": _ENTITY_ARRAY,
        "analyses": _ENTITY_ARRAY,
        "index": {"type": "boolean"},
    },
    "required": ["datasetId", "assemblyId", "vcfLocations", "dataset"],
    "additionalProperties": False,
}

SUBMIT_SCHEMA_UPDATE = {
    **SUBMIT_SCHEMA_NEW,
    "required": ["datasetId"],
}


def validate_submission(body: dict, *, update: bool) -> None:
    schema = SUBMIT_SCHEMA_UPDATE if update else SUBMIT_SCHEMA_NEW
    validator = jsonschema.Draft7Validator(schema)
    errors = sorted(validator.iter_errors(body), key=lambda e: e.path)
    if errors:
        raise RequestError(
            "; ".join(e.message for e in errors[:5])
        )


PAYLOAD_REF_SCHEMA = {
    "type": "object",
    "properties": {"payloadRef": {"type": "string", "minLength": 1}},
    "required": ["payloadRef"],
    "additionalProperties": False,
}

#: ceiling for dereferenced submission payloads (a wrong/hostile ref must
#: not OOM the server); generous vs the reference's motivating limit (API
#: Gateway's ~10 MB request cap is WHY s3Payload exists)
MAX_PAYLOAD_REF_BYTES = 512 * 1024 * 1024


def resolve_payload_ref(body: dict) -> dict:
    """``{"payloadRef": "<file path or URL>"}`` -> the real submission.

    The reference accepts ``s3Payload`` bodies pointing at an S3 object so
    submissions can exceed the API gateway's request-size cap (reference:
    submitDataset/lambda_function.py:278-282). The equivalent here is a
    local path or object-store URL (http(s)/s3 via sbeacon_tpu.io)
    holding the JSON document."""
    import json

    from ..io import is_remote, open_source

    ref = body["payloadRef"]
    try:
        # remote refs get a hard byte budget BEFORE any body is read — a
        # hostile Range-less server must not stream past the cap
        src = (
            open_source(ref, max_object_bytes=MAX_PAYLOAD_REF_BYTES)
            if is_remote(ref)
            else open_source(ref)
        )
        n = src.size()
        if n > MAX_PAYLOAD_REF_BYTES:
            raise RequestError(
                f"payloadRef object is {n} bytes "
                f"(limit {MAX_PAYLOAD_REF_BYTES})"
            )
        raw = src.read_range(0, n, workers=4)
    except RequestError:
        raise
    except Exception as e:
        raise RequestError(f"could not read payloadRef {ref}: {e}")
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise RequestError(f"payloadRef {ref} is not valid JSON: {e}")
    if not isinstance(doc, dict):
        raise RequestError(f"payloadRef {ref} must hold a JSON object")
    if "payloadRef" in doc:
        raise RequestError("payloadRef must not nest another payloadRef")
    return doc


def submit_dataset(
    app,
    body: dict,
    *,
    update: bool = False,
) -> dict:
    """Validate and ingest one submission; returns the progress summary."""
    if not isinstance(body, dict):
        raise RequestError("body must be a JSON object")
    if "payloadRef" in body:
        # large-body indirection (the reference's s3Payload form): the
        # inline body is only the pointer; the real submission is
        # fetched, then validated exactly like an inline one
        ref_errors = list(
            jsonschema.Draft7Validator(PAYLOAD_REF_SCHEMA).iter_errors(body)
        )
        if ref_errors:
            raise RequestError(
                "; ".join(e.message for e in ref_errors[:5])
            )
        body = resolve_payload_ref(body)
    validate_submission(body, update=update)

    dataset_id = body["datasetId"]
    cohort_id = body.get("cohortId")
    completed: list[str] = []
    pending: list[str] = []

    existing = app.store.get_by_id("datasets", dataset_id) if update else None

    vcf_locations = body.get("vcfLocations", [])
    # VCF reachability + chromosome map (reference check_vcf_locations
    # :48-76 + get_vcf_chromosomes); delegated to the ingestion layer so
    # the API has no direct file-format knowledge
    chrom_map = []
    if vcf_locations:
        chrom_map = app.ingest.check_vcf_locations(vcf_locations)
        completed.append("Verified VCF locations")
    elif existing:
        # PATCH without vcfLocations keeps the registered VCFs
        vcf_locations = existing.get("_vcfLocations", [])
        chrom_map = existing.get("_vcfChromosomeMap", [])

    groups_given = body.get("vcfGroups")
    if groups_given is not None:
        # an explicit grouping must partition vcfLocations exactly —
        # a spelling mismatch or omission would silently skew sampleCount
        flat = [str(v) for grp in groups_given for v in grp]
        if sorted(flat) != sorted(str(v) for v in vcf_locations):
            raise RequestError(
                "vcfGroups must partition vcfLocations exactly "
                "(every VCF in exactly one group, same spelling)"
            )

    if body.get("dataset") is not None or (
        existing and (body.get("vcfLocations") or groups_given)
    ):
        # a PATCH carrying only new vcfLocations (or only a corrected
        # vcfGroups) must still land on the stored doc, else it verifies
        # but never persists/summarises
        doc = dict(existing or {})
        doc.update(body.get("dataset") or {})
        doc["id"] = dataset_id
        doc["_assemblyId"] = body.get(
            "assemblyId",
            (existing or {}).get("_assemblyId", "UNKNOWN"),
        )
        doc["_vcfLocations"] = vcf_locations
        # default: one group holding every VCF — all VCFs share one
        # sample cohort unless the submitter says otherwise (reference
        # submitDataset:93 vcfGroups = [vcfLocations]). A stored default
        # (explicit flag unset) is recomputed whenever vcfLocations
        # change; a submitter-specified grouping is kept only while it
        # still matches the locations.
        if groups_given is not None:
            doc["_vcfGroups"] = groups_given
            doc["_vcfGroupsExplicit"] = True
        else:
            stored = (existing or {}).get("_vcfGroups")
            stored_flat = sorted(
                str(v) for grp in (stored or []) for v in grp
            )
            keep = (
                (existing or {}).get("_vcfGroupsExplicit")
                and stored_flat == sorted(str(v) for v in vcf_locations)
            )
            if not keep:
                doc["_vcfGroups"] = [list(vcf_locations)]
                doc["_vcfGroupsExplicit"] = False
        doc["_vcfChromosomeMap"] = chrom_map
        app.store.upsert("datasets", [doc])
        completed.append("Added dataset metadata")

    if cohort_id and body.get("cohort") is not None:
        doc = dict(body["cohort"])
        doc["id"] = cohort_id
        app.store.upsert("cohorts", [doc])
        completed.append("Added cohorts")

    if dataset_id:
        # the reference drops these silently without a cohortId
        # (lambda_function.py:122 gates on both); here a dataset-only
        # submission still lands its entities, with _cohortId left unset
        for kind in ("individuals", "biosamples", "runs", "analyses"):
            docs = body.get(kind, [])
            if not docs:
                continue
            for doc in docs:
                doc["_datasetId"] = dataset_id
                if cohort_id:
                    doc["_cohortId"] = cohort_id
            app.store.upsert(kind, list(docs))
            completed.append(f"Added {kind}")

    if body.get("index", False):
        app.store.rebuild_indexes()
        completed.append("Rebuilt indexes")
        rcfg = app.config.resolvers
        if rcfg.enabled:
            # ontology closure build (the indexer's index_terms_tree,
            # reference indexer:60-222); failures per-term are logged and
            # counted, never fatal to the submission
            from ..metadata.resolvers import (
                OlsResolver,
                OntoserverResolver,
                TermTreeIndexer,
            )

            stats = TermTreeIndexer(
                app.store,
                app.ontology,
                ols=OlsResolver(rcfg.ols_url),
                ontoserver=OntoserverResolver(rcfg.ontoserver_url),
                workers=rcfg.workers,
            ).run()
            completed.append(
                "Resolved ontology closures "
                f"({stats['resolved']} new, {stats['skipped']} cached, "
                f"{stats['failed']} failed)"
            )

    # ingestion pipeline kick (unconditional, unlike the reference's
    # commented-out SNS publish)
    if vcf_locations:
        pending.extend(app.ingest.schedule_summarisation(dataset_id))

    return {"completed": completed, "pending": pending}
