"""Beacon v2 framework endpoints: /info, /configuration, /map, /entry_types.

The reference serves these as four lambdas of hand-written model JSON
(reference: lambda/getInfo/lambda_function.py:20-57, getConfiguration,
getMap/lambda_function.py, getEntryTypes). Here the Beacon v2 default-model
entry-type descriptors are generated from one compact table so the four
documents stay mutually consistent and the beacon identity comes from the
typed config instead of env vars.
"""

from __future__ import annotations

from datetime import datetime, timezone

from ..config import BeaconInfo
from .envelopes import SCHEMA

_MODEL_URL = (
    "https://github.com/ga4gh-beacon/beacon-v2/blob/main/models/json/"
    "beacon-v2-default-model"
)

# entry type id -> (name, plural path part, description, ontology id, label)
ENTRY_TYPES: dict[str, dict] = {
    "analysis": {
        "name": "Bioinformatics analysis",
        "path": "analyses",
        "description": (
            "Apply analytical methods to existing data of a specific type."
        ),
        "ontology": ("edam:operation_2945", "Analysis"),
    },
    "biosample": {
        "name": "Biological Sample",
        "path": "biosamples",
        "description": (
            "Any material sample taken from a biological entity for testing, "
            "diagnostic, propagation, treatment or research purposes."
        ),
        "ontology": ("NCIT:C70699", "Biospecimen"),
    },
    "cohort": {
        "name": "Cohort",
        "path": "cohorts",
        "description": (
            "A group of individuals, identified by a common characteristic."
        ),
        "ontology": ("NCIT:C61512", "Cohort"),
        "collection_of": [{"id": "individual", "name": "Individuals"}],
    },
    "dataset": {
        "name": "Dataset",
        "path": "datasets",
        "description": (
            "A data collection with some shared context: provenance, "
            "granted access, or contained data types."
        ),
        "ontology": ("NCIT:C47824", "Data set"),
        "collection_of": [{"id": "genomicVariant", "name": "Genomic Variants"}],
    },
    "genomicVariant": {
        "name": "Genomic Variants",
        "path": "g_variants",
        "description": "The location of a sequence.",
        "ontology": ("ENSGLOSSARY:0000092", "Variant"),
    },
    "individual": {
        "name": "Individual",
        "path": "individuals",
        "description": "A human being.",
        "ontology": ("NCIT:C25190", "Person"),
    },
    "run": {
        "name": "Sequencing run",
        "path": "runs",
        "description": (
            "The valid and completed operation of a high-throughput "
            "sequencing instrument for a single sequencing process."
        ),
        "ontology": ("NCIT:C148088", "Sequencing run"),
    },
}

# per-entry-type sub-endpoints exposed under /{path}/{id}/... — mirrors the
# reference API Gateway resource tree (api-*.tf; SURVEY.md L1 path table)
_SUB_ENDPOINTS: dict[str, list[str]] = {
    "analysis": ["genomicVariant"],
    "biosample": ["analysis", "genomicVariant", "run"],
    "cohort": ["individual"],
    "dataset": ["biosample", "genomicVariant", "individual"],
    "genomicVariant": ["biosample", "individual"],
    "individual": ["biosample", "genomicVariant"],
    "run": ["analysis", "genomicVariant"],
}


def _default_schema(entry_id: str, base_uri: str | None = None) -> dict:
    """Entry-type default schema descriptor pointing at THIS beacon's
    served schema document (/schemas/{entityType} — api/model_schemas.py),
    so returned schema references resolve without reaching external model
    repositories."""
    from .model_schemas import schema_url

    info = ENTRY_TYPES[entry_id]
    return {
        "id": f"ga4gh-beacon-{entry_id.lower()}-v2.0.0",
        "name": f"Default schema for {info['name'].lower()}",
        "referenceToSchemaDefinition": schema_url(
            base_uri or "", entry_id
        ),
        "schemaVersion": "v2.0.0",
    }


def _entry_type_descriptor(entry_id: str, base_uri: str = "") -> dict:
    info = ENTRY_TYPES[entry_id]
    desc = {
        "additionallySupportedSchemas": [],
        "defaultSchema": _default_schema(entry_id, base_uri),
        "description": info["description"],
        "id": entry_id,
        "name": info["name"],
        "ontologyTermForThisType": {
            "id": info["ontology"][0],
            "label": info["ontology"][1],
        },
        "partOfSpecification": "Beacon v2.0.0",
    }
    if "collection_of" in info:
        desc["aCollectionOf"] = info["collection_of"]
    return desc


def _framework_meta(info: BeaconInfo) -> dict:
    return {
        "apiVersion": info.api_version,
        "beaconId": info.beacon_id,
        "returnedSchemas": [
            {"entityType": "info", "schema": "beacon-map-v2.0.0"}
        ],
    }


def info_response(info: BeaconInfo) -> dict:
    """GET / and /info (reference getInfo/lambda_function.py:20-57)."""
    now = datetime.now(timezone.utc).isoformat()
    return {
        "$schema": SCHEMA,
        "info": {},
        "meta": {
            **_framework_meta(info),
            "returnedSchemas": [
                {"entityType": "info", "schema": "beacon-info-v2.0.0"}
            ],
        },
        "response": {
            "alternativeUrl": info.alternative_url,
            "apiVersion": info.api_version,
            "createDateTime": now,
            "description": info.description,
            "environment": info.environment,
            "id": info.beacon_id,
            "info": {},
            "name": info.beacon_name,
            "organization": {
                "address": info.org_address,
                "contactUrl": info.org_contact_url,
                "description": info.org_description,
                "id": info.org_id,
                "info": {},
                "logoUrl": info.org_logo_url,
                "name": info.org_name,
                "welcomeUrl": info.org_welcome_url,
            },
            "updateDateTime": now,
            "version": info.version,
            "welcomeUrl": info.welcome_url,
        },
    }


def entry_types_response(info: BeaconInfo) -> dict:
    """GET /entry_types (reference getEntryTypes)."""
    return {
        "$schema": SCHEMA,
        "info": {},
        "meta": _framework_meta(info),
        "response": {
            "entryTypes": {
                eid: _entry_type_descriptor(eid, info.uri) for eid in ENTRY_TYPES
            }
        },
    }


def configuration_response(info: BeaconInfo) -> dict:
    """GET /configuration (reference getConfiguration)."""
    return {
        "$schema": SCHEMA,
        "info": {},
        "meta": _framework_meta(info),
        "response": {
            "$schema": SCHEMA,
            "entryTypes": {
                eid: _entry_type_descriptor(eid, info.uri) for eid in ENTRY_TYPES
            },
            "maturityAttributes": {"productionStatus": "DEV"},
            "securityAttributes": {
                "defaultGranularity": info.default_granularity,
                "securityLevels": ["PUBLIC"],
            },
        },
    }


def map_response(info: BeaconInfo) -> dict:
    """GET /map (reference getMap) — endpoint sets generated from the same
    table that drives the actual router, so the map cannot drift from the
    served routes."""
    base = info.uri.rstrip("/")
    endpoint_sets = {}
    for eid, einfo in ENTRY_TYPES.items():
        path = einfo["path"]
        endpoints = {
            sub: {
                "returnedEntryType": sub,
                "url": f"{base}/{path}/{{id}}/{ENTRY_TYPES[sub]['path']}",
            }
            for sub in _SUB_ENDPOINTS.get(eid, [])
        }
        endpoint_sets[eid] = {
            "endpoints": endpoints,
            "entryType": eid,
            "filteringTermsUrl": f"{base}/{path}/filtering_terms",
            "openAPIEndpointsDefinition": (
                f"{_MODEL_URL}/{path}/endpoints.json"
            ),
            "rootUrl": f"{base}/{path}",
            "singleEntryUrl": f"{base}/{path}/{{id}}",
        }
    return {
        "$schema": SCHEMA,
        "info": {},
        "meta": _framework_meta(info),
        "response": {"$schema": SCHEMA, "endpointSets": endpoint_sets},
    }
