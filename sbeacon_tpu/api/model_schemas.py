"""Per-entity default model schemas, served at ``/schemas/{entityType}``.

The reference vendors the GA4GH Beacon v2 default model as ~8.2k lines of
JSON under shared_resources/schemas/ and points entry-type descriptors at
the upstream model URLs (SURVEY.md §2.3 'schemas'). Here the same role is
filled by compact hand-authored JSON Schema documents describing exactly
the fields this framework stores and returns (metadata/entities.py +
api/envelopes.py), self-hosted so ``returnedSchemas`` and
``/map``/``/entry_types`` reference resolvable documents instead of
external URLs. Written against the published Beacon v2 model structure —
a GA4GH standard — not copied from the reference's vendored files.
"""

from __future__ import annotations

SCHEMA_VERSION = "v2.0.0"


def schema_id(entity: str) -> str:
    return f"beacon-{entity}-{SCHEMA_VERSION}"


_ONTOLOGY_TERM = {
    "type": "object",
    "description": "CURIE-identified ontology term",
    "properties": {
        "id": {
            "type": "string",
            "pattern": "^\\w[^:]*:.+$",
            "description": "CURIE, e.g. NCIT:C20197 or HP:0000001",
        },
        "label": {"type": "string"},
    },
    "required": ["id"],
}

_DEFS = {"ontologyTerm": _ONTOLOGY_TERM}
_TERM_REF = {"$ref": "#/$defs/ontologyTerm"}
_TERM_LIST = {"type": "array", "items": _TERM_REF}


def _doc(entity: str, title: str, description: str, properties: dict,
         required: list[str]) -> dict:
    return {
        "$schema": "https://json-schema.org/draft/2020-12/schema",
        "$id": schema_id(entity),
        "title": title,
        "description": description,
        "type": "object",
        "$defs": _DEFS,
        "properties": properties,
        "required": required,
        "additionalProperties": True,
    }


ENTITY_SCHEMAS: dict[str, dict] = {
    "dataset": _doc(
        "dataset",
        "Dataset",
        "A coherent collection of genomic data grouped for sharing "
        "(Beacon v2 datasets collection).",
        {
            "id": {"type": "string", "minLength": 1},
            "name": {"type": "string", "minLength": 1},
            "description": {"type": "string"},
            "createDateTime": {"type": "string", "format": "date-time"},
            "updateDateTime": {"type": "string", "format": "date-time"},
            "dataUseConditions": {
                "type": "object",
                "properties": {
                    "duoDataUse": {
                        "type": "array",
                        "items": {
                            "allOf": [
                                _TERM_REF,
                                {
                                    "properties": {
                                        "version": {"type": "string"},
                                        "modifiers": _TERM_LIST,
                                    }
                                },
                            ]
                        },
                    }
                },
            },
            "externalUrl": {"type": "string"},
            "info": {"type": "object"},
            "version": {"type": "string"},
        },
        ["id", "name"],
    ),
    "cohort": _doc(
        "cohort",
        "Cohort",
        "A group of individuals analysed together (Beacon v2 cohorts "
        "collection).",
        {
            "id": {"type": "string", "minLength": 1},
            "name": {"type": "string", "minLength": 1},
            "cohortType": {
                "type": "string",
                "enum": ["study-defined", "beacon-defined", "user-defined"],
            },
            "cohortDesign": _TERM_REF,
            "cohortSize": {"type": "integer"},
            "inclusionCriteria": {"type": "object"},
            "exclusionCriteria": {"type": "object"},
            "cohortDataTypes": _TERM_LIST,
        },
        ["id", "name"],
    ),
    "individual": _doc(
        "individual",
        "Individual",
        "A human subject carrying biosamples (Beacon v2 individuals "
        "entry type).",
        {
            "id": {"type": "string", "minLength": 1},
            "sex": _TERM_REF,
            "karyotypicSex": {
                "type": "string",
                "enum": [
                    "UNKNOWN_KARYOTYPE", "XX", "XY", "XO", "XXY", "XXX",
                    "XXYY", "XXXY", "XXXX", "XYY", "OTHER_KARYOTYPE",
                ],
            },
            "ethnicity": _TERM_REF,
            "geographicOrigin": _TERM_REF,
            "diseases": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "diseaseCode": _TERM_REF,
                        "ageOfOnset": {"type": "object"},
                        "familyHistory": {"type": "boolean"},
                        "severity": _TERM_REF,
                        "stage": _TERM_REF,
                    },
                    "required": ["diseaseCode"],
                },
            },
            "measures": {"type": "array", "items": {"type": "object"}},
            "phenotypicFeatures": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "featureType": _TERM_REF,
                        "excluded": {"type": "boolean"},
                    },
                    "required": ["featureType"],
                },
            },
            "interventionsOrProcedures": {
                "type": "array", "items": {"type": "object"},
            },
        },
        ["id", "sex"],
    ),
    "biosample": _doc(
        "biosample",
        "Biosample",
        "A biological sample from which genomic data derives (Beacon v2 "
        "biosamples entry type).",
        {
            "id": {"type": "string", "minLength": 1},
            "individualId": {"type": "string"},
            "biosampleStatus": _TERM_REF,
            "sampleOriginType": _TERM_REF,
            "sampleOriginDetail": _TERM_REF,
            "collectionDate": {"type": "string", "format": "date"},
            "collectionMoment": {"type": "string"},
            "obtentionProcedure": {"type": "object"},
            "tumorProgression": _TERM_REF,
            "tumorGrade": _TERM_REF,
            "pathologicalStage": _TERM_REF,
            "histologicalDiagnosis": _TERM_REF,
            "diagnosticMarkers": _TERM_LIST,
            "phenotypicFeatures": {
                "type": "array", "items": {"type": "object"},
            },
            "notes": {"type": "string"},
        },
        ["id", "biosampleStatus"],
    ),
    "run": _doc(
        "run",
        "Run",
        "One sequencing experiment on a biosample (Beacon v2 runs entry "
        "type).",
        {
            "id": {"type": "string", "minLength": 1},
            "biosampleId": {"type": "string"},
            "individualId": {"type": "string"},
            "runDate": {"type": "string", "format": "date"},
            "libraryLayout": {
                "type": "string", "enum": ["PAIRED", "SINGLE"],
            },
            "librarySelection": {"type": "string"},
            "librarySource": _TERM_REF,
            "libraryStrategy": {"type": "string"},
            "platform": {"type": "string"},
            "platformModel": _TERM_REF,
        },
        ["id", "biosampleId", "runDate"],
    ),
    "analysis": _doc(
        "analysis",
        "Analysis",
        "A bioinformatics analysis of a sequencing run (Beacon v2 "
        "analyses entry type).",
        {
            "id": {"type": "string", "minLength": 1},
            "runId": {"type": "string"},
            "biosampleId": {"type": "string"},
            "individualId": {"type": "string"},
            "analysisDate": {"type": "string", "format": "date"},
            "pipelineName": {"type": "string"},
            "pipelineRef": {"type": "string"},
            "aligner": {"type": "string"},
            "variantCaller": {"type": "string"},
            "vcfSampleId": {
                "type": "string",
                "description": "sample column this analysis maps to in "
                "the dataset's VCFs (drives the selected-samples search)",
            },
        },
        ["id", "analysisDate", "pipelineName"],
    ),
    "genomicVariant": _doc(
        "genomicVariant",
        "Genomic Variant",
        "A genomic variant entry as returned by /g_variants (Beacon v2 "
        "genomicVariations entry type, VRS-flavoured variation).",
        {
            "variantInternalId": {
                "type": "string",
                "description": "opaque stable id; decodable via "
                "/g_variants/{id}",
            },
            "variation": {
                "type": "object",
                "properties": {
                    "referenceBases": {"type": "string"},
                    "alternateBases": {"type": "string"},
                    "variantType": {"type": "string"},
                    "location": {
                        "type": "object",
                        "properties": {
                            "interval": {
                                "type": "object",
                                "properties": {
                                    "start": {
                                        "type": "object",
                                        "properties": {
                                            "type": {"type": "string"},
                                            "value": {"type": "integer"},
                                        },
                                    },
                                    "end": {
                                        "type": "object",
                                        "properties": {
                                            "type": {"type": "string"},
                                            "value": {"type": "integer"},
                                        },
                                    },
                                    "type": {"type": "string"},
                                },
                            },
                            "sequence_id": {"type": "string"},
                            "type": {"type": "string"},
                        },
                    },
                },
                "required": ["location"],
            },
            "caseLevelData": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "biosampleId": {"type": "string"},
                        "individualId": {"type": "string"},
                    },
                },
            },
            "frequencyInPopulations": {
                "type": "array", "items": {"type": "object"},
            },
        },
        ["variantInternalId", "variation"],
    ),
}

#: path-part -> entityType (the router's plural paths)
PATH_TO_ENTITY = {
    "datasets": "dataset",
    "cohorts": "cohort",
    "individuals": "individual",
    "biosamples": "biosample",
    "runs": "run",
    "analyses": "analysis",
    "g_variants": "genomicVariant",
}


def schema_url(base_uri: str, entity: str) -> str:
    return f"{base_uri.rstrip('/')}/schemas/{entity}"
