"""Beacon v2 response envelopes.

The three result envelopes (boolean / count / resultSets) plus the error
envelope and the VRS-style variant entry, matching the reference's
apiutils (reference: shared_resources/apiutils/responses.py:145-254
get_boolean_response/get_counts_response/get_result_sets_response,
api_response.py:13-46 bad_request, entries.py:1-24 get_variant_entry).
Envelope shape is the GA4GH Beacon v2 framework response model.
"""

from __future__ import annotations

from ..config import BeaconInfo

SCHEMA = "https://json-schema.org/draft/2020-12/schema"


class Envelopes:
    """Envelope factory bound to one beacon identity."""

    def __init__(self, info: BeaconInfo):
        self.info = info

    def _meta(
        self,
        *,
        granularity: str,
        req_granularity: str | None = None,
        pagination: dict | None = None,
        schemas: list | None = None,
    ) -> dict:
        return {
            "beaconId": self.info.beacon_id,
            "apiVersion": self.info.api_version,
            "returnedSchemas": (
                schemas
                if schemas is not None
                else [{"entityType": "info", "schema": "beacon-map-v2.0.0"}]
            ),
            "returnedGranularity": granularity,
            "receivedRequestSummary": {
                "apiVersion": self.info.api_version,
                "requestedSchemas": [],
                "pagination": pagination or {},
                "requestedGranularity": req_granularity or granularity,
            },
        }

    def boolean(self, *, exists: bool, info: dict | None = None) -> dict:
        return {
            "$schema": SCHEMA,
            "info": info or {},
            "meta": self._meta(granularity="boolean"),
            "responseSummary": {"exists": bool(exists)},
        }

    def count(
        self, *, exists: bool, count: int, info: dict | None = None
    ) -> dict:
        return {
            "$schema": SCHEMA,
            "info": info or {},
            "meta": self._meta(granularity="count"),
            "responseSummary": {
                "exists": bool(exists),
                "numTotalResults": int(count),
            },
        }

    def _entity_schemas(self, set_type: str) -> list | None:
        """returnedSchemas entries pointing at the served per-entity
        default model schema (api/model_schemas.py), so record responses
        reference resolvable documents."""
        from .model_schemas import (
            ENTITY_SCHEMAS,
            PATH_TO_ENTITY,
            schema_url,
        )

        # setType values mix singular/plural (app._SET_TYPE); the path
        # table is the single normalisation source
        entity = PATH_TO_ENTITY.get(set_type, set_type)
        if entity not in ENTITY_SCHEMAS:
            return None
        return [
            {
                "entityType": entity,
                "schema": schema_url(self.info.uri, entity),
            }
        ]

    def result_sets(
        self,
        *,
        results: list,
        set_type: str,
        exists: bool | None = None,
        total: int | None = None,
        skip: int = 0,
        limit: int = 100,
        info: dict | None = None,
    ) -> dict:
        if exists is None:
            exists = len(results) > 0
        if total is None:
            total = len(results)
        return {
            "$schema": SCHEMA,
            "info": info or {},
            "meta": self._meta(
                granularity="record",
                pagination={"skip": skip, "limit": limit},
                schemas=self._entity_schemas(set_type),
            ),
            "response": {
                "resultSets": [
                    {
                        "exists": len(results) > 0,
                        "id": "redacted",
                        "results": results,
                        "resultsCount": len(results),
                        "resultsHandovers": [],
                        "setType": set_type,
                    }
                ]
            },
            "responseSummary": {
                "exists": bool(exists),
                "numTotalResults": int(total),
            },
        }

    def by_granularity(
        self,
        granularity: str,
        *,
        exists: bool,
        count: int = 0,
        results: list | None = None,
        set_type: str = "",
        skip: int = 0,
        limit: int = 100,
    ) -> dict:
        """Dispatch on requestedGranularity the way every reference route
        does (boolean -> exists, count -> numTotalResults,
        record/aggregated -> resultSets)."""
        if granularity == "boolean":
            return self.boolean(exists=exists)
        if granularity == "count":
            return self.count(exists=exists, count=count)
        return self.result_sets(
            results=results or [],
            set_type=set_type,
            exists=exists,
            total=count,
            skip=skip,
            limit=limit,
        )

    def filtering_terms(
        self, terms: list[dict], *, skip: int = 0, limit: int = 100
    ) -> dict:
        return {
            "$schema": SCHEMA,
            "info": {},
            "meta": self._meta(
                granularity="record",
                pagination={"skip": skip, "limit": limit},
                schemas=[],
            ),
            "response": {"filteringTerms": terms},
        }

    def error(self, status: int, message: str) -> dict:
        return {
            "$schema": SCHEMA,
            "error": {"errorCode": status, "errorMessage": str(message)},
            "meta": {
                "apiVersion": self.info.api_version,
                "beaconId": self.info.beacon_id,
                "receivedRequestSummary": {},
                "returnedSchemas": [],
            },
        }


def variant_entry(
    internal_id: str,
    seq_id: str,
    ref: str,
    alt: str,
    start: int,
    end: int,
    typ: str | None,
) -> dict:
    """VRS-ish genomicVariant entry (reference entries.py:1-24)."""
    return {
        "variantInternalId": internal_id,
        "variation": {
            "referenceBases": ref,
            "alternateBases": alt,
            "location": {
                "interval": {
                    "start": {"type": "Number", "value": start},
                    "end": {"type": "Number", "value": end},
                    "type": "SequenceInterval",
                },
                "sequence_id": seq_id,
                "type": "SequenceLocation",
            },
            "variantType": typ,
        },
    }
