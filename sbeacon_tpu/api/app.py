"""The Beacon v2 application: one router over the full REST surface.

Replaces the reference's API Gateway resource tree + 13 route lambdas
(reference: api.tf + api-*.tf path parts; lambda/get*/lambda_function.py
dispatchers) with a single in-process route table:

    /  /info  /configuration  /map  /entry_types  /filtering_terms
    /submit                          (POST new, PATCH update)
    /{entity}                        x {datasets, cohorts, individuals,
    /{entity}/filtering_terms           biosamples, runs, analyses}
    /{entity}/{id}
    /{entity}/{id}/{sub}             (cross-entity + scoped g_variants)
    /g_variants  /g_variants/{id}  /g_variants/{id}/{biosamples,individuals}

Every handler returns ``(status_code, body_dict)``; transport (HTTP server,
tests, batch drivers) is external.
"""

from __future__ import annotations

import hmac
import json
import math
import time
from pathlib import Path

from ..accounting import (
    CostAccounting,
    cost_units,
    disabled_snapshot,
    query_shape,
)
from ..canary import CanaryProber
from ..config import BeaconConfig, StorageConfig
from ..engine import VariantEngine
from ..ingest import IngestService
from ..ingest.service import VcfLocationError
from ..harness import faults
from ..metadata import MetadataStore, OntologyStore
from ..metadata.filters import FilterError
from ..plan import (
    PlanStore,
    plan_document,
    plan_note,
    plan_stage,
    register_plan_metrics,
)
from ..query_jobs import AsyncQueryRunner, QueryJobTable
from ..resilience import (
    NO_DEADLINE,
    AdmissionController,
    Deadline,
    ResilienceError,
    deadline_scope,
    register_admission_metrics,
    register_breaker_metrics,
)
from ..shaping import TrafficShaper, requested_granularity
from ..slo import (
    DIAGNOSTIC_ROUTE_LABELS,
    PROBE_BYPASS_PATHS,
    PROBE_HEAD_LABELS,
    SloEngine,
)
from .. import telemetry as telemetry_mod
from ..telemetry import (
    MetricsRegistry,
    RequestContext,
    SlowQueryLog,
    annotate,
    current_context,
    journal,
    profiler,
    register_device_metrics,
    request_context,
    sanitize_trace_id,
)
from ..utils.trace import span, tracer
from .envelopes import Envelopes
from .framework import (
    configuration_response,
    entry_types_response,
    info_response,
    map_response,
)
from .requests import BeaconRequest, RequestError, parse_request
from .submit import submit_dataset
from .variants import (
    decode_internal_id,
    resolve_datasets,
    run_variant_search,
)

ENTITY_PATHS = {
    "datasets",
    "cohorts",
    "individuals",
    "biosamples",
    "runs",
    "analyses",
}

_SET_TYPE = {
    "datasets": "dataset",
    "cohorts": "cohort",
    "individuals": "individuals",
    "biosamples": "biosamples",
    "runs": "runs",
    "analyses": "analyses",
    "g_variants": "genomicVariant",
}

# {parent}/{id}/{child} metadata joins: child rows whose ``column`` = id
_CROSS_ENTITY: dict[tuple[str, str], tuple[str, str]] = {
    ("datasets", "individuals"): ("individuals", "_datasetid"),
    ("datasets", "biosamples"): ("biosamples", "_datasetid"),
    ("cohorts", "individuals"): ("individuals", "_cohortid"),
    ("individuals", "biosamples"): ("biosamples", "individualid"),
    ("biosamples", "analyses"): ("analyses", "biosampleid"),
    ("biosamples", "runs"): ("runs", "biosampleid"),
    ("runs", "analyses"): ("analyses", "runid"),
}


def strip_private(doc: dict) -> dict:
    """Drop '_'-prefixed internal fields (reference jsons.dump
    strip_privates=True on every record response)."""
    return {k: v for k, v in doc.items() if not k.startswith("_")}


def _wants_explain(query_params: dict | None) -> bool:
    """``?explain=1`` (or true/yes/on) — the inline plan request."""
    raw = str((query_params or {}).get("explain") or "").lower()
    return raw in ("1", "true", "yes", "on")


def _header(headers: dict | None, name: str) -> str | None:
    """Case-insensitive single-header lookup over a plain dict."""
    name = name.lower()
    for k, v in (headers or {}).items():
        if k.lower() == name:
            return v
    return None




def _authorization_header(headers: dict) -> str:
    return _header(headers, "authorization") or ""


def bearer_token_verifier(token: str):
    """Default auth hook: require ``Authorization: Bearer <token>``.

    Returns a verifier ``(method, path, headers) -> (authorized, reason)``.
    The reference gates ``/submit`` with an AWS_IAM authorizer (reference:
    api.tf:120-149); deployments needing real identity (OIDC, mTLS) pass
    their own callable as ``BeaconApp(auth_verifier=...)``.
    """

    def verify(method: str, path: str, headers: dict) -> tuple[bool, str]:
        got = _authorization_header(headers)
        # constant-time compare (== short-circuits on the first differing
        # byte, leaking token-prefix length via response timing); encoded
        # to bytes because compare_digest raises TypeError on non-ASCII
        # str, which would turn a malformed header into a 500
        if not hmac.compare_digest(
            got.encode(), f"Bearer {token}".encode()
        ):
            return False, "invalid token"
        return True, ""

    return verify


class BeaconApp:
    def __init__(
        self,
        config: BeaconConfig | None = None,
        *,
        store: MetadataStore | None = None,
        ontology: OntologyStore | None = None,
        engine: VariantEngine | None = None,
        ingest: IngestService | None = None,
        auth_verifier=None,
    ):
        if config is None:
            # configless (ad hoc / test) apps keep sqlite in memory and
            # write index shards under a throwaway temp root, removed when
            # the app is garbage-collected
            import tempfile

            config_given = False
            self._tmp_root = tempfile.TemporaryDirectory(prefix="beacon-")
            self.config = BeaconConfig(
                storage=StorageConfig(root=Path(self._tmp_root.name))
            )
        else:
            config_given = True
            self.config = config
        storage = self.config.storage
        if ontology is None:
            ontology = (
                OntologyStore(storage.ontology_db)
                if config_given
                else OntologyStore()
            )
        self.ontology = ontology
        if store is None:
            store = (
                MetadataStore(storage.metadata_db, ontology=self.ontology)
                if config_given
                else MetadataStore(ontology=self.ontology)
            )
        elif store.ontology is None:
            store.ontology = self.ontology
        self.store = store
        self.engine = engine or VariantEngine(self.config)
        # ingestion always targets an engine that can host shards: a
        # DistributedEngine coordinator exposes its local VariantEngine
        # as .local (shard ownership lives on hosts, not the coordinator)
        ingest_engine = getattr(self.engine, "local", None) or self.engine
        if ingest is None and not hasattr(ingest_engine, "add_index"):
            # fail at wiring time, not as an opaque 500 on first /submit
            raise ValueError(
                "engine cannot host index shards (no add_index): pass a "
                "DistributedEngine with local=VariantEngine(...), or an "
                "explicit ingest= service"
            )
        self.ingest = ingest or IngestService(
            self.config, engine=ingest_engine, store=self.store
        )
        self.env = Envelopes(self.config.info)
        # async query job table (VariantQueries/VariantQueryResponses roles):
        # coalesces concurrent identical queries, caches results for the
        # query TTL, spills oversized response sets to query_results_dir
        storage.ensure()
        self.query_jobs = QueryJobTable(
            storage.root / "query-jobs.sqlite",
            spill_dir=storage.query_results_dir,
            inline_limit=self.config.engine.max_response_inline_bytes,
        )
        self.query_runner = AsyncQueryRunner(self.engine, self.query_jobs)
        # resilience envelope (resilience.py): bounded in-flight
        # admission + request deadlines; /health, /ready and /metrics
        # bypass it so probes answer while the server is saturated
        res = self.config.resilience
        self.admission = AdmissionController(
            res.max_in_flight, retry_after_s=res.shed_retry_after_s
        )
        # traffic shaping (shaping.py): tenant-weighted fair queueing +
        # priority lanes in FRONT of the global gate (a queued request
        # holds no admission slot), with the brownout ladder fed by the
        # SLO engine's breach signal below. The hedge kill-switch is
        # process-wide, like the scan pools it governs.
        def _hedge_control(enabled: bool) -> None:
            from ..parallel.dispatch import set_hedging_enabled

            set_hedging_enabled(enabled)

        # cost accounting (accounting.py): every tracked request's
        # CostVector folds into the per-(tenant, lane, query-shape)
        # table served at /ops/costs; tenant cardinality reuses
        # shaping's cap. Built BEFORE the shaper so the cost-aware DRR
        # seam (BEACON_COST_DRR) can charge measured shape costs.
        obs_cfg = self.config.observability
        if getattr(obs_cfg, "cost_accounting", True):
            self.accounting = CostAccounting(
                window_s=getattr(obs_cfg, "cost_window_s", 300.0),
                max_tenants=self.config.shaping.max_tenants,
            )
        else:
            self.accounting = None
        self.shaping = TrafficShaper.from_config(
            self.config,
            hedge_control=_hedge_control,
            cost_charge_fn=(
                self.accounting.drr_charge
                if self.accounting is not None
                else None
            ),
        )
        # the background compactor runs off any request context: book
        # its fold cost under the 'system' tenant via the explicit hook
        compactor = getattr(self.ingest, "compactor", None)
        if compactor is not None and self.accounting is not None:
            compactor.accounting = self.accounting
        # readiness flag: constructed apps are servable; a deployment
        # may clear it during reload/drain so load balancers back off
        self.ready = True
        # telemetry plane (telemetry.py): one typed-metrics registry per
        # app — every producer registers its instruments here and
        # /metrics renders the registry (JSON or Prometheus text)
        # instead of hand-assembling nested dicts
        self.telemetry = MetricsRegistry()
        obs = self.config.observability
        self.slow_log = SlowQueryLog(
            threshold_ms=obs.slow_query_ms, path=obs.slow_query_log
        )
        # SLO engine (slo.py): per-route availability + latency
        # objectives evaluated as 5m/1h burn rates over every request
        # outcome; served at /slo and as slo.* gauges. The brownout
        # ladder subscribes to its breach signal: sustained burn steps
        # degradation up, sustained recovery steps it back down.
        self.slo = SloEngine.from_config(
            obs, max_tenants=self.config.shaping.max_tenants
        )
        self.slo.add_breach_listener(self.shaping.on_slo_signal)
        # execution-plan plane (plan.py): sampled per-request plan
        # documents aggregated by (query-shape, plan-shape) and served
        # at /ops/plans, with the drift sentinel's observation window
        # tied to the canary interval — the prober's round loop rolls
        # the window, so a dominant-shape flip (mesh quietly refusing
        # planes, L0 coverage collapsing to tail walks) is diagnosed
        # within one canary round even on a coordinator with no
        # organic traffic
        self.plans = PlanStore(
            sample_n=getattr(obs, "plan_sample_n", 16),
            drift_windows=getattr(obs, "plan_drift_windows", 2),
            window_s=getattr(obs, "canary_interval_s", 30.0),
        )
        # known-answer canary prober (canary.py): expected-answer
        # probes derived from the serving snapshot, run per query
        # shape x dispatch path under the synthetic 'canary' route —
        # budget- and cost-excluded like every probe. The thread waits
        # one full interval before its first round.
        self.canary = CanaryProber(
            self.engine,
            interval_s=getattr(obs, "canary_interval_s", 30.0),
            enabled=getattr(obs, "canary_enabled", True),
            latency_ms=getattr(obs, "canary_latency_ms", 1000.0),
            plan_store=self.plans,
        )
        self.canary.start()
        # flight recorder: the process journal was built from env
        # defaults at import; the config tier re-applies here (like
        # profiler.directory) so BEACON_EVENT_JOURNAL_* and explicit
        # ObservabilityConfig fields agree
        journal.configure(
            keep=getattr(obs, "event_journal_size", 1024),
            enabled=getattr(obs, "event_journal", True),
        )
        # device-plane flight recorder (ISSUE 14): same config-tier
        # re-application as the journal — the process global was built
        # from BEACON_DEVICE_RING_SIZE / BEACON_COMPILE_TRACKING env
        # defaults at import. Resolved through the module at call time
        # (never bound by value here), so a test or bench that swaps
        # telemetry.flight_recorder swaps this app's view too.
        telemetry_mod.flight_recorder.configure(
            ring_size=getattr(obs, "device_ring_size", 256),
            compile_tracking=getattr(obs, "compile_tracking", True),
        )
        if obs.profile_dir:
            # config-armed profiling (the env var SBEACON_PROFILE sets
            # the same field at import); first profiled region starts
            # the jax trace capture. The profiler is process-global
            # (jax supports one capture per process), so a second app
            # cannot redirect an already-armed capture — warn instead
            # of silently dropping the request.
            if not profiler.directory:
                profiler.directory = obs.profile_dir
            elif profiler.directory != obs.profile_dir:
                import logging

                logging.getLogger(__name__).warning(
                    "profiling already armed for %s; ignoring "
                    "profile_dir=%s (one capture per process)",
                    profiler.directory,
                    obs.profile_dir,
                )
        self._register_metrics()
        # mutating-route auth (reference /submit is AWS_IAM-gated,
        # api.tf:120-149): explicit verifier > config token > open (dev)
        if auth_verifier is not None:
            self.auth_verifier = auth_verifier
        elif self.config.auth.submit_token:
            self.auth_verifier = bearer_token_verifier(
                self.config.auth.submit_token
            )
        else:
            self.auth_verifier = None

    def close(self) -> None:
        """Release app-owned resources: the async runner's worker pool
        and the job table. The engine is NOT closed here — it may be
        caller-owned and shared (pass-in wiring); call engine.close()
        separately when this app owns it."""
        self.query_runner.close()
        self.query_jobs.close()
        self.canary.close()
        shaper_close = getattr(self.shaping, "close", None)
        if shaper_close is not None:
            shaper_close()
        ingest_close = getattr(self.ingest, "close", None)
        if ingest_close is not None:
            ingest_close()

    # -- telemetry wiring ---------------------------------------------------

    def _register_metrics(self) -> None:
        """Wire every producer's typed instruments into this app's
        registry. Suppliers read through ``self`` so components swapped
        at runtime (tests replace ``app.admission``) stay observable."""
        reg = self.telemetry
        # request-level series owned by the app itself; exemplars link
        # each latency bucket to the trace id of its latest request, so
        # a slow bucket resolves at /_trace?trace_id=...
        self._req_latency = reg.histogram(
            "request.latency_ms",
            "end-to-end request latency per route",
            label="route",
            exemplars=True,
            # the route label set is bounded by _route_label but its
            # legitimate cardinality (entity heads x sub-routes) tops
            # the registry's default 64-value guard — raise the cap
            # instead of collapsing real routes to "other"
            max_label_values=128,
        )
        reg.counter(
            "request.slow_queries",
            "requests recorded by the slow-query log",
            fn=lambda: self.slow_log.count(),
        )
        self.slo.register_metrics(reg)
        if self.accounting is not None:
            self.accounting.register_metrics(reg)
        else:
            # catalogue stability: the cost.* series exist (zeros) even
            # with accounting off, like every other optional plane
            CostAccounting().register_metrics(reg)
        reg.counter(
            "events.published",
            "control-plane events published to the flight recorder",
            fn=journal.published,
        )
        if "device.launches" not in reg.names():
            # device-plane flight recorder series (ISSUE 14): the
            # recorder is process-global, so the usual app fallback
            # registration keeps a second app from double-registering
            register_device_metrics(reg)
        self.canary.register_metrics(reg)
        register_plan_metrics(reg, self.plans)
        register_admission_metrics(reg, lambda: self.admission)
        self.shaping.register_metrics(reg)
        self.query_runner.register_metrics(reg)
        engine_reg = getattr(self.engine, "register_metrics", None)
        if engine_reg is not None:
            engine_reg(reg)
        if "breaker.state" not in reg.names():
            # single-host engines have no worker routes; the series
            # still exist (empty) so the catalogue is deployment-stable
            register_breaker_metrics(
                reg, lambda: getattr(self.engine, "breaker", None)
            )
        if "transport.conn.opened" not in reg.names():
            # same catalogue stability for the data-plane transport +
            # fan-out series: a single-host engine never opens worker
            # connections, but the instruments exist (zeros) so
            # dashboards don't flap with the deployment shape
            from ..parallel.dispatch import register_dispatch_metrics
            from ..parallel.transport import register_transport_metrics

            register_transport_metrics(reg)
            register_dispatch_metrics(
                reg,
                lambda: getattr(self.engine, "dispatch_stats", dict)(),
            )
        if "ingest.delta_publishes" not in reg.names():
            # local-less coordinators have no delta registry; zeros
            from ..engine import register_delta_metrics

            register_delta_metrics(
                reg,
                lambda: getattr(
                    getattr(self.engine, "local", None) or self.engine,
                    "delta_metrics",
                    dict,
                )(),
            )
        # compaction + slice-disk series (ingest-while-serving plane)
        from ..ingest.pipeline import register_ingest_metrics
        from ..ingest.service import register_compaction_metrics

        register_ingest_metrics(reg)
        register_compaction_metrics(
            reg,
            lambda: getattr(self.ingest, "compaction_metrics", dict)(),
        )

    #: heads of the two-segment diagnostic surfaces (``ops``,
    #: ``debug``, ``fleet``) — derived from the ONE probe-route source
    #: in slo.py (tools/check_probe_routes.py enforces the derivation)
    _DIAG_HEADS = frozenset(
        label.split(".", 1)[0] for label in DIAGNOSTIC_ROUTE_LABELS
    )

    #: bounded route-label set for the latency histogram — unknown
    #: paths collapse to "other" so a URL scanner cannot mint series.
    #: Probe heads derive from slo.PROBE_ROUTE_LABELS, the single
    #: literal source shared with the SLO budget exclusion and the
    #: auth/admission bypass set.
    _ROUTE_HEADS = (
        ENTITY_PATHS
        | {
            "info",
            "configuration",
            "map",
            "entry_types",
            "filtering_terms",
            "schemas",
            "submit",
            "g_variants",
        }
        | {
            label.split(".", 1)[0]
            for label in DIAGNOSTIC_ROUTE_LABELS
        }
        | PROBE_HEAD_LABELS
    )

    def _route_label(self, path: str) -> str:
        parts = [p for p in path.strip("/").split("/") if p]
        if not parts:
            return "info"
        head = parts[0]
        if head not in self._ROUTE_HEADS:
            return "other"
        if len(parts) == 1:
            return head
        if head in self._DIAG_HEADS:
            # diagnostic surfaces: only the KNOWN two-segment paths get
            # named labels — /ops/<anything-else> must collapse like
            # any other unknown path or a scanner mints series
            label = f"{head}.{parts[1]}"
            return (
                label if label in DIAGNOSTIC_ROUTE_LABELS else "other"
            )
        sub = parts[-1]
        if sub in ("filtering_terms", "g_variants", "biosamples",
                   "individuals", "runs", "analyses"):
            return f"{head}.{sub}"
        return f"{head}.id"

    # -- transport-facing entry --------------------------------------------

    def handle(
        self,
        method: str,
        path: str,
        query_params: dict | None = None,
        body: dict | None = None,
        headers: dict | None = None,
    ) -> tuple[int, dict]:
        """One request end to end, under a request context: a trace id
        minted here (or honored from an inbound ``X-Beacon-Trace``
        header) rides every hop — spans, pool hand-offs, worker HTTP
        calls — and returns in the response envelope's ``meta`` next to
        the elapsed time (the reference's VariantQuery start/end/
        elapsedTime columns, with propagated identity)."""
        t0 = time.perf_counter()
        route = self._route_label(path)
        ctx = RequestContext(
            trace_id=sanitize_trace_id(_header(headers, "x-beacon-trace")),
            route=route,
        )
        with request_context(ctx):
            status, payload = self._handle(
                method, path, query_params, body, headers
            )
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        # the exemplar is passed explicitly: this runs OUTSIDE the
        # request_context scope, so the ambient lookup would miss
        self._req_latency.observe(
            elapsed_ms, label_value=route, exemplar=ctx.trace_id
        )
        tenant = ctx.notes.get("tenant")
        self.slo.record(route, status, elapsed_ms, tenant=tenant)
        # cost accounting: fold this request's CostVector into the
        # (tenant, lane, shape) table. Probe/diagnostic routes are
        # excluded exactly like SLO budgets — a /metrics scrape is not
        # tenant work. Response bytes are measured here (the one place
        # the final payload exists); the serialization is the same one
        # the transport pays, bounded to tracked routes only.
        if self.accounting is not None and self.slo.tracked(route):
            cost = ctx.cost
            if isinstance(payload, dict):
                try:
                    cost.add(
                        response_bytes=len(
                            json.dumps(payload, default=str)
                        )
                    )
                except (TypeError, ValueError):
                    pass
            # seal BEFORE snapshotting: late charges (a launch
            # finishing after this request 504ed, a losing hedge leg's
            # RTT) redirect to the unattributed residue, and a charge
            # racing this very fold cannot fall between the snapshot
            # and the seal — it lands in exactly one of the two sides
            cost.seal()
            self.accounting.record(
                tenant or "anon",
                ctx.notes.get("lane") or "interactive",
                query_shape(route, ctx.notes.get("granularity")),
                cost.snapshot(),
            )
        # execution-plan fold: tracked requests' stage trails aggregate
        # by (query-shape, plan-shape) for /ops/plans and the drift
        # sentinel. Probe/diagnostic routes are excluded exactly like
        # SLO budgets and the cost fold — the canary folds its own
        # probes under bounded synthetic shapes instead.
        if self.slo.tracked(route):
            self.plans.observe(
                query_shape(route, ctx.notes.get("granularity")),
                ctx.plan,
                units=cost_units(ctx.cost.snapshot()),
                trace_id=ctx.trace_id,
            )
        notes = ctx.notes
        if ctx.cost.nonzero():
            # slow-query records carry the cost decomposition: a tail
            # is attributable to device time vs host scan vs worker
            # RTT without cross-referencing /ops/costs
            notes = {**notes, "cost": ctx.cost.as_dict()}
        if ctx.plan:
            # ... and the plan fingerprint + any refusal reasons: a
            # slow record says WHICH road the query took (and which it
            # was refused) without a second lookup
            notes = {**notes, "plan": plan_note(ctx)}
        self.slow_log.maybe_record(
            trace_id=ctx.trace_id,
            route=route,
            status=status,
            elapsed_ms=elapsed_ms,
            notes=notes,
        )
        if isinstance(payload, dict):
            meta = payload.get("meta")
            if isinstance(meta, dict):
                meta["traceId"] = ctx.trace_id
                meta["elapsedTimeMs"] = round(elapsed_ms, 2)
                if ctx.explain:
                    # ?explain=1 (gated in _handle): the full bounded
                    # plan document rides the envelope — never cached,
                    # since explain forces no_response_cache
                    meta["executionPlan"] = plan_document(ctx)
                unavailable = ctx.notes.get("unavailable_datasets")
                if unavailable:
                    # partial-results degradation (dispatch.search):
                    # every replica of these datasets was unreachable,
                    # so the response covers the datasets that
                    # answered — say so instead of 502ing the request
                    meta["unavailableDatasets"] = list(unavailable)
                    meta.setdefault("warnings", []).append(
                        "no reachable replica for dataset(s): "
                        + ", ".join(unavailable)
                        + "; results are partial"
                    )
        return status, payload

    def _handle(
        self, method, path, query_params, body, headers
    ) -> tuple[int, dict]:
        try:
            with span("api.handle", path=path, method=method):
                head = path.strip("/")
                if (
                    method.upper() == "GET"
                    and head in PROBE_BYPASS_PATHS
                ):
                    # probes/metrics AND the self-diagnosis surfaces
                    # bypass auth, admission and deadlines: a flight
                    # recorder that stops answering exactly when the
                    # server is saturated or shedding is useless —
                    # answering then is their whole job. The path set
                    # derives from slo.PROBE_ROUTE_LABELS — the SAME
                    # source that excludes these routes from SLO
                    # budgets and the cost fold below.
                    return self._probe(head, query_params, headers)
                denied = self._check_auth(method.upper(), path, headers)
                if denied is not None:
                    return denied
                if _wants_explain(query_params):
                    denied = self._check_explain(headers)
                    if denied is not None:
                        return denied
                    ctx = current_context()
                    if ctx is not None:
                        # armed only after the gate: an unauthorized
                        # ?explain=1 never records, never bypasses the
                        # response cache, never changes the answer
                        ctx.explain = True
                deadline = self._request_deadline(head, headers)
                # traffic shaping: classify tenant (header/API key/anon
                # bucket) and priority lane (interactive boolean-count
                # vs bulk record retrieval), then admit through the
                # weighted fair queue BEFORE the global gate — a queued
                # request holds no admission slot, and the deadline
                # scope wraps the queue wait so it stays bounded
                tenant = self.shaping.tenant_of(headers)
                lane = self.shaping.lane_of(head, query_params, body)
                granularity = requested_granularity(query_params, body)
                annotate(tenant=tenant, lane=lane)
                plan_stage("admission", decision=lane, tenant=tenant)
                if granularity:
                    annotate(granularity=granularity)
                # the query-shape key (route x granularity): the same
                # key the accounting fold uses, so the cost-aware DRR
                # (BEACON_COST_DRR) charges admission with the measured
                # cost of exactly this shape
                ctx = current_context()
                shape = query_shape(
                    ctx.route if ctx is not None else head, granularity
                )
                with deadline_scope(deadline), self.shaping.admit(
                    tenant, lane, shape
                ), self.admission.admit():
                    return self._route(
                        method.upper(), path, query_params, body
                    )
        except ResilienceError as e:
            # 429 shed / 503 batch-timeout & circuit-open / 504 deadline
            payload = self.env.error(e.status, str(e))
            if e.retry_after_s is not None:
                # integer seconds, rounded up: the RFC 9110 Retry-After
                # header only carries whole seconds, and the envelope
                # field must say the SAME thing the header does (the
                # transport derives the header from this field) — a
                # sub-second adaptive value still advises >= 1 s
                payload["retryAfterSeconds"] = max(
                    1, math.ceil(e.retry_after_s)
                )
            return e.status, payload
        except TimeoutError as e:
            return 504, self.env.error(504, str(e))
        except (RequestError, FilterError, VcfLocationError) as e:
            return 400, self.env.error(400, str(e))
        except Exception as e:  # pragma: no cover - defensive 500
            return 500, self.env.error(500, f"{type(e).__name__}: {e}")

    def _request_deadline(self, head: str, headers: dict | None) -> Deadline:
        """The request's deadline: ``X-Beacon-Deadline`` header
        (seconds) when sent, else the config default — except for
        ``/submit``, where bulk ingest is a batch job and only an
        explicit header bounds it."""
        raw = _header(headers, "x-beacon-deadline")
        if raw is not None:
            try:
                seconds = float(raw)
                # NaN slips through every <=0 guard (all comparisons
                # false) and would poison downstream clamps with a
                # deadline that is never expired yet has 0 remaining;
                # inf and <=0 are equally meaningless as bounds — and
                # <=0 must NOT silently disable the operator's default
                # (Deadline.after semantics), so all three reject
                if not math.isfinite(seconds) or seconds <= 0:
                    raise ValueError(raw)
                return Deadline.after(seconds)
            except (TypeError, ValueError):
                raise RequestError(
                    f"invalid X-Beacon-Deadline header: {raw!r}"
                    " (want a finite number of seconds > 0)"
                ) from None
        if head == "submit":
            return NO_DEADLINE
        return Deadline.after(self.config.resilience.default_deadline_s)

    def _probe(
        self,
        head: str,
        query_params: dict | None = None,
        headers: dict | None = None,
    ) -> tuple[int, dict]:
        info = self.config.info
        if head == "health":
            # liveness: cheap, no store/engine access
            return 200, {"ok": True, "beaconId": info.beacon_id}
        if head == "ready":
            # readiness: local state only — never a worker round-trip
            # (a probe that can hang is worse than no probe)
            local = getattr(self.engine, "local", None) or self.engine
            body = {
                "ready": bool(self.ready),
                "beaconId": info.beacon_id,
                "shards": len(getattr(local, "_indexes", {})),
                "inFlight": self.admission.metrics()["in_flight"],
            }
            # degraded datasets (every replica's circuit open) are
            # reported but do NOT flip readiness: the server still
            # serves everything else, with partial-results envelopes
            # naming the rest — pulling it from rotation would turn a
            # partial outage into a total one
            degraded = getattr(self.engine, "unavailable_datasets", None)
            if degraded is not None:
                body["degradedDatasets"] = degraded()
            return (200 if self.ready else 503), body
        if head == "slo":
            # per-route objectives + multi-window burn rates (the JSON
            # twin of the slo.* Prometheus gauges); ?tenant=<id> scopes
            # the same document to one tenant's isolated burn rings
            want_tenant = (query_params or {}).get("tenant")
            return 200, self.slo.snapshot(tenant=want_tenant or None)
        if head == "ops/events":
            return self._ops_events(query_params)
        if head == "ops/costs":
            # the tenant accounting plane's rollup: top tenants by
            # cost unit, per-shape mean/p99, attribution ratio
            if self.accounting is None:
                return 200, disabled_snapshot()
            return 200, self.accounting.snapshot()
        if head == "ops/plans":
            # the execution-plan plane's rollup: per (query-shape,
            # plan-shape) counts, cost-unit means, exemplar trace ids
            # (resolvable through /_trace when tracing is on), and the
            # drift sentinel's recent dominant-shape flips
            return 200, self.plans.snapshot()
        if head == "fleet/status":
            # fleet-wide federation rollup: every worker's /ops/digest
            # collected at a bounded cadence + the coordinator's own
            # digest, with a fleet-level diagnosis (stalest replica,
            # hottest worker, divergent fingerprints)
            return 200, self._fleet_status()
        if head == "fleet/migrations":
            # live shard-migration history + in-flight phases: a
            # diagnostic read (the POST trigger is /fleet/migrate,
            # behind the worker-token gate)
            ctl = getattr(self.engine, "migrations", None)
            return 200, {
                "migrations": ctl.status() if ctl is not None else [],
                "counters": (
                    ctl.counters() if ctl is not None else {}
                ),
                "stuck": ctl.stuck() if ctl is not None else None,
            }
        if head == "debug/status":
            return 200, self._debug_status()
        if head == "device/status":
            return 200, self._device_status()
        # /metrics: content negotiation — ?format=openmetrics or an
        # ``Accept: application/openmetrics-text`` (what a modern
        # Prometheus scrape sends first) gets the OpenMetrics dialect
        # WITH exemplar annotations; ?format=prometheus or plain
        # ``Accept: text/plain`` gets the classic text format, whose
        # parsers reject exemplar syntax; everything else the
        # back-compat nested JSON (which always carries the
        # ``exemplars`` maps)
        fmt = (query_params or {}).get("format", "")
        accept = _header(headers, "accept") or ""
        if fmt == "openmetrics" or "application/openmetrics-text" in accept:
            return 200, self.telemetry.render_prometheus(openmetrics=True)
        if fmt == "prometheus" or "text/plain" in accept:
            return 200, self.telemetry.render_prometheus()
        return 200, self._metrics()

    def _ops_events(self, query_params: dict | None) -> tuple[int, dict]:
        """The flight recorder, filtered: ``?since=<seq>`` returns only
        newer events — the OLDEST ``limit`` of them, with a
        ``nextSince`` cursor to pass back as ``since``, so a tailing
        client pages forward through a burst without re-reading or
        silently skipping the middle (ISSUE 12 satellite; previously
        the newest ``limit`` were served and a tailer had to guess the
        resume point). ``?kind=breaker`` filters by kind prefix
        (comma-separated list accepted)."""
        qp = query_params or {}
        try:
            since = int(qp.get("since") or 0)
            limit = int(qp.get("limit") or 256)
        except (TypeError, ValueError):
            return 400, self.env.error(
                400, "since/limit must be integers"
            )
        events, next_since = journal.events_page(
            since=since, kind=str(qp.get("kind") or ""), limit=limit
        )
        return 200, {
            "events": events,
            "nextSince": next_since,
            "lastSeq": journal.last_seq(),
            "published": journal.published(),
            "enabled": journal.enabled,
        }

    def _digest_extras(self) -> dict:
        """The coordinator's app-tier digest fields (the worker digest
        carries engine fields only): SLO breaches, slow-query count,
        top cost tenants, canary rollup."""
        canary = self.canary.counters()
        extras = {
            "sloBreached": self.slo.breached_routes(),
            "slowQueries": self.slow_log.count(),
            "canary": {
                "mismatches": canary["mismatches"],
                "failures": canary["failures"],
            },
        }
        if self.accounting is not None:
            extras["topCostTenants"] = self.accounting.snapshot(
                top_n=3
            )["topTenants"]
        else:
            extras["topCostTenants"] = []
        return extras

    def _fleet_status(self) -> dict:
        """The ``/fleet/status`` document: the FleetView's per-worker
        digest rollup + diagnosis (fan-out engines), always including
        the coordinator's own digest as ``local`` — a single-host
        deployment serves the same schema with an empty worker map."""
        from ..parallel.dispatch import ops_digest

        local_engine = getattr(self.engine, "local", None) or self.engine
        local = ops_digest(local_engine, extras=self._digest_extras())
        fleet = getattr(self.engine, "fleet", None)
        if fleet is None:
            doc = {
                "intervalS": getattr(
                    self.config.observability,
                    "fleet_digest_interval_s",
                    10.0,
                ),
                "polls": 0,
                "lastPollAgeS": None,
                "workers": {},
                "diagnosis": {
                    "stalestReplica": None,
                    "hottestWorker": None,
                    "divergentDatasets": {},
                    "unreachableWorkers": [],
                    "worstCompilingReplica": None,
                },
            }
        else:
            doc = fleet.snapshot()
        doc["local"] = local
        return doc

    def _fleet_migrate(self, body: dict) -> tuple[int, dict]:
        """``POST /fleet/migrate``: launch a live shard migration
        (copy -> dual-serve -> canary-verify -> cut-over) on the
        fan-out engine's controller. 202: the protocol runs on a
        background thread — poll ``GET /fleet/migrations`` for phase
        progress; 409: the request was rejected up front (dataset
        already migrating, migrations disabled, bad endpoints)."""
        from ..parallel.migration import MigrationError

        ctl = getattr(self.engine, "migrations", None)
        if ctl is None:
            return 400, self.env.error(
                400,
                "this deployment has no migration controller "
                "(single-host engine — nothing to migrate between)",
            )
        dataset = str(body.get("dataset") or "")
        source = str(body.get("source") or "")
        target = str(body.get("target") or "")
        if not dataset or not source or not target:
            return 400, self.env.error(
                400, "fleet/migrate needs dataset, source and target"
            )
        try:
            m = ctl.start(dataset, source, target)
        except MigrationError as e:
            return 409, self.env.error(409, str(e))
        return 202, {
            "migrationId": m.id,
            "dataset": m.dataset,
            "source": m.source,
            "target": m.target,
            "phase": m.phase,
        }

    def _debug_status(self) -> dict:
        """The self-diagnosis rollup: SLO state, breaker states,
        replica-table staleness, queue depths, and the queue-wait
        decomposition composed into one document whose ``diagnosis``
        names the stage and worker eating the latency budget. Local
        state only — safe to serve while saturated."""
        engine = self.engine
        local = getattr(engine, "local", None) or engine
        breaker = getattr(engine, "breaker", None)
        breakers = breaker.metrics() if breaker is not None else {}
        routing: dict = {}
        router = getattr(engine, "router", None)
        if router is not None:
            age = engine.route_table_age_s()
            routing = {
                "datasets": len(router.table()),
                "replicas": router.replica_count(),
                "tableAgeS": None if age is None else round(age, 1),
                "unavailableDatasets": engine.unavailable_datasets(),
                "workers": engine.worker_stats(),
            }
            # which dispatch tier serves pod-local dataset groups (and
            # how often it has fallen back to the scatter)
            tier = getattr(engine, "mesh_tier", None)
            if tier is not None:
                routing["meshTier"] = tier.stats()
        batcher = getattr(local, "_batcher", None)
        occ = batcher.occupancy() if batcher is not None else {}
        queues = {
            "admission": self.admission.metrics(),
            "shaping": self.shaping.debug(),
            "runner": self.query_runner.metrics(),
            "batcher": {
                k: occ[k] for k in ("launcher", "fetcher") if k in occ
            },
        }
        # stage decomposition: runner admission wait first, then the
        # batcher/engine stages (batch wait -> encode -> launch ->
        # device -> fetch -> materialize)
        stages: dict = {
            "admission_wait_ms": self.query_runner.queue_wait_summary()
        }
        st = getattr(local, "stage_timing", None)
        if st is not None:
            stages.update(st())
        # ingest-while-serving rollup: per-dataset delta-tail depth
        # (rows queryable but not yet folded) + compactor counters —
        # "how stale is the base, and is the fold keeping up" in one
        # glance
        ingest: dict = {}
        delta_stats = getattr(local, "delta_stats", None)
        if delta_stats is not None:
            ingest["deltaTails"] = delta_stats()
        l0_status = getattr(local, "l0_status", None)
        if l0_status is not None:
            # the L0 delta-tail mini-index (ISSUE 15): built/served
            # state next to the tails it covers
            ingest["l0"] = l0_status()
        compactor = getattr(self.ingest, "compactor", None)
        if compactor is not None:
            ingest["compactor"] = compactor.metrics()
        slo = self.slo.snapshot()
        breached = sorted(
            r for r, doc in slo["routes"].items() if doc["breached"]
        )
        stage_p99 = {
            name: q.get("p99", 0.0)
            for name, q in stages.items()
            if isinstance(q, dict) and q
        }
        slowest_stage = (
            max(stage_p99, key=stage_p99.get)
            if any(stage_p99.values())
            else None
        )
        workers = routing.get("workers") or {}
        rtts = {
            u: w["medianRttMs"]
            for u, w in workers.items()
            if w.get("medianRttMs") is not None
        }
        # cost-accounting rollup + the two attribution diagnoses: an
        # operator staring at a breached SLO sees WHO is burning the
        # budget in the same document that names the breach
        costs = (
            self.accounting.debug()
            if self.accounting is not None
            else {"enabled": False}
        )
        # canary rollup (ISSUE 12): the known-answer prober's state —
        # a mismatch here means the data plane is SILENTLY WRONG, the
        # one failure mode no latency or availability signal shows
        canary = self.canary.status()
        # device-plane rollup (ISSUE 14): launch decomposition +
        # padding waste + the mid-request compile count, so the
        # diagnosis can name a device-side regression (a novel batch
        # shape paying its XLA compile inside a request, or a family
        # whose padding wastes most of its launches) next to the
        # breached SLOs it explains
        recorder = telemetry_mod.flight_recorder
        device = {
            "launches": recorder.launch_summary(),
            "padWaste": recorder.pad_waste_by_family(),
            "midRequestCompiles": recorder.mid_request_compiles(),
        }
        last_compile = recorder.last_mid_request_compile()
        # execution-plan rollup: observation/sample counters + the
        # drift sentinel's recent dominant-shape flips, with the
        # diagnosis naming the drifted query shapes next to the
        # breaches and canary mismatches they often explain
        plans = self.plans.counters()
        return {
            "ready": bool(self.ready),
            "beaconId": self.config.info.beacon_id,
            "slo": slo,
            "breakers": breakers,
            "routing": routing,
            "queues": queues,
            "ingest": ingest,
            "stages": stages,
            "costs": costs,
            "canary": canary,
            "device": device,
            "plans": plans,
            "events": {
                "lastSeq": journal.last_seq(),
                "published": journal.published(),
            },
            "diagnosis": {
                "breachedSlos": breached,
                "openBreakers": sorted(
                    u
                    for u, d in breakers.items()
                    if d.get("state") != "closed"
                ),
                "slowestStage": slowest_stage,
                "slowestWorker": (
                    max(rtts, key=rtts.get) if rtts else None
                ),
                "costliestTenant": costs.get("costliestTenant"),
                "costliestShape": costs.get("costliestShape"),
                "canaryMismatches": list(canary.get("mismatched", [])),
                "worstPadWaste": recorder.worst_pad_waste(),
                "midRequestCompiles": device["midRequestCompiles"],
                "lastMidRequestCompile": (
                    last_compile["key"] if last_compile else None
                ),
                "planDrift": self.plans.drifted_shapes(),
            },
        }

    def _device_status(self) -> dict:
        """The device-plane flight recorder's read surface (ISSUE 14):
        the launch ring summary (padding waste by family/tier,
        evaluated pairs, per-launch records), the compile cache vs the
        warmup shape set, the HBM plane ledger, and the fused/mesh
        stack states. Every piece is a lock-free snapshot (the
        recorder's own short lock, try-lock on the engine ledger) —
        this surface must answer DURING an in-flight stack rebuild,
        the same discipline as ``/ops/digest``."""
        engine = self.engine
        local = getattr(engine, "local", None) or engine
        doc = telemetry_mod.flight_recorder.snapshot()
        ledger = getattr(local, "plane_ledger", None)
        doc["hbm"] = (
            ledger()
            if callable(ledger)
            else {
                "residentBytes": 0,
                "reservedBytes": 0,
                "reservedTokens": 0,
                "budgetBytes": 0,
                "headroomBytes": 0,
                "stale": False,
            }
        )
        stacks: dict = {}
        fused = getattr(local, "fused_stack_status", None)
        if callable(fused):
            stacks["fused"] = fused()
        tier = getattr(engine, "mesh_tier", None)
        if tier is not None:
            stacks["meshTier"] = tier.stats()
        doc["stacks"] = stacks
        doc["time"] = time.time()
        return doc

    def _metrics(self) -> dict:
        """Serving observability: the typed-instrument registry rendered
        as nested JSON (``admission``, ``runner``, ``batcher``,
        ``response_cache``, ``engine``, ``request`` under their stable
        keys), plus the two surfaces kept in their historical non-dotted
        shapes — per-worker breaker states and the armed fault plan."""
        out = self.telemetry.render_json()
        breaker = getattr(self.engine, "breaker", None)
        if breaker is not None:
            out["breaker"] = breaker.metrics()
        injector = faults.installed()
        if injector is not None:
            out["faults"] = injector.stats()
        return out

    def _check_explain(self, headers) -> tuple[int, dict] | None:
        """404/401/403 envelope for an unauthorized ``?explain=1``,
        else None (explain may proceed).

        The plan document names internal topology — worker URLs, mesh
        shard counts, HBM headroom — so it rides the WORKER-token trust
        boundary exactly like ``/fleet/migrate``: disabled entirely
        unless ``BEACON_EXPLAIN_ENABLED`` (a 404, indistinguishable
        from the feature not existing), then no credential -> 401,
        wrong credential -> 403. Empty worker token = open (dev mode /
        private network), matching the worker endpoints themselves."""
        if not getattr(
            self.config.observability, "explain_enabled", False
        ):
            return 404, self.env.error(
                404, "explain disabled (set BEACON_EXPLAIN_ENABLED)"
            )
        token = self.config.auth.worker_token
        if not token:
            return None
        got = _authorization_header(headers or {})
        if not got:
            return 401, self.env.error(
                401, "missing Authorization header"
            )
        if not hmac.compare_digest(
            got.encode(), f"Bearer {token}".encode()
        ):
            return 403, self.env.error(
                403, "explain requires the worker token"
            )
        return None

    def _check_auth(self, method, path, headers) -> tuple[int, dict] | None:
        """401/403 envelope for unauthorized mutating requests, else None.

        Only mutating routes (``/submit`` POST/PATCH) are gated — read
        routes stay public, matching the reference API where only the
        submit resource carries the AWS_IAM authorizer. Standard HTTP
        semantics decide the status structurally: no credential presented
        (no Authorization header) -> 401; credential presented but
        rejected by the verifier -> 403.

        ``POST /fleet/migrate`` is the exception: it rides the
        WORKER-token trust boundary (``BEACON_WORKER_TOKEN``), not the
        submit authorizer — triggering a migration drives ``/migrate/*``
        artifact reads and drops across the fleet, so it carries the
        same secret and the same blast radius as direct worker access.
        Empty worker token = open (dev mode / private network), matching
        the worker endpoints themselves."""
        if (
            path.strip("/") == "fleet/migrate"
            and method == "POST"
        ):
            token = self.config.auth.worker_token
            if not token:
                return None
            got = _authorization_header(headers or {})
            if not got:
                return 401, self.env.error(
                    401, "missing Authorization header"
                )
            if not hmac.compare_digest(
                got.encode(), f"Bearer {token}".encode()
            ):
                return 403, self.env.error(
                    403, "fleet/migrate requires the worker token"
                )
            return None
        if self.auth_verifier is None:
            return None
        if path.strip("/") != "submit" or method not in ("POST", "PATCH"):
            return None
        ok, reason = self.auth_verifier(method, path, headers or {})
        if ok:
            return None
        if not _authorization_header(headers or {}):
            return 401, self.env.error(401, "missing Authorization header")
        return 403, self.env.error(403, reason or "forbidden")

    # -- routing ------------------------------------------------------------

    def _route(self, method, path, query_params, body):
        parts = [p for p in path.strip("/").split("/") if p]
        info = self.config.info

        if not parts or parts == ["info"]:
            return 200, info_response(info)
        head = parts[0]
        # NOTE: /health, /ready and /metrics are served in handle()
        # BEFORE auth/admission/deadline — probes must answer while the
        # server sheds; they never reach this router
        if head == "schemas":
            # served per-entity default model schemas (the reference
            # vendors these as shared_resources/schemas/ JSON documents;
            # here /map, /entry_types and returnedSchemas point at THIS
            # beacon's resolvable copies — api/model_schemas.py)
            from .model_schemas import ENTITY_SCHEMAS, schema_url

            if len(parts) == 1:
                return 200, {
                    "entityTypes": sorted(ENTITY_SCHEMAS),
                    "schemas": {
                        e: schema_url(info.uri, e)
                        for e in sorted(ENTITY_SCHEMAS)
                    },
                }
            if len(parts) == 2 and parts[1] in ENTITY_SCHEMAS:
                return 200, ENTITY_SCHEMAS[parts[1]]
            return 404, self.env.error(
                404, f"unknown schema /{'/'.join(parts[1:])}"
            )
        if len(parts) == 1:
            if head == "_trace":
                # debug-only profiling surface; 404s unless tracing is on
                if not tracer.is_enabled:
                    return 404, self.env.error(404, "tracing disabled")
                # recent span trees (structured, trace ids attached) +
                # the aggregate report + the slow-query ring; ?trace_id=
                # filters the trees to one distributed request
                want = (query_params or {}).get("trace_id")
                return 200, {
                    "report": tracer.report(),
                    "traces": tracer.recent_trees(trace_id=want),
                    "slowQueries": self.slow_log.recent(),
                }
            if head == "configuration":
                return 200, configuration_response(info)
            if head == "map":
                return 200, map_response(info)
            if head == "entry_types":
                return 200, entry_types_response(info)
            if head == "filtering_terms":
                req = parse_request(method, query_params, body)
                terms = self.store.filtering_terms(
                    skip=req.skip, limit=req.limit
                )
                return 200, self.env.filtering_terms(
                    terms, skip=req.skip, limit=req.limit
                )
            if head == "submit":
                if method not in ("POST", "PATCH"):
                    return 400, self.env.error(
                        400, "submit accepts POST (new) or PATCH (update)"
                    )
                summary = submit_dataset(
                    self, body or {}, update=(method == "PATCH")
                )
                return 200, summary

        if parts == ["fleet", "migrate"]:
            # the migrate trigger (worker-token gated in _check_auth);
            # /fleet/status and /fleet/migrations are probe reads and
            # never reach this router
            if method != "POST":
                return 405, self.env.error(
                    405, "fleet/migrate accepts POST"
                )
            return self._fleet_migrate(body or {})

        req = parse_request(method, query_params, body)

        if head == "g_variants":
            return self._route_g_variants(parts, req)
        if head in ENTITY_PATHS:
            return self._route_entity(parts, req)
        return 404, self.env.error(404, f"unknown path /{'/'.join(parts)}")

    # -- entity routes -------------------------------------------------------

    def _route_entity(self, parts: list[str], req: BeaconRequest):
        kind = parts[0]
        if len(parts) == 1:
            return self._entity_collection(kind, req)
        if len(parts) == 2:
            if parts[1] == "filtering_terms":
                terms = self.store.filtering_terms(
                    skip=req.skip, limit=req.limit, kinds=[kind]
                )
                return 200, self.env.filtering_terms(
                    terms, skip=req.skip, limit=req.limit
                )
            return self._entity_by_id(kind, parts[1], req)
        if len(parts) == 3:
            entity_id, sub = parts[1], parts[2]
            if sub == "filtering_terms" and kind in ("datasets", "cohorts"):
                terms = self.store.filtering_terms_for_entity(
                    kind, entity_id, skip=req.skip, limit=req.limit
                )
                return 200, self.env.filtering_terms(
                    terms, skip=req.skip, limit=req.limit
                )
            if sub == "g_variants" and kind != "cohorts":
                # cohorts expose no g_variants endpoint (reference api
                # tree: cohort endpoints are {id}/individuals only)
                return self._scoped_g_variants(kind, entity_id, req)
            join = _CROSS_ENTITY.get((kind, sub))
            if join is not None:
                child_kind, column = join
                return self._entity_collection(
                    child_kind,
                    req,
                    extra_where=f"{column} = ?",
                    extra_params=[entity_id],
                )
        return 404, self.env.error(404, f"unknown path /{'/'.join(parts)}")

    def _entity_collection(
        self,
        kind: str,
        req: BeaconRequest,
        *,
        extra_where: str | None = None,
        extra_params: list | None = None,
    ):
        """Granularity switch over the store (reference route_individuals.py
        :86-111 get_bool/count/record_query trio)."""
        if req.granularity == "boolean":
            # streaming existence check — at 1M individuals this is the
            # difference between ~0 ms and a full COUNT scan
            found = self.store.exists(
                kind,
                req.filters,
                extra_where=extra_where,
                extra_params=extra_params,
            )
            return 200, self.env.boolean(exists=found)
        count = self.store.count(
            kind,
            req.filters,
            extra_where=extra_where,
            extra_params=extra_params,
        )
        if req.granularity == "count":
            return 200, self.env.count(exists=count > 0, count=count)
        docs = self.store.fetch(
            kind,
            req.filters,
            skip=req.skip,
            limit=req.limit,
            extra_where=extra_where,
            extra_params=extra_params,
        )
        return 200, self.env.result_sets(
            results=[strip_private(d) for d in docs],
            set_type=_SET_TYPE[kind],
            exists=count > 0,
            total=count,
            skip=req.skip,
            limit=req.limit,
        )

    def _entity_by_id(self, kind: str, entity_id: str, req: BeaconRequest):
        doc = self.store.get_by_id(kind, entity_id)
        results = [strip_private(doc)] if doc else []
        if req.granularity == "boolean":
            return 200, self.env.boolean(exists=bool(doc))
        if req.granularity == "count":
            return 200, self.env.count(exists=bool(doc), count=len(results))
        return 200, self.env.result_sets(
            results=results,
            set_type=_SET_TYPE[kind],
            exists=bool(doc),
            total=len(results),
        )

    # -- variant routes ------------------------------------------------------

    def _route_g_variants(self, parts: list[str], req: BeaconRequest):
        if len(parts) == 1:
            return self._g_variants_collection(req)
        variant_id = parts[1]
        if len(parts) == 2:
            return self._g_variants_by_id(variant_id, req)
        if len(parts) == 3 and parts[2] in ("biosamples", "individuals"):
            return self._g_variants_id_entities(variant_id, parts[2], req)
        return 404, self.env.error(404, f"unknown path /{'/'.join(parts)}")

    def _g_variants_collection(self, req: BeaconRequest):
        """POST/GET /g_variants (reference route_g_variants.py:49-208)."""
        start_min, start_max, end_min, end_max = req.coordinates()
        datasets, samples = resolve_datasets(
            self.store, self.ontology, req.assembly_id, req.filters
        )
        agg = run_variant_search(
            self.engine,
            datasets,
            req,
            start_min=start_min,
            start_max=start_max,
            end_min=end_min,
            end_max=end_max,
            samples_by_dataset=samples,
            runner=self.query_runner,
        )
        return 200, self.env.by_granularity(
            req.granularity,
            exists=agg.exists,
            count=len(agg.variants),
            results=agg.results[req.skip : req.skip + req.limit],
            set_type="genomicVariant",
            skip=req.skip,
            limit=req.limit,
        )

    def _g_variants_by_id(self, variant_id: str, req: BeaconRequest):
        """/g_variants/{id}: decode the internal id back into a point query
        (reference route_g_variants_id.py:71-77); resultsets always ALL."""
        assembly, chrom, pos0, ref, alt = decode_internal_id(variant_id)
        req.assembly_id = assembly
        datasets, samples = resolve_datasets(
            self.store, self.ontology, assembly, req.filters
        )
        agg = run_variant_search(
            self.engine,
            datasets,
            req,
            start_min=pos0 + 1,
            start_max=pos0 + 1,
            end_min=pos0 + 1,
            end_max=pos0 + len(alt) + 1,
            reference_name=chrom,
            reference_bases=ref,
            alternate_bases=alt,
            samples_by_dataset=samples,
            include_resultset_responses="ALL",
            runner=self.query_runner,
        )
        return 200, self.env.by_granularity(
            req.granularity,
            exists=agg.exists,
            count=len(agg.variants),
            results=agg.results,
            set_type="genomicVariant",
        )

    def _g_variants_id_entities(
        self, variant_id: str, sub: str, req: BeaconRequest
    ):
        """/g_variants/{id}/{biosamples,individuals}: find the samples
        carrying the variant, then join to the entity table (reference
        route_g_variants_id_individuals.py get_record_query)."""
        assembly, chrom, pos0, ref, alt = decode_internal_id(variant_id)
        req.assembly_id = assembly
        datasets, _ = resolve_datasets(
            self.store, self.ontology, assembly, req.filters
        )
        # force record granularity internally so sample hits materialise
        inner = BeaconRequest(
            method=req.method,
            granularity="record",
            filters=req.filters,
            assembly_id=assembly,
        )
        agg = run_variant_search(
            self.engine,
            datasets,
            inner,
            start_min=pos0 + 1,
            start_max=pos0 + 1,
            end_min=pos0 + 1,
            end_max=pos0 + len(alt) + 1,
            reference_name=chrom,
            reference_bases=ref,
            alternate_bases=alt,
            include_resultset_responses="ALL",
            runner=self.query_runner,
        )
        docs: list[dict] = []
        for ds_id, names in sorted(agg.sample_names_by_dataset.items()):
            docs.extend(
                self.store.entities_for_samples(
                    sub, ds_id, names, skip=0, limit=1_000_000_000
                )
            )
        count = len(docs)
        return 200, self.env.by_granularity(
            req.granularity,
            exists=count > 0,
            count=count,
            results=[
                strip_private(d)
                for d in docs[req.skip : req.skip + req.limit]
            ],
            set_type=_SET_TYPE[sub],
            skip=req.skip,
            limit=req.limit,
        )

    def _scoped_g_variants(self, kind: str, entity_id: str, req: BeaconRequest):
        """/{entity}/{id}/g_variants — the entity-restricted variant search
        (reference route_individuals_id_g_variants.py etc.): datasets come
        from the entity's analyses join and the search runs in
        selected-samples mode; /datasets/{id}/g_variants restricts by
        dataset id only."""
        start_min, start_max, end_min, end_max = req.coordinates()
        if kind == "datasets":
            datasets, samples = resolve_datasets(
                self.store,
                self.ontology,
                req.assembly_id,
                req.filters,
                dataset_ids=[entity_id],
            )
        else:
            samples = {
                "individuals": self.store.sample_names_for_individual,
                "biosamples": self.store.sample_names_for_biosample,
                "runs": self.store.sample_names_for_run,
                "analyses": self.store.sample_names_for_analysis,
            }[kind](entity_id)
            if not samples:
                return 200, self.env.by_granularity(
                    req.granularity, exists=False, count=0, results=[]
                )
            datasets, _ = resolve_datasets(
                self.store,
                self.ontology,
                req.assembly_id,
                req.filters,
                dataset_ids=sorted(samples),
            )
            datasets = [d for d in datasets if samples.get(d["id"])]
        agg = run_variant_search(
            self.engine,
            datasets,
            req,
            start_min=start_min,
            start_max=start_max,
            end_min=end_min,
            end_max=end_max,
            samples_by_dataset=samples,
            runner=self.query_runner,
        )
        return 200, self.env.by_granularity(
            req.granularity,
            exists=agg.exists,
            count=len(agg.variants),
            results=agg.results[req.skip : req.skip + req.limit],
            set_type="genomicVariant",
            skip=req.skip,
            limit=req.limit,
        )
