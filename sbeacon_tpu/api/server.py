"""Threaded stdlib HTTP transport for BeaconApp.

The reference's API Gateway + AWS_PROXY integration layer (reference:
api.tf REST resources, stage 'prod') reduced to one ThreadingHTTPServer:
URL + query string + JSON body in, JSON out, CORS header kept
(reference apiutils/api_response.py HEADERS).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .app import BeaconApp


def _make_handler(app: BeaconApp):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet by default
            pass

        def _respond(self):
            parsed = urlparse(self.path)
            # flatten single-valued query params (API-GW style)
            query = {
                k: (v[0] if len(v) == 1 else ",".join(v))
                for k, v in parse_qs(parsed.query).items()
            }
            body = None
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                raw = self.rfile.read(length)
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError:
                    self._send(400, {"error": "invalid JSON body"})
                    return
            status, payload = app.handle(
                self.command, parsed.path, query, body
            )
            self._send(status, payload)

        def _send(self, status: int, payload: dict):
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_OPTIONS(self):  # CORS preflight
            self.send_response(204)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Access-Control-Allow-Methods", "GET, POST, PATCH, OPTIONS"
            )
            self.send_header("Access-Control-Allow-Headers", "Content-Type")
            self.send_header("Content-Length", "0")
            self.end_headers()

        do_GET = _respond
        do_POST = _respond
        do_PATCH = _respond

    return Handler


def make_server(app: BeaconApp, host: str = "127.0.0.1", port: int = 0):
    """ThreadingHTTPServer bound to (host, port); port 0 picks a free one."""
    return ThreadingHTTPServer((host, port), _make_handler(app))


def serve(app: BeaconApp, host: str = "0.0.0.0", port: int = 5000):
    """Blocking serve-forever (the deployment entry)."""
    server = make_server(app, host, port)
    try:
        server.serve_forever()
    finally:
        server.server_close()


def start_background(app: BeaconApp, host: str = "127.0.0.1", port: int = 0):
    """(server, thread) with the server running on a daemon thread —
    used by tests and the benchmark harness."""
    server = make_server(app, host, port)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, t
