"""Threaded stdlib HTTP transport for BeaconApp.

The reference's API Gateway + AWS_PROXY integration layer (reference:
api.tf REST resources, stage 'prod') reduced to one ThreadingHTTPServer:
URL + query string + JSON body in, JSON out, CORS header kept
(reference apiutils/api_response.py HEADERS).
"""

from __future__ import annotations

import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .app import BeaconApp


def _make_handler(app: BeaconApp):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet by default
            pass

        def _respond(self):
            parsed = urlparse(self.path)
            # flatten single-valued query params (API-GW style)
            query = {
                k: (v[0] if len(v) == 1 else ",".join(v))
                for k, v in parse_qs(parsed.query).items()
            }
            body = None
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                raw = self.rfile.read(length)
                try:
                    body = json.loads(raw)
                except json.JSONDecodeError:
                    self._send(400, {"error": "invalid JSON body"})
                    return
            status, payload = app.handle(
                self.command, parsed.path, query, body,
                headers=dict(self.headers.items()),
            )
            self._send(status, payload)

        def _send(self, status: int, payload):
            if isinstance(payload, str):
                # text payloads (Prometheus exposition from /metrics)
                # go out verbatim as text/plain
                data = payload.encode()
                content_type = "text/plain; version=0.0.4"
            else:
                data = json.dumps(payload).encode()
                content_type = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Access-Control-Allow-Origin", "*")
            retry_after = (
                payload.get("retryAfterSeconds")
                if isinstance(payload, dict) and status in (429, 503)
                else None
            )
            if retry_after is not None:
                # standard client-backoff hint: the SAME value as the
                # envelope's retryAfterSeconds — the app layer already
                # normalized it to RFC 9110 integral seconds (rounded
                # up), so the ceil here is a no-op guard for payloads
                # minted outside BeaconApp.handle
                self.send_header(
                    "Retry-After", str(max(1, math.ceil(retry_after)))
                )
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_OPTIONS(self):  # CORS preflight
            self.send_response(204)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Access-Control-Allow-Methods", "GET, POST, PATCH, OPTIONS"
            )
            self.send_header(
                "Access-Control-Allow-Headers",
                # the client-settable request headers DEPLOYMENT.md
                # documents: auth, per-request deadline, trace id
                "Content-Type, Authorization, X-Beacon-Deadline, "
                "X-Beacon-Trace",
            )
            self.send_header("Content-Length", "0")
            self.end_headers()

        do_GET = _respond
        do_POST = _respond
        do_PATCH = _respond

    return Handler


class _BeaconServer(ThreadingHTTPServer):
    # socketserver's default listen backlog is 5: a 16-client connect
    # burst overflows it, the kernel drops the SYN, and the client's
    # SYN retransmit fires after exactly 1 s — measured as ~1050 ms
    # p99 outliers with the entire serving path warm (r5 soak tail
    # decomposition: in-process p99 was 1.4x p50, HTTP p99 was 17x).
    request_queue_size = 128


def make_server(app: BeaconApp, host: str = "127.0.0.1", port: int = 0):
    """ThreadingHTTPServer bound to (host, port); port 0 picks a free one."""
    return _BeaconServer((host, port), _make_handler(app))


def serve(app: BeaconApp, host: str = "0.0.0.0", port: int = 5000):
    """Blocking serve-forever (the deployment entry)."""
    server = make_server(app, host, port)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        # app-owned pools/tables die with the deployment entry (the
        # runner's worker threads are non-daemon; leaving them alive
        # stalls interpreter exit on the atexit join)
        app.close()


def start_background(app: BeaconApp, host: str = "127.0.0.1", port: int = 0):
    """(server, thread) with the server running on a daemon thread —
    used by tests and the benchmark harness."""
    server = make_server(app, host, port)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, t


def main(argv: list[str] | None = None) -> None:
    """``python -m sbeacon_tpu.api.server`` — the deployment entry the
    reference expresses as terraform apply (api.tf + lambda env blocks):
    one process serving the full Beacon v2 surface over a disk-backed
    store, optionally fronting remote worker hosts (--worker)."""
    import argparse

    from ..config import BeaconConfig

    p = argparse.ArgumentParser(description="TPU-native Beacon v2 server")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=5000)
    p.add_argument(
        "--data-root",
        default=None,
        help="storage root (default: BeaconConfig/./beacon_data)",
    )
    p.add_argument(
        "--worker",
        action="append",
        default=[],
        metavar="URL",
        help="remote worker base URL (repeatable); queries fan out across "
        "workers + local shards",
    )
    args = p.parse_args(argv)

    config = BeaconConfig.from_env(args.data_root)
    from ..config import enable_persistent_compile_cache
    from ..harness.faults import install_from_env

    enable_persistent_compile_cache(config.storage.root)
    # chaos runs against a real server: BEACON_FAULT_PLAN arms seeded
    # fault injection (harness/faults.py); unset = no-op
    install_from_env()
    engine = None
    if args.worker:
        from ..engine import VariantEngine
        from ..parallel.dispatch import DistributedEngine

        # the local VariantEngine hosts this machine's shards; BeaconApp
        # wires ingestion to it (engine.local) while queries fan out
        # through the coordinator
        engine = DistributedEngine(
            args.worker, local=VariantEngine(config), config=config
        )
    app = BeaconApp(config, engine=engine)
    n = app.ingest.load_all()
    # pre-compile every dispatchable kernel program so no request pays
    # a first-compile (the soak-tail cause, VERDICT r4 #10/next #7)
    warm = getattr(app.engine, "warmup", None)
    n_warm = warm() if warm else 0
    print(
        f"beacon serving on {args.host}:{args.port} "
        f"({n} index shards loaded, {len(args.worker)} workers, "
        f"{n_warm} kernel programs warmed)"
    )
    serve(app, host=args.host, port=args.port)


if __name__ == "__main__":  # pragma: no cover
    main()
