"""Beacon v2 request parsing + validation.

One parser for the GET/POST duality every reference route re-implements
(reference: each route's paired ``if event['httpMethod'] == 'GET'/'POST'``
blocks, e.g. getGenomicVariants/route_g_variants.py:50-116): GET flattens
query parameters (comma-joined filters/start/end), POST nests them under
``meta`` / ``query.requestParameters`` / ``query.pagination``.

Also owns the Beacon start/end coordinate interpretation — the 1- vs
2-element bracket forms and the 0->1-based ``+1`` dance (reference:
shared_resources/variantutils/search_variants.py:48-68).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jsonschema


class RequestError(ValueError):
    """400-worthy request problem; message is user-facing."""


# POST body schema — the requestBody.json / gVariantsRequestParameters.json
# role (reference: shared_resources/schemas/, enforced per-route at e.g.
# getGenomicVariants/lambda_function.py:13-15,27-37), authored compactly:
# structure + enums + the allele patterns, with unknown extras tolerated
# the way the reference's additionalProperties:true does.
_ALLELE_PATTERN = r"^([ACGTUNRYSWKMBDHV\-\.acgtunryswkmbdhv]*)$"

QUERY_BODY_SCHEMA = {
    "type": "object",
    "properties": {
        "meta": {"type": "object"},
        "query": {
            "type": "object",
            "properties": {
                "requestedGranularity": {
                    "enum": ["boolean", "count", "record", "aggregated"]
                },
                "includeResultsetResponses": {
                    "enum": ["ALL", "HIT", "MISS", "NONE"]
                },
                "pagination": {
                    "type": "object",
                    "properties": {
                        "skip": {"type": "integer", "minimum": 0},
                        "limit": {"type": "integer", "minimum": 0},
                    },
                },
                "filters": {
                    "type": "array",
                    "items": {
                        "anyOf": [
                            {"type": "string"},
                            {
                                "type": "object",
                                "required": ["id"],
                                "properties": {
                                    "id": {"type": "string"},
                                    "scope": {"type": "string"},
                                    "includeDescendantTerms": {
                                        "type": "boolean"
                                    },
                                    "similarity": {
                                        "enum": [
                                            "exact",
                                            "high",
                                            "medium",
                                            "low",
                                        ]
                                    },
                                },
                            },
                        ]
                    },
                },
                "requestParameters": {
                    "type": "object",
                    "properties": {
                        "assemblyId": {"type": "string"},
                        "referenceName": {"type": "string"},
                        "referenceBases": {
                            "type": "string",
                            "pattern": _ALLELE_PATTERN,
                        },
                        "alternateBases": {
                            "type": "string",
                            "pattern": _ALLELE_PATTERN,
                        },
                        "variantType": {"type": "string"},
                        "start": {
                            "type": "array",
                            "items": {"type": "integer", "minimum": 0},
                            "maxItems": 2,
                        },
                        "end": {
                            "type": "array",
                            "items": {"type": "integer", "minimum": 0},
                            "maxItems": 2,
                        },
                        "variantMinLength": {
                            "type": "integer",
                            "minimum": 0,
                        },
                        "variantMaxLength": {
                            "type": "integer",
                            "minimum": 0,
                        },
                    },
                },
            },
        },
    },
}

_QUERY_VALIDATOR = jsonschema.Draft7Validator(QUERY_BODY_SCHEMA)


def validate_query_body(body: dict) -> None:
    """Schema-check a POST body before parsing (reference: jsonschema
    validate at the top of every POST route)."""
    errors = sorted(
        _QUERY_VALIDATOR.iter_errors(body), key=lambda e: list(e.path)
    )
    if errors:
        where = "/".join(str(p) for p in errors[0].path) or "body"
        raise RequestError(f"invalid request at {where}: {errors[0].message}")


def _int(value, name: str, default: int | None = None) -> int:
    if value is None or value == "":
        if default is None:
            raise RequestError(f"{name} must be specified")
        return default
    try:
        return int(value)
    except (TypeError, ValueError):
        raise RequestError(f"{name} must be an integer") from None


def _int_list(value, name: str) -> list[int]:
    if value is None:
        return []
    if isinstance(value, str):
        parts = [p for p in value.split(",") if p != ""]
    elif isinstance(value, (list, tuple)):
        parts = list(value)
    else:
        parts = [value]
    try:
        return [int(p) for p in parts]
    except (TypeError, ValueError):
        raise RequestError(f"{name} must be a list of integers") from None


def _upper(value):
    """Allele case normalisation: the index hashes record alleles
    uppercased, so queries must be uppercased too or lowercase input
    (legal per the allele alphabet) silently never matches."""
    return value.upper() if isinstance(value, str) else value


def _parse_filters(raw) -> list[dict]:
    """GET form 'A,B' -> [{'id': 'A'}, {'id': 'B'}]; POST form passes
    through the filter dicts."""
    if raw is None:
        return []
    if isinstance(raw, str):
        return [{"id": fid} for fid in raw.split(",") if fid]
    if isinstance(raw, list):
        out = []
        for f in raw:
            if isinstance(f, str):
                out.append({"id": f})
            elif isinstance(f, dict):
                if "id" not in f:
                    raise RequestError("filter missing 'id'")
                out.append(f)
            else:
                raise RequestError("filters must be strings or objects")
        return out
    raise RequestError("filters must be a list or comma-joined string")


@dataclass
class BeaconRequest:
    """Normalised request: both HTTP methods collapse into this."""

    method: str = "GET"
    granularity: str = "boolean"
    skip: int = 0
    limit: int = 100
    filters: list[dict] = field(default_factory=list)
    include_resultset_responses: str = "NONE"
    # g_variants request parameters
    start: list[int] = field(default_factory=list)
    end: list[int] = field(default_factory=list)
    assembly_id: str | None = None
    reference_name: str | None = None
    reference_bases: str | None = None
    alternate_bases: str | None = None
    variant_type: str | None = None
    variant_min_length: int = 0
    variant_max_length: int = -1

    def coordinates(self) -> tuple[int, int, int, int]:
        """(start_min, start_max, end_min, end_max), 1-based inclusive.

        The exact bracket interpretation + the '+1' conversion of
        reference search_variants.py:48-68: a 2-element start/end is a
        bracket range; 1-element start with 1-element end is a
        start-anchored range whose end list bounds the variant end.
        """
        start, end = self.start, self.end
        if not start:
            raise RequestError("start must be specified")
        if len(start) > 2 or len(end) > 2:
            raise RequestError("start and end accept at most 2 values")
        if len(start) == 2:
            start_min, start_max = start
        else:
            start_min = start[0]
        if len(end) == 2:
            end_min, end_max = end
        elif len(end) == 1:
            end_min = start_min
            end_max = end[0]
        else:
            raise RequestError("end must be specified")
        if len(start) != 2:
            start_max = end_max
        return start_min + 1, start_max + 1, end_min + 1, end_max + 1


def parse_request(
    method: str,
    query_params: dict | None,
    body: dict | None,
) -> BeaconRequest:
    req = BeaconRequest(method=method.upper())
    if req.method == "POST":
        params = body or {}
        validate_query_body(params)
        query = params.get("query") or {}
        pagination = query.get("pagination") or {}
        rp = query.get("requestParameters") or {}
        req.granularity = query.get("requestedGranularity", "boolean")
        req.skip = _int(pagination.get("skip"), "skip", 0)
        req.limit = _int(pagination.get("limit"), "limit", 100)
        req.filters = _parse_filters(query.get("filters"))
        req.include_resultset_responses = query.get(
            "includeResultsetResponses", "NONE"
        )
        req.start = _int_list(rp.get("start"), "start")
        req.end = _int_list(rp.get("end"), "end")
        req.assembly_id = rp.get("assemblyId")
        req.reference_name = rp.get("referenceName")
        req.reference_bases = _upper(rp.get("referenceBases"))
        req.alternate_bases = _upper(rp.get("alternateBases"))
        req.variant_type = _upper(rp.get("variantType"))
        req.variant_min_length = _int(
            rp.get("variantMinLength"), "variantMinLength", 0
        )
        req.variant_max_length = _int(
            rp.get("variantMaxLength"), "variantMaxLength", -1
        )
    else:
        params = query_params or {}
        req.granularity = params.get("requestedGranularity", "boolean")
        req.skip = _int(params.get("skip"), "skip", 0)
        req.limit = _int(params.get("limit"), "limit", 100)
        req.filters = _parse_filters(params.get("filters"))
        req.include_resultset_responses = params.get(
            "includeResultsetResponses", "NONE"
        )
        req.start = _int_list(params.get("start"), "start")
        req.end = _int_list(params.get("end"), "end")
        req.assembly_id = params.get("assemblyId")
        req.reference_name = params.get("referenceName")
        req.reference_bases = _upper(params.get("referenceBases"))
        req.alternate_bases = _upper(params.get("alternateBases"))
        req.variant_type = _upper(params.get("variantType"))
        req.variant_min_length = _int(
            params.get("variantMinLength"), "variantMinLength", 0
        )
        req.variant_max_length = _int(
            params.get("variantMaxLength"), "variantMaxLength", -1
        )
    if req.granularity not in ("boolean", "count", "record", "aggregated"):
        raise RequestError(
            f"unknown requestedGranularity {req.granularity!r}"
        )
    if req.skip < 0 or req.limit < 0:
        raise RequestError("skip and limit must be non-negative")
    return req
