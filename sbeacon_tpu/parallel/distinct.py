"""Device-sharded distinct-variant count: duplicateVariantSearch on mesh.

The reference counts distinct variants by fanning bp-ranges (≤750 MB
each) to 8 GB lambdas that insert ``pos + ref_alt`` strings into an
``unordered_set`` (reference: duplicateVariantSearch.cpp:31-84;
range packing initDuplicateVariantSearch.py:171-191). SURVEY.md §2.5
maps this to device-sharded dedupe: **sort-unique per shard + cross-
shard reduction via collectives**, which is what this module does:

1. host: concatenate all shards' fixed-width keys
   (chrom_code, pos, ref_hash, alt_hash, ref_len, alt_len) and partition
   them into ``n_shards`` disjoint HASH buckets — the reference's
   range-packing role; identical keys hash identically so no duplicate
   pair can cross shards (rows sharing only (code, pos) MAY split —
   sort-unique compares all six columns, so that is harmless);
2. device (shard_map over the mesh): lexsort the local key block, count
   rows that differ from their predecessor (sort-unique), mask padding;
3. ``psum`` over the mesh axis replaces the DynamoDB
   ``VariantDuplicates`` atomic-DELETE barrier entirely — the total is
   on every device when the one compiled program returns.

Keys are hash-exact (fnv1a32 of each allele + lengths + position): a
false merge needs two alleles at the same position with equal lengths
and a double FNV collision. The host path
(``ingest.pipeline.distinct_variant_count``) byte-verifies duplicate
groups and serves as the oracle; tests assert equality.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..index.columnar import VariantIndexShard
from ..utils.trace import span
from .mesh import AXIS, make_mesh

#: sentinel key rows sort last and are excluded from the count
_PAD = np.iinfo(np.int32).max


def shard_keys(shards: list[VariantIndexShard]) -> np.ndarray:
    """[n, 6] int32 key matrix over all rows of all shards (the same key
    the host exact counter groups by)."""
    parts = []
    for s in shards:
        n = s.n_rows
        codes = (
            np.searchsorted(s.chrom_offsets, np.arange(n), side="right") - 1
        ).astype(np.int32)
        # everything int32 (the device default): the 32-bit FNV hashes
        # ride as bit patterns — any total order groups equal keys, which
        # is all sort-unique needs
        parts.append(
            np.stack(
                [
                    codes,
                    s.cols["pos"].astype(np.int32),
                    s.cols["ref_hash"].astype(np.uint32).view(np.int32),
                    s.cols["alt_hash"].astype(np.uint32).view(np.int32),
                    s.cols["ref_len"].astype(np.int32),
                    s.cols["alt_len"].astype(np.int32),
                ],
                axis=1,
            )
        )
    if not parts:
        return np.zeros((0, 6), np.int32)
    return np.concatenate(parts)


def partition_keys(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Partition into n_shards disjoint blocks such that EQUAL keys
    always land in the same block (so no duplicate pair can straddle a
    psum shard) — the range-packing role, memory-bounded like
    ABS_MAX_DATA_SPLIT.

    Partitioning is by key-hash bucket, not by sorted (code, pos)
    ranges: identical rows hash identically, which is the whole
    invariant sort-unique needs, and it drops the host-side full
    lexsort that dominated the 8M-key device count (the only remaining
    host passes are a counting sort over small bucket ids)."""
    n = len(keys)
    if n == 0 or n_shards <= 1:
        order = np.arange(n)
        counts = np.array([n], dtype=np.int64)
        n_shards = max(n_shards, 1)
    else:
        # cheap row mix; equal rows (all 6 columns equal) collide by
        # construction. Row hashes spread uniformly for real corpora.
        mix = (
            keys[:, 0].astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
            ^ keys[:, 1].astype(np.uint64) * np.uint64(0xC2B2AE3D27D4EB4F)
            ^ keys[:, 2].astype(np.uint64) * np.uint64(0x165667B19E3779F9)
            ^ keys[:, 3].astype(np.uint64) * np.uint64(0x27D4EB2F165667C5)
            ^ keys[:, 4].astype(np.uint64) * np.uint64(0x85EBCA6B)
            ^ keys[:, 5].astype(np.uint64) * np.uint64(0xC2B2AE35)
        )
        # uint16 bucket ids: numpy dispatches RADIX sort for <=16-bit
        # ints (int64 would silently fall back to O(n log n) timsort —
        # ~11x slower at 1M ids, defeating the point of this rewrite)
        bucket = ((mix >> np.uint64(33)) % np.uint64(n_shards)).astype(
            np.uint16
        )
        order = np.argsort(bucket, kind="stable")
        counts = np.bincount(bucket, minlength=n_shards)
    width = int(counts.max()) if len(counts) else 0
    # pad width to a power-of-two bucket so repeated counts of similar
    # corpora reuse one compiled program instead of retracing per size
    pad_w = 256
    while pad_w < width:
        pad_w *= 2
    out = np.full((n_shards, pad_w, 6), _PAD, dtype=np.int32)
    start = 0
    for k in range(n_shards):
        c = int(counts[k]) if k < len(counts) else 0
        out[k, :c] = keys[order[start : start + c]]
        start += c
    return out


def _local_distinct(block):
    """Per-device body: lexsort-unique count of one [1, width, 6] block,
    psum over the mesh axis."""
    blk = block[0]  # [width, 6]
    order = jnp.lexsort(
        (blk[:, 5], blk[:, 4], blk[:, 3], blk[:, 2], blk[:, 1], blk[:, 0])
    )
    srt = blk[order]
    real = srt[:, 0] != _PAD
    diff = jnp.any(srt[1:] != srt[:-1], axis=1)
    first = jnp.concatenate([jnp.array([True]), diff])
    local = jnp.sum(first & real)
    return jax.lax.psum(local, AXIS)


@lru_cache(maxsize=8)
def _compiled_for(mesh: Mesh):
    """One jitted shard_map program per mesh — rebuilding the closure per
    call would defeat the jit cache and recompile every time."""
    return jax.jit(
        jax.shard_map(
            _local_distinct,
            mesh=mesh,
            in_specs=P(AXIS),
            out_specs=P(),
        )
    )


def distinct_count_device(
    shards: list[VariantIndexShard],
    *,
    mesh: Mesh | None = None,
) -> int:
    """Distinct (contig, pos, ref, alt) across shards, computed as one
    mesh program (hash-exact; see module docstring)."""
    with span("distinct.device") as sp:
        keys = shard_keys(shards)
        if len(keys) == 0:
            return 0
        mesh = mesh or make_mesh()
        n_dev = mesh.devices.size
        blocks = partition_keys(keys, n_dev)
        sharding = NamedSharding(mesh, P(AXIS))
        blocks_dev = jax.device_put(jnp.asarray(blocks), sharding)
        fn = _compiled_for(mesh)
        total = int(jax.device_get(fn(blocks_dev)))
        sp.note(rows=len(keys), devices=n_dev)
    return total
