"""Live shard migration: copy -> dual-serve -> canary-verify -> cut-over.

The reference sBeacon rebalances by tearing a dataset down and
re-summarising it — a serving gap every time the fleet grows or
shrinks. This controller converges the seams the last five PRs built
(epoch-retiring atomic publish, per-dataset fingerprint routing, the
known-answer canary prober, the fleet digest plane) into a migration
protocol that moves a dataset between replicas with **zero serving
gap**:

1. **copy** — stream the source's base + L1 artifacts and standing
   delta tail to the target over ``/migrate/fetch`` / ``/migrate/
   adopt``. Artifact identity is the epoch-ranged fingerprint the
   replica grouping already reads (``vcf|vc|cc|rows`` base comps,
   ``vcf#d<epoch>|rows`` tail parts), so a crashed copy RESUMES: the
   re-run's manifest diff skips everything the target already adopted.
2. **dual-serve** — admit the target to the fleet and publish it into
   the routing table alongside the source. The router's tail-superset
   relation (``dispatch._group_replicas``) makes this safe under load:
   a target standing one delta behind the still-ingesting source is a
   valid (slightly stale) copy, not a divergence loser.
3. **canary-verify** — drive known-answer probes (the canary prober's
   bracket grammar, carried in the migration manifest) directly at
   source and target via ``call_replica`` and require N consecutive
   clean rounds of byte-identical answers; any mismatch aborts and
   rolls the target back out.
4. **cut-over** — retire the source's route entries ATOMICALLY
   (``ReplicaRouter.retire`` pins the pair out in the same critical
   section that bumps the table, and the pin survives rediscovery
   republish), drain the source's in-flight legs, then tell it to
   drop the dataset.

Every phase entry is a ``fault_point`` seam (``migration:copy``,
``migration:dual_serve``, ``migration:verify``, ``migration:cutover``)
so chaos tests can kill the controller at each boundary. The invariant
the exception paths preserve: **at every instant at least one
routable, fresh copy serves the dataset** — a copy-phase crash leaves
the source untouched (and the partial target un-admitted); any later
crash rolls the target back out while the source keeps serving. Never
a half-routed state.

Stdlib-only. This module never imports ``dispatch`` (the edge runs the
other way: ``DistributedEngine`` constructs the controller); transport
rides the engine's pooled keep-alive layer when present, the urllib
fallbacks otherwise — always inside the existing worker-token boundary.
"""

from __future__ import annotations

import base64
import dataclasses
import itertools
import logging
import threading
import time

from ..harness.faults import fault_point
from ..payloads import VariantQueryPayload
from ..telemetry import publish_event
from .transport import urllib_post, urllib_post_bytes

log = logging.getLogger(__name__)

#: phases an in-flight migration moves through (terminal states below)
ACTIVE_PHASES = ("pending", "copy", "dual_serve", "verify", "cutover")
TERMINAL_PHASES = ("completed", "rolled_back", "failed")


class MigrationError(RuntimeError):
    """A migration aborted (after cleanup — rollback or abandon)."""


@dataclasses.dataclass
class Migration:
    """One migration's record (mutated under the controller lock)."""

    id: str
    dataset: str
    source: str
    target: str
    phase: str = "pending"
    started_mono: float = 0.0
    phase_mono: float = 0.0
    copy_s: float = 0.0
    bytes_copied: int = 0
    artifacts_copied: int = 0
    artifacts_skipped: int = 0
    verify_rounds: int = 0
    error: str | None = None


class MigrationController:
    """The coordinator-side migration protocol driver.

    ``start()`` validates and runs one migration on a background
    thread (the ``POST /fleet/migrate`` entry); ``run()`` is the same
    protocol synchronous (tests, benches — and its ``on_phase`` hook
    is the corruption seam the verify-mismatch tests use).
    ``status()`` / ``stuck()`` feed the fleet digest, ``counters()``
    the ``migration.*`` metric series.
    """

    #: control-message budget (manifest/adopt/drop are small JSON)
    CONTROL_TIMEOUT_S = 10.0
    #: per-artifact fetch/adopt budget (a base shard is a real blob)
    FETCH_TIMEOUT_S = 60.0
    #: manifest re-diff rounds before declaring non-convergence (the
    #: source is still ingesting faster than the copier can mirror)
    MIRROR_ROUNDS = 8
    #: seconds the cut-over waits for the retired source's in-flight
    #: legs to drain before telling it to drop the dataset
    DRAIN_GRACE_S = 5.0
    #: finished migrations retained for /fleet/migrations history
    KEEP = 32

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.Lock()
        self._migrations: list[Migration] = []
        self._threads: list[threading.Thread] = []
        self._seq = itertools.count(1)
        self._closed = threading.Event()
        self._started = 0
        self._completed = 0
        self._rolled_back = 0
        self._bytes_copied = 0

    # -- knobs (read live: a rebuilt config object is picked up) ------------

    def _obs(self):
        return getattr(self.engine.config, "observability", None)

    def enabled(self) -> bool:
        return bool(getattr(self._obs(), "migration_enabled", True))

    def verify_rounds(self) -> int:
        return max(
            1, int(getattr(self._obs(), "migration_verify_rounds", 3))
        )

    def copy_timeout_s(self) -> float:
        return float(
            getattr(self._obs(), "migration_copy_timeout_s", 120.0)
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self, dataset: str, source: str, target: str) -> Migration:
        """Validate + launch one migration on a daemon thread; returns
        its registered record immediately (phase ``pending``)."""
        m = self._admit(dataset, source, target)
        t = threading.Thread(
            target=self._run_safe,
            args=(m,),
            daemon=True,
            name=f"migration-{m.id}",
        )
        with self._lock:
            self._threads = [
                th for th in self._threads if th.is_alive()
            ] + [t]
        t.start()
        return m

    def run(
        self, dataset: str, source: str, target: str, on_phase=None
    ) -> Migration:
        """The synchronous protocol (tests/benches): raises
        :class:`MigrationError` after cleanup on any failure."""
        m = self._admit(dataset, source, target)
        self._run(m, on_phase)
        return m

    def _admit(self, dataset: str, source: str, target: str) -> Migration:
        if not self.enabled():
            raise MigrationError(
                "migration disabled (BEACON_MIGRATION_ENABLED=0)"
            )
        dataset, source, target = str(dataset), str(source), str(target)
        if not dataset or not source or not target:
            raise MigrationError(
                "migrate needs dataset, source and target"
            )
        if source == target:
            raise MigrationError("source and target are the same worker")
        with self._lock:
            for m in self._migrations:
                if m.dataset == dataset and m.phase in ACTIVE_PHASES:
                    raise MigrationError(
                        f"dataset {dataset!r} already migrating ({m.id})"
                    )
            now = time.monotonic()
            m = Migration(
                id=f"mig-{next(self._seq)}",
                dataset=dataset,
                source=source,
                target=target,
                started_mono=now,
                phase_mono=now,
            )
            self._migrations.append(m)
            # bounded history: prune the OLDEST terminal records
            while len(self._migrations) > self.KEEP:
                for i, old in enumerate(self._migrations):
                    if old.phase in TERMINAL_PHASES:
                        del self._migrations[i]
                        break
                else:
                    break
            self._started += 1
        publish_event(
            "migration.started",
            id=m.id,
            dataset=dataset,
            source=source,
            target=target,
        )
        return m

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)

    def _check_abort(self) -> None:
        if self._closed.is_set():
            raise MigrationError("migration controller closing")

    # -- the protocol --------------------------------------------------------

    def _run_safe(self, m: Migration) -> None:
        try:
            self._run(m, None)
        except MigrationError as e:
            log.warning("migration %s aborted: %s", m.id, e)
        except Exception:
            log.exception("migration %s died unexpectedly", m.id)

    def _run(self, m: Migration, on_phase) -> None:
        # a copy-phase crash ABANDONS (source untouched + still routed,
        # adopted artifacts kept on the target so a re-run resumes);
        # any later crash ROLLS BACK (target routed out + dropped,
        # source keeps serving) — the never-half-routed invariant
        try:
            self._copy(m, on_phase)
        except BaseException as e:
            self._abandon(m, e)
            raise MigrationError(f"{m.id}: copy failed: {e}") from e
        try:
            self._dual_serve(m, on_phase)
            self._verify(m, on_phase)
            self._cutover(m, on_phase)
        except BaseException as e:
            self._rollback(m, e)
            raise MigrationError(f"{m.id}: rolled back: {e}") from e
        self._complete(m)

    def _enter_phase(self, m: Migration, phase: str) -> None:
        with self._lock:
            m.phase = phase
            m.phase_mono = time.monotonic()
        publish_event(
            "migration.phase",
            id=m.id,
            dataset=m.dataset,
            phase=phase,
            source=m.source,
            target=m.target,
        )

    def _tag(self, m: Migration) -> str:
        return f"{m.dataset}:{m.source}->{m.target}"

    def _copy(self, m: Migration, on_phase) -> None:
        self._enter_phase(m, "copy")
        fault_point("migration:copy", self._tag(m))
        if on_phase:
            on_phase("copy", m)
        t0 = time.monotonic()
        self._mirror(
            m,
            deadline=t0 + max(1.0, self.copy_timeout_s()),
            count_skips=True,
        )
        with self._lock:
            m.copy_s = time.monotonic() - t0

    def _dual_serve(self, m: Migration, on_phase) -> None:
        self._enter_phase(m, "dual_serve")
        fault_point("migration:dual_serve", self._tag(m))
        if on_phase:
            on_phase("dual_serve", m)
        # late arrivals between copy end and admission
        self._mirror(m, deadline=time.monotonic() + self.copy_timeout_s())
        if not self.engine.add_worker(m.target):
            # already a fleet member: republish so its new dataset
            # copy enters the table
            self.engine.replica_table(refresh=True)
        urls = self.engine.router.replicas(m.dataset)
        missing = {m.source, m.target} - set(urls)
        if missing:
            raise MigrationError(
                f"dual-serve did not route both copies of {m.dataset} "
                f"(absent: {sorted(missing)}; routed: {sorted(urls)}) — "
                "copies grouped divergent?"
            )

    def _verify(self, m: Migration, on_phase) -> None:
        self._enter_phase(m, "verify")
        fault_point("migration:verify", self._tag(m))
        if on_phase:
            on_phase("verify", m)
        rounds = self.verify_rounds()
        clean = 0
        attempts = 0
        while clean < rounds:
            self._check_abort()
            attempts += 1
            if attempts > rounds + self.MIRROR_ROUNDS:
                raise MigrationError(
                    f"verify never reached {rounds} consecutive clean "
                    "rounds (source manifest kept moving)"
                )
            src_man = self._manifest(m.source, m.dataset)
            tgt_man = self._manifest(m.target, m.dataset)
            if not self._covered(src_man, tgt_man):
                # the still-ingesting source published since the copy:
                # re-mirror; this round does NOT count toward N
                self._mirror(
                    m, deadline=time.monotonic() + self.copy_timeout_s()
                )
                continue
            for pay in self._verify_payloads(
                m.dataset, src_man.get("bracket")
            ):
                ref = self.engine.call_replica(m.source, pay)
                got = self.engine.call_replica(m.target, pay)
                if sorted(r.dumps() for r in ref) != sorted(
                    r.dumps() for r in got
                ):
                    raise MigrationError(
                        f"canary-verify mismatch ({pay.query_id}, "
                        f"{pay.requested_granularity}): target answer "
                        "diverges from source"
                    )
            clean += 1
            with self._lock:
                m.verify_rounds = clean

    def _cutover(self, m: Migration, on_phase) -> None:
        self._enter_phase(m, "cutover")
        # the seam fires BEFORE the retire: a crash here rolls back
        # with the source never having left the table
        fault_point("migration:cutover", self._tag(m))
        if on_phase:
            on_phase("cutover", m)
        src_man = self._manifest(m.source, m.dataset)
        tgt_man = self._manifest(m.target, m.dataset)
        if not self._covered(src_man, tgt_man):
            raise MigrationError(
                "cut-over refused: target no longer covers the source "
                "manifest (late publish after verify)"
            )
        router = self.engine.router
        # atomic retire: pin + table removal in ONE router critical
        # section, and the pin survives any concurrent rediscovery
        # republish. Everything after this point is non-raising: the
        # source must never stay retired because of a later exception
        # while the pin's cleanup was skipped.
        router.retire(m.dataset, m.source)
        t0 = time.monotonic()
        while (
            self.engine.inflight(m.source) > 0
            and time.monotonic() - t0 < self.DRAIN_GRACE_S
        ):
            time.sleep(0.01)
        dropped = False
        try:
            status, doc = self._post_json(
                m.source, "drop", {"dataset": m.dataset}
            )
            dropped = status == 200 and bool(doc.get("ok"))
            if not dropped:
                log.warning(
                    "migration %s: source %s refused drop (http %s: "
                    "%s) — keeping its route for %s retired",
                    m.id,
                    m.source,
                    status,
                    doc.get("error"),
                    m.dataset,
                )
        except Exception as e:
            log.warning(
                "migration %s: source %s drop failed (%s) — keeping "
                "its route for %s retired",
                m.id,
                m.source,
                e,
                m.dataset,
            )
        if dropped:
            # the source no longer advertises the dataset: the pin has
            # nothing left to filter and a future re-ingest on that
            # worker must be routable again
            router.unretire(m.dataset, m.source)
        try:
            self.engine.replica_table(refresh=True)
        except Exception:
            log.exception("post-cutover route refresh failed")

    def _complete(self, m: Migration) -> None:
        with self._lock:
            m.phase = "completed"
            m.phase_mono = time.monotonic()
            self._completed += 1
        publish_event(
            "migration.completed",
            id=m.id,
            dataset=m.dataset,
            source=m.source,
            target=m.target,
            bytes=m.bytes_copied,
            verifyRounds=m.verify_rounds,
        )

    def _abandon(self, m: Migration, err: BaseException) -> None:
        """Copy-phase failure: the source was never touched and the
        target never admitted — keep the adopted artifacts so a re-run
        resumes (its manifest diff skips them)."""
        with self._lock:
            m.phase = "failed"
            m.phase_mono = time.monotonic()
            m.error = str(err)[:500]
        publish_event(
            "migration.failed",
            id=m.id,
            dataset=m.dataset,
            source=m.source,
            target=m.target,
            error=str(err)[:200],
        )

    def _rollback(self, m: Migration, err: BaseException) -> None:
        """Route the target back out (atomically, pin-protected
        against rediscovery) and best-effort drop its copy; the source
        never stopped serving. A dead target (chaos kill) keeps its
        pin — it cannot re-enter this dataset's routes until an
        operator (or a fresh migration) lifts it."""
        router = self.engine.router
        router.retire(m.dataset, m.target)
        dropped = False
        try:
            status, doc = self._post_json(
                m.target, "drop", {"dataset": m.dataset}
            )
            dropped = status == 200 and bool(doc.get("ok"))
        except Exception:
            pass
        if dropped:
            router.unretire(m.dataset, m.target)
        try:
            self.engine.replica_table(refresh=True)
        except Exception:
            pass
        with self._lock:
            m.phase = "rolled_back"
            m.phase_mono = time.monotonic()
            m.error = str(err)[:500]
            self._rolled_back += 1
        publish_event(
            "migration.rolled_back",
            id=m.id,
            dataset=m.dataset,
            source=m.source,
            target=m.target,
            error=str(err)[:200],
        )

    # -- copy machinery ------------------------------------------------------

    @staticmethod
    def _art_key(art: dict) -> tuple:
        return (
            art.get("kind"),
            art.get("vcf"),
            art.get("epoch"),
            art.get("fingerprint"),
        )

    @classmethod
    def _covered(cls, src_man: dict, tgt_man: dict) -> bool:
        """Target covers source: every source artifact (by epoch-ranged
        fingerprint) stands on the target. The target may stand EXTRA
        stale deltas the source has since folded — adopting the folded
        base retires them, and until then the tail-superset relation
        keeps the copies routable together."""
        src = {cls._art_key(a) for a in src_man.get("artifacts", [])}
        tgt = {cls._art_key(a) for a in tgt_man.get("artifacts", [])}
        return src <= tgt

    def _mirror(
        self, m: Migration, deadline: float, count_skips: bool = False
    ) -> dict:
        """Diff manifests and stream every artifact the target lacks
        (bases before deltas — the manifest's order — so epoch
        monotonicity holds on adoption), re-diffing until covered.
        Returns the last source manifest."""
        for _ in range(self.MIRROR_ROUNDS):
            self._check_abort()
            src_man = self._manifest(m.source, m.dataset)
            if not src_man.get("artifacts"):
                raise MigrationError(
                    f"source {m.source} serves no artifacts for "
                    f"{m.dataset!r}"
                )
            tgt_man = self._manifest(m.target, m.dataset)
            tgt_keys = {
                self._art_key(a) for a in tgt_man.get("artifacts", [])
            }
            missing = [
                a
                for a in src_man["artifacts"]
                if self._art_key(a) not in tgt_keys
            ]
            if count_skips:
                with self._lock:
                    m.artifacts_skipped += len(
                        src_man["artifacts"]
                    ) - len(missing)
                count_skips = False
            if not missing:
                return src_man
            for art in missing:
                self._check_abort()
                if time.monotonic() > deadline:
                    raise MigrationError(
                        f"copy budget "
                        f"({self.copy_timeout_s():g}s) exhausted with "
                        f"{len(missing)} artifact(s) outstanding"
                    )
                blob = self._fetch(m.source, m.dataset, art)
                if blob is None:
                    # a racing fold retired the artifact between the
                    # diff and the fetch: re-diff and move on
                    break
                self._adopt(m, art, blob)
        raise MigrationError(
            f"source and target manifests for {m.dataset!r} failed to "
            f"converge in {self.MIRROR_ROUNDS} mirror rounds"
        )

    def _manifest(self, url: str, dataset: str) -> dict:
        status, doc = self._post_json(
            url, "manifest", {"dataset": dataset}
        )
        return self._checked(url, "manifest", status, doc)

    def _fetch(self, url: str, dataset: str, art: dict):
        body: dict = {"dataset": dataset, "vcf": art.get("vcf")}
        if art.get("kind") == "delta":
            body["epoch"] = art.get("epoch")
        t = getattr(self.engine, "transport", None)
        post_b = t.post_bytes if t is not None else urllib_post_bytes
        status, blob = post_b(
            f"{url}/migrate/fetch",
            body,
            self.FETCH_TIMEOUT_S,
            self._headers() or None,
        )
        if status == 404:
            return None
        if status != 200:
            raise MigrationError(
                f"fetch {self._art_key(art)} from {url}: http {status}"
            )
        return blob

    def _adopt(self, m: Migration, art: dict, blob: bytes) -> None:
        doc: dict = {
            "dataset": m.dataset,
            "kind": art.get("kind"),
            "blob": base64.b64encode(blob).decode("ascii"),
        }
        if art.get("kind") == "delta":
            doc["epoch"] = art.get("epoch")
        status, out = self._post_json(
            m.target, "adopt", doc, timeout_s=self.FETCH_TIMEOUT_S
        )
        self._checked(m.target, "adopt", status, out)
        if not out.get("ok"):
            raise MigrationError(
                f"adopt {self._art_key(art)} on {m.target}: "
                f"{out.get('error')}"
            )
        with self._lock:
            m.bytes_copied += len(blob)
            m.artifacts_copied += 1
            self._bytes_copied += len(blob)

    @staticmethod
    def _checked(url: str, op: str, status: int, doc) -> dict:
        if status == 404:
            raise MigrationError(
                f"worker {url} does not support migration "
                f"(/migrate/{op} answered 404 — engine without the "
                "migration seams?)"
            )
        if status in (401, 403):
            raise MigrationError(
                f"worker {url} rejected migration credentials "
                f"(http {status}): check BEACON_WORKER_TOKEN"
            )
        if status != 200 or not isinstance(doc, dict):
            err = doc.get("error") if isinstance(doc, dict) else doc
            raise MigrationError(
                f"/migrate/{op} on {url}: http {status}: {err}"
            )
        return doc

    # -- verify probes -------------------------------------------------------

    def _verify_payloads(
        self, dataset: str, bracket: dict | None
    ) -> list[VariantQueryPayload]:
        """Known-answer probes x query shapes, from the bracket the
        source's manifest carried (canary.py grammar): the known-hit
        row, a known-miss window past the coordinate ceiling, and a
        full-range row-count sweep — each in boolean and count shape.
        No bracket (artifact-less corner) -> manifest parity was the
        whole check and the round is clean by construction."""
        if not bracket:
            return []
        chrom = str(bracket.get("chrom"))
        max_end = int(bracket.get("maxEnd") or 0)
        shapes = ("boolean", "count")
        specs: list[tuple[str, dict]] = []
        if "pos" in bracket:
            pos = int(bracket["pos"])
            specs.append(
                (
                    "hit",
                    dict(
                        start_min=pos,
                        start_max=pos,
                        end_min=1,
                        end_max=max_end + 1_000_000,
                        alternate_bases=str(bracket.get("alt") or "N"),
                    ),
                )
            )
        specs.append(
            (
                "range",
                dict(
                    start_min=1,
                    start_max=max_end + 1_000_000,
                    end_min=1,
                    end_max=max_end + 2_000_000,
                    alternate_bases="N",
                ),
            )
        )
        specs.append(
            (
                "miss",
                dict(
                    start_min=max_end + 1_000,
                    start_max=max_end + 2_000,
                    end_min=1,
                    end_max=max_end + 2_000,
                    alternate_bases="N",
                ),
            )
        )
        return [
            VariantQueryPayload(
                dataset_ids=[dataset],
                reference_name=chrom,
                requested_granularity=shape,
                # the probe must read the LIVE plane on both replicas
                no_response_cache=True,
                query_id=f"migrate-{name}-{dataset}",
                **spec,
            )
            for name, spec in specs
            for shape in shapes
        ]

    # -- transport -----------------------------------------------------------

    def _headers(self) -> dict:
        tok = getattr(self.engine, "_token", "") or ""
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    def _post_json(
        self, url: str, op: str, doc: dict, timeout_s: float | None = None
    ):
        t = getattr(self.engine, "transport", None)
        post = t.post_json if t is not None else urllib_post
        return post(
            f"{url}/migrate/{op}",
            doc,
            timeout_s or self.CONTROL_TIMEOUT_S,
            self._headers() or None,
        )

    # -- surfaces ------------------------------------------------------------

    def status(self) -> list[dict]:
        """Every retained migration, oldest first — the fleet digest's
        ``migrations`` section and ``GET /fleet/migrations``."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "id": m.id,
                    "dataset": m.dataset,
                    "source": m.source,
                    "target": m.target,
                    "phase": m.phase,
                    "phaseAgeS": round(now - m.phase_mono, 1),
                    "ageS": round(now - m.started_mono, 1),
                    "bytesCopied": m.bytes_copied,
                    "artifactsCopied": m.artifacts_copied,
                    "artifactsSkipped": m.artifacts_skipped,
                    "verifyRounds": m.verify_rounds,
                    "error": m.error,
                }
                for m in self._migrations
            ]

    def stuck(self) -> dict | None:
        """The first in-flight migration whose current phase outlived
        its bound — the copy budget for the copy phase, 2x the
        measured copy time (floor 1 s) for every later phase — or
        None. The fleet diagnosis names it, mirroring the
        stalest-replica pattern."""
        now = time.monotonic()
        with self._lock:
            for m in self._migrations:
                if m.phase not in ACTIVE_PHASES or m.phase == "pending":
                    continue
                bound = (
                    max(1.0, self.copy_timeout_s())
                    if m.phase == "copy"
                    else 2.0 * max(m.copy_s, 1.0)
                )
                age = now - m.phase_mono
                if age > bound:
                    return {
                        "id": m.id,
                        "dataset": m.dataset,
                        "source": m.source,
                        "target": m.target,
                        "phase": m.phase,
                        "phaseAgeS": round(age, 1),
                        "boundS": round(bound, 1),
                    }
        return None

    def counters(self) -> dict:
        """The ``migration.*`` metric values (dispatch_stats merges
        these; register_dispatch_metrics reads them through it)."""
        with self._lock:
            return {
                "started": self._started,
                "completed": self._completed,
                "rolled_back": self._rolled_back,
                "bytes_copied": self._bytes_copied,
            }
