"""Cross-host query dispatch: the DCN tier of the comm backbone.

SURVEY.md §2.5/§5: inside a pod, fan-out/fan-in is one compiled program
over ICI (``mesh.py`` — psum/all_gather replace the SNS/DynamoDB barrier
apparatus entirely); *across hosts*, the reference's process boundary —
SNS messages / direct Lambda invokes carrying ``SplitQueryPayload`` /
``PerformQueryResponse`` JSON (reference: sns.tf, variantutils/
local_utils.py:37-44, splitQuery/lambda_function.py:28-35) — becomes a
thin typed-payload dispatcher: each worker host owns a set of dataset
index shards behind a :class:`WorkerServer`; the coordinator's
:class:`DistributedEngine` routes a ``VariantQueryPayload`` to the
workers owning its datasets (thread-pool scatter, the reference's
ThreadPoolExecutor(500) shape), retries transient failures (the
reference's 10x save / retry loops), and merges the per-(dataset,vcf)
response lists — presenting the exact ``VariantEngine`` interface so the
API layer, job table, and micro-batcher compose unchanged. Datasets
served by several workers keep their full replica list
(:class:`ReplicaRouter`): power-of-two-choices routing over recent
RTTs, failover to the next replica on worker errors or open circuits,
replica-hedged searches for slow primaries, partial-results
degradation when every copy is down, and a background rediscovery loop
that heals routes — the fault tolerance the reference inherited from
Lambda invoke retries, made explicit.

Transport is stdlib HTTP+JSON (the payload types' stable dict form)
over the pooled keep-alive layer in ``transport.py`` (per-worker
connection pools, hedged scans, gzip bodies); inject ``post=``/``get=``
callables to swap in gRPC/DCN transport in a pod deployment. For
multi-host *compute* (one jit program spanning hosts), see
``init_multihost`` — jax.distributed over the same coordinator model.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import gzip
import hmac
import json
import logging
import random
import threading
import time
import urllib.error
import concurrent.futures as futures_mod

import numpy as np
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..harness.faults import fault_point
from .transport import (
    PooledTransport,
    note_hedge,
    register_transport_metrics,
    urllib_get,
    urllib_post,
    urllib_post_bytes,
)
from ..payloads import (
    SliceScanPayload,
    VariantQueryPayload,
    VariantSearchResponse,
)
from .. import telemetry as telemetry_mod
from ..plan import plan_stage
from ..resilience import (
    CLOSED,
    OPEN,
    CircuitBreaker,
    CircuitOpen,
    DeadlineExceeded,
    current_deadline,
    register_breaker_metrics,
)
from ..telemetry import (
    TRACE_HEADER,
    RequestContext,
    annotate,
    charge_cost,
    current_context,
    device_warmup_phase,
    new_span_id,
    publish_event,
    request_context,
    sanitize_trace_id,
)
from ..utils.trace import Span, span

log = logging.getLogger(__name__)


# -- hedging kill-switch ------------------------------------------------------

#: process-wide hedge enable flag: the brownout ladder's FIRST rung
#: (shaping.BrownoutLadder via set_hedging_enabled) — under a sustained
#: SLO breach the cheapest load to shed is the duplicate calls hedging
#: adds, before any request is refused. Process-global like the fault
#: injector: scan pools and replica routers live below the app layer.
_hedging_enabled = True


def set_hedging_enabled(enabled: bool) -> None:
    """Flip the process-wide hedging kill-switch (brownout rung 1).
    Affects the adaptive/fixed hedge delay computation in BOTH the
    ingest scan pool and the replica-hedged search path; in-flight
    hedges are unaffected."""
    global _hedging_enabled
    _hedging_enabled = bool(enabled)


def hedging_enabled() -> bool:
    return _hedging_enabled


# -- worker side --------------------------------------------------------------


def _make_handler(
    engine, token: str = "", open_scan: bool = False, reload_fn=None
):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: the coordinator's pooled transport holds a few
        # persistent connections per worker instead of a TCP handshake
        # (and a ThreadingHTTPServer thread spawn) per call
        protocol_version = "HTTP/1.1"
        # reap idle keep-alive connections a little after the
        # coordinator's pool TTL would have evicted them anyway
        timeout = 120.0

        def log_message(self, *a):  # quiet
            pass

        def _read_body(self) -> bytes:
            """The full request body, gunzipped when the coordinator
            compressed it (transport.py gzip_min_bytes)."""
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n else b""
            if self.headers.get("Content-Encoding", "").lower() == "gzip":
                raw = gzip.decompress(raw)
            return raw

        def _send(self, status: int, payload):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authorized(self) -> bool:
            # shared-token gate on the worker boundary (the reference's
            # equivalent — direct Lambda invoke/SNS — was IAM-gated);
            # /health stays open for liveness probes
            if not token:
                return True
            got = self.headers.get("Authorization", "")
            # bytes compare: compare_digest raises TypeError on non-ASCII
            # str, which would kill the request with no response
            return hmac.compare_digest(
                got.encode(), f"Bearer {token}".encode()
            )

        def do_GET(self):
            if self.path == "/health":
                self._send(200, {"ok": True})
            elif not self._authorized():
                self._send(401, {"error": "unauthorized"})
            elif self.path == "/ops/digest":
                # the fleet-federation exchange payload (ISSUE 12):
                # bounded worker health/freshness digest, behind the
                # SAME worker-token boundary as /search — the digest
                # names datasets and fingerprints, which are data-plane
                # metadata, not public probe output
                self._send(200, ops_digest(engine))
            elif self.path == "/datasets":
                # per-dataset fingerprints let the coordinator group
                # only IDENTICAL shard copies as replicas (a worker
                # serving a stale copy of one dataset must not be
                # treated as interchangeable with a fresh one)
                ds_fps = getattr(engine, "dataset_fingerprints", None)
                self._send(
                    200,
                    {
                        "datasets": engine.datasets(),
                        "fingerprint": engine.index_fingerprint(),
                        "dataset_fingerprints": (
                            ds_fps() if ds_fps is not None else {}
                        ),
                    },
                )
            else:
                self._send(404, {"error": "not found"})

        def _send_bytes(self, status: int, body: bytes):
            self.send_response(status)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            # the body is read BEFORE any early return: with HTTP/1.1
            # keep-alive, unread body bytes would bleed into the next
            # request's parse on this connection
            try:
                raw = self._read_body()
            except Exception:
                self._send(400, {"error": "bad request body"})
                return
            if not self._authorized():
                self._send(401, {"error": "unauthorized"})
                return
            if self.path == "/reload":
                # re-pin shards from storage (a coordinator that ingested
                # into shared storage tells workers to pick the new
                # shards up without a process restart)
                if reload_fn is None:
                    self._send(404, {"error": "reload not wired"})
                    return
                try:
                    n = reload_fn()
                    self._send(200, {"ok": True, "shards": int(n)})
                except Exception as e:
                    log.exception("worker reload failed")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            if self.path.startswith("/migrate/"):
                # live-migration artifact plane (ISSUE 16): manifest /
                # fetch / adopt / drop, all POST (keep-alive-safe
                # bodies), all inside the SAME worker-token boundary
                # as /search and /reload — migration widens no trust
                # surface. Served only when the engine grows the
                # migration seams; a worker running an engine shape
                # without them answers 404.
                self._do_migrate(raw)
                return
            if self.path == "/scan":
                # /scan range-reads a CLIENT-SUPPLIED location (local path
                # or URL) — an SSRF/arbitrary-read primitive if exposed.
                # Secure by default: only served when a shared token gates
                # the worker, or when the operator opted in explicitly
                # (in-process tests, airtight private networks).
                if not token and not open_scan:
                    self._send(
                        403,
                        {
                            "error": "scan requires a worker token "
                            "(or --open-scan on a private network)"
                        },
                    )
                    return
                self._do_scan(raw)
                return
            if self.path != "/search":
                self._send(404, {"error": "not found"})
                return
            try:
                t_recv = time.perf_counter()
                # from_doc drops unknown keys: this worker must keep
                # answering a coordinator one payload-field ahead of it
                payload = VariantQueryPayload.from_doc(json.loads(raw))
                # adopt the coordinator's trace id (X-Beacon-Trace) so
                # worker-side spans parent into the same distributed
                # trace; a direct caller without the header gets a
                # fresh worker-local id
                ctx = RequestContext(
                    trace_id=sanitize_trace_id(
                        self.headers.get(TRACE_HEADER)
                    ),
                    route="worker.search",
                )
                with request_context(ctx), span(
                    "worker.search",
                    datasets=len(payload.dataset_ids or []),
                ):
                    t_eng = time.perf_counter()
                    responses = engine.search(payload)
                    engine_s = time.perf_counter() - t_eng
                t_ser = time.perf_counter()
                docs = [dataclasses.asdict(r) for r in responses]
                serialize_s = time.perf_counter() - t_ser
                # the span-summary side channel (ISSUE 12): a compact
                # worker-stage decomposition the coordinator grafts as
                # child spans into its own trace tree — the worker's
                # time stops being an opaque RTT. ``queueMs`` is the
                # micro-batch wait when the engine annotated one;
                # ``cache`` the response-cache outcome; ``rows`` the
                # matched rows shipped back. Bounded and additive: an
                # old coordinator ignores the extra key.
                notes = ctx.notes
                try:
                    queue_ms = float(notes.get("batch_ms") or 0.0)
                except (TypeError, ValueError):
                    queue_ms = 0.0
                # the batch wait happened INSIDE engine.search: report
                # engine time EXCLUSIVE of it so the grafted stages lay
                # out sequentially without double-counting the queue
                engine_excl_ms = max(engine_s * 1e3 - queue_ms, 0.0)
                self._send(
                    200,
                    {
                        "responses": docs,
                        "meta": {
                            "spanId": new_span_id(),
                            "queueMs": round(queue_ms, 3),
                            "engineMs": round(engine_excl_ms, 3),
                            "serializeMs": round(serialize_s * 1e3, 3),
                            "totalMs": round(
                                (time.perf_counter() - t_recv) * 1e3, 3
                            ),
                            "rows": sum(len(r.variants) for r in responses),
                            "cache": notes.get("response_cache", ""),
                            "datasets": len(payload.dataset_ids or []),
                        },
                    },
                )
            except Exception as e:  # worker errors travel to coordinator
                log.exception("worker search failed")
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _do_scan(self, raw: bytes):
            """Ingest slice-scan leaf (the summariseSlice worker role):
            range-read + parse + build one slice shard, returned as a raw
            npz blob. The VCF location must be reachable from the worker
            (shared filesystem or object-store URL)."""
            try:
                from ..index.columnar import dumps_index
                from ..ingest.pipeline import scan_slice_to_shard

                p = SliceScanPayload(**json.loads(raw))
                shard = scan_slice_to_shard(
                    p.vcf_location,
                    p.vstart,
                    p.vend,
                    dataset_id=p.dataset_id,
                    sample_names=p.sample_names,
                )
                self._send_bytes(200, dumps_index(shard))
            except Exception as e:
                log.exception("worker slice scan failed")
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _do_migrate(self, raw: bytes):
            """Shard-migration artifact exchange: ``manifest`` lists a
            dataset's base + standing-delta artifacts by epoch-ranged
            fingerprint (the resume key), ``fetch`` streams one as a
            raw npz blob, ``adopt`` installs a received artifact at its
            ORIGINAL epoch, and ``drop`` retires the dataset after
            cut-over. Every seam is getattr-guarded: a worker embedding
            an engine without the migration entry points answers 404,
            and the controller reports it instead of half-migrating."""
            from ..index.columnar import dumps_index, loads_index

            op = self.path[len("/migrate/"):]
            try:
                doc = json.loads(raw) if raw else {}
                if not isinstance(doc, dict):
                    raise ValueError("migrate body must be an object")
            except Exception:
                self._send(400, {"error": "bad migrate body"})
                return
            ds = str(doc.get("dataset") or "")
            try:
                if op == "manifest":
                    fn = getattr(engine, "migration_manifest", None)
                    if fn is None:
                        self._send(
                            404, {"error": "migration not supported"}
                        )
                    else:
                        self._send(200, fn(ds))
                elif op == "fetch":
                    fn = getattr(engine, "export_artifact", None)
                    if fn is None:
                        self._send(
                            404, {"error": "migration not supported"}
                        )
                        return
                    shard = fn(
                        ds,
                        str(doc.get("vcf") or ""),
                        epoch=doc.get("epoch"),
                    )
                    if shard is None:
                        self._send(404, {"error": "artifact not found"})
                    else:
                        self._send_bytes(200, dumps_index(shard))
                elif op == "adopt":
                    shard = loads_index(
                        base64.b64decode(doc.get("blob") or "")
                    )
                    if doc.get("kind") == "delta":
                        fn = getattr(engine, "adopt_delta", None)
                        if fn is None:
                            self._send(
                                404,
                                {"error": "migration not supported"},
                            )
                            return
                        adopted = fn(shard, int(doc.get("epoch") or 0))
                        self._send(
                            200, {"ok": True, "adopted": bool(adopted)}
                        )
                    else:
                        engine.add_index(shard)
                        self._send(200, {"ok": True, "adopted": True})
                elif op == "drop":
                    fn = getattr(engine, "drop_dataset", None)
                    if fn is None:
                        self._send(
                            404, {"error": "migration not supported"}
                        )
                    else:
                        self._send(
                            200, {"ok": True, "shards": int(fn(ds))}
                        )
                else:
                    self._send(404, {"error": "not found"})
            except Exception as e:
                log.exception("worker migrate %s failed", op)
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


class _WorkerHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that tracks live client connections so
    shutdown can sever them. A killed worker process takes every
    socket with it; ``server_close`` alone only closes the LISTENER,
    leaving keep-alive handler threads answering on pooled
    coordinator connections — a zombie that would mask exactly the
    dead-worker failover paths the replica layer (and its tests)
    exist for."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conn_lock = threading.Lock()
        self._conns: set = set()

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def handle_error(self, request, client_address):
        # a handler mid-write when close_all_connections severed its
        # socket raises BrokenPipe/ConnectionReset — that IS the
        # faithful kill, not an error worth a stderr traceback
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)

    def close_all_connections(self) -> None:
        import socket as socket_mod

        with self._conn_lock:
            conns, self._conns = list(self._conns), set()
        for sock in conns:
            try:
                sock.shutdown(socket_mod.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class WorkerServer:
    """One worker host's engine behind HTTP (the performQuery leaf's
    process boundary, minus SNS)."""

    def __init__(
        self,
        engine,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        token: str = "",
        open_scan: bool = False,
        reload_fn=None,
    ):
        self.engine = engine
        self.server = _WorkerHTTPServer(
            (host, port),
            _make_handler(engine, token, open_scan, reload_fn),
        )
        self.thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        h, p = self.server.server_address[:2]
        return f"http://{h}:{p}"

    def start_background(self) -> "WorkerServer":
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()
        return self

    def shutdown(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        # faithful kill: live keep-alive connections die with the
        # server, like the process death they stand in for
        self.server.close_all_connections()


#: datasets/fingerprints listed per digest before truncation — the
#: digest must stay a bounded control-plane message, never a data dump
DIGEST_DATASET_CAP = 128


def ops_digest(engine, extras: dict | None = None) -> dict:
    """The bounded worker-health digest served at ``/ops/digest`` (the
    fleet-federation exchange payload, ISSUE 12): per-dataset identity
    (the divergence signal), delta-tail depth/rows (the freshness-lag
    signal), delta publishes, and open breakers. Every field reads
    lock-free engine snapshots — a digest poll must answer while a
    stack rebuild holds the publish lock. ``extras`` lets an embedded
    coordinator add its app-tier signals (SLO breaches, slow-query
    count, top cost tenants); a bare worker host serves the engine
    fields alone. This is also the exchange payload ROADMAP item 4's
    cross-coordinator quota convergence will ride."""
    base_fp = getattr(engine, "base_fingerprint", None)
    ds_fps_fn = getattr(engine, "dataset_fingerprints", None)
    delta_stats = getattr(engine, "delta_stats", None)
    delta_metrics = getattr(engine, "delta_metrics", None)
    datasets = engine.datasets()
    ds_fps = dict(
        sorted((ds_fps_fn() if ds_fps_fn is not None else {}).items())[
            :DIGEST_DATASET_CAP
        ]
    )
    breakers: list[str] = []
    breaker = getattr(engine, "breaker", None)
    if breaker is not None:
        breakers = sorted(
            u
            for u, d in breaker.metrics().items()
            if d.get("state") != "closed"
        )
    doc = {
        "time": time.time(),
        "datasets": datasets[:DIGEST_DATASET_CAP],
        "datasetsTotal": len(datasets),
        "baseFingerprint": (
            base_fp() if base_fp is not None else engine.index_fingerprint()
        ),
        "datasetFingerprints": ds_fps,
        "deltaTails": delta_stats() if delta_stats is not None else {},
        "deltaPublishes": (
            delta_metrics().get("publishes", 0)
            if delta_metrics is not None
            else 0
        ),
        "openBreakers": breakers,
        # device-health exchange fields (module attr, not a from-import:
        # the recorder is process-global and tests swap it): a replica
        # quietly recompiling mid-request or padding most of its lanes
        # away shows up in the FLEET view, not just its own /debug
        "midRequestCompiles": (
            telemetry_mod.flight_recorder.mid_request_compiles()
        ),
        "worstPadWaste": telemetry_mod.flight_recorder.worst_pad_waste(),
    }
    if extras:
        doc.update(extras)
    return doc


# -- coordinator side ---------------------------------------------------------
#
# urllib_post / urllib_get / urllib_post_bytes live in transport.py now
# (re-exported above for back-compat): every real coordinator->worker
# call goes through the pooled keep-alive transport, and the unpooled
# fallbacks are kept only as injectable seams and CLI probes.


def register_dispatch_metrics(registry, supplier) -> None:
    """The coordinator fan-out's own series. ``supplier`` returns the
    current :meth:`DistributedEngine.dispatch_stats` dict (empty on
    single-host engines — the app's fallback registration keeps the
    catalogue deployment-stable, like the breaker series)."""

    def field(name):
        return lambda: supplier().get(name, 0)

    registry.counter(
        "dispatch.short_circuits",
        "boolean fan-outs answered before the full worker drain",
        fn=field("short_circuits"),
    )
    registry.counter(
        "dispatch.failovers",
        "worker search legs re-routed to another replica after a failure",
        fn=field("failovers"),
    )
    registry.counter(
        "dispatch.partial_responses",
        "searches answered partially with some datasets unavailable",
        fn=field("partial_responses"),
    )
    registry.gauge(
        "routing.replicas",
        "replica routes in the table (sum of copies across datasets)",
        fn=field("replicas"),
    )
    registry.counter(
        "routing.rediscoveries",
        "background route-rediscovery passes run to heal dead routes",
        fn=field("rediscoveries"),
    )
    registry.counter(
        "mesh.dispatches",
        "k-shard queries answered by the pod-local single-launch tier",
        fn=field("mesh_dispatches"),
    )
    registry.counter(
        "mesh.fallbacks",
        "mesh-tier failures that fell back to the scatter path",
        fn=field("mesh_fallbacks"),
    )
    registry.counter(
        "mesh.gather_rows",
        "hit rows gathered on-device by the mesh tier's row gather",
        fn=field("mesh_gather_rows"),
    )
    registry.counter(
        "mesh.refusals",
        "queries the mesh tier declined, by reason (planes = "
        "plane-reading shape the stack cannot serve, stale = publish "
        "outran the stack, min_shards = too few local targets, "
        "unbuilt = no stack yet)",
        label="reason",
        fn=lambda: supplier().get("mesh_refusals", {}) or {},
    )
    # fleet federation (ISSUE 12): the digest-poll plane's own series
    registry.counter(
        "fleet.digest_polls",
        "worker /ops/digest collection passes run by the fleet view",
        fn=field("fleet_polls"),
    )
    registry.gauge(
        "fleet.workers_reachable",
        "workers whose latest digest poll answered",
        fn=field("fleet_reachable"),
    )
    registry.gauge(
        "fleet.divergent_datasets",
        "datasets whose replicas advertise divergent fingerprints",
        fn=field("fleet_divergent"),
    )
    # live shard migration (ISSUE 16): the controller's lifecycle series
    registry.counter(
        "migration.started",
        "shard migrations started (copy phase entered)",
        fn=field("migration_started"),
    )
    registry.counter(
        "migration.completed",
        "shard migrations completed through cut-over",
        fn=field("migration_completed"),
    )
    registry.counter(
        "migration.rolled_back",
        "shard migrations aborted and rolled back (verify mismatch, "
        "crash mid-protocol)",
        fn=field("migration_rolled_back"),
    )
    registry.counter(
        "migration.bytes_copied",
        "artifact bytes streamed source->target by migration copies",
        fn=field("migration_bytes_copied"),
    )


def _graft_worker_spans(wsp, url: str, meta, rtt_s: float) -> None:
    """Adopt one worker leg's side-channel span summary (the ``meta``
    block of a ``/search`` response) as child spans of the
    coordinator's ``dispatch.worker_call`` span — the Dapper
    cross-process assembly the reference's SNS fan-out never had.
    Network time is DERIVED (RTT minus the worker-reported total,
    split evenly around the remote span: the coordinator cannot
    observe the skew) and the worker's queue/engine/serialize stages
    lay out sequentially inside it. No-op when tracing is disabled
    (``wsp`` is the null span) or the worker predates the summary."""
    sp = getattr(wsp, "span", None)
    if sp is None or not isinstance(meta, dict):
        return
    try:
        total_ms = float(meta.get("totalMs") or 0.0)
    except (TypeError, ValueError):
        return
    rtt_ms = rtt_s * 1e3
    net_ms = max(rtt_ms - total_ms, 0.0)
    wsp.note(
        networkMs=round(net_ms, 3),
        workerMs=round(total_ms, 3),
        rows=meta.get("rows", 0),
        cache=meta.get("cache", ""),
    )
    now = time.perf_counter()
    w_start = now - rtt_s + net_ms / 2e3
    remote = Span(
        name="worker.remote",
        t_start=w_start,
        t_end=w_start + total_ms / 1e3,
        meta={
            "url": url,
            "rows": meta.get("rows", 0),
            "cache": meta.get("cache", ""),
            "datasets": meta.get("datasets", 0),
        },
        trace_id=sp.trace_id,
        span_id=str(meta.get("spanId") or new_span_id()),
    )
    t = w_start
    for name, key in (
        ("worker.queue", "queueMs"),
        ("worker.engine", "engineMs"),
        ("worker.serialize", "serializeMs"),
    ):
        try:
            ms = float(meta.get(key) or 0.0)
        except (TypeError, ValueError):
            ms = 0.0
        if ms <= 0.0:
            continue
        remote.children.append(
            Span(
                name=name,
                t_start=t,
                t_end=t + ms / 1e3,
                trace_id=sp.trace_id,
                span_id=new_span_id(),
            )
        )
        t += ms / 1e3
    sp.children.append(remote)


def _fingerprint_freshness(fp: str) -> int:
    """Total indexed rows encoded in a per-dataset fingerprint (the
    ``vcf|variant_count|call_count|n_rows`` base parts and the
    ``vcf#d<epoch>|rows`` standing delta-tail parts, joined by ``&``) —
    the 'newer copy' heuristic for divergent replicas: re-ingestion
    only grows a dataset's row count, so when two workers advertise
    the same dataset with different fingerprints the larger copy is
    the one that saw the latest publish. Only the exact 4-field base
    / 2-field epoch-tagged delta shapes parse; anything else sorts
    oldest — in particular a legacy worker's ENGINE-WIDE fallback
    string (``ds|vcf|vc|cc|rows`` 5-field parts spanning its whole
    corpus) must lose to real per-dataset identity, not out-freshen
    it by summing rows across unrelated datasets."""
    total = 0
    for part in fp.split("&"):
        fields = part.split("|")
        # delta-tail part: "vcf#d<epoch>|rows" (engine.py
        # _rebuild_serving_state_locked) — the tail rows count toward
        # freshness, so a deeper-tail copy out-freshens its base twin
        if len(fields) == 2 and "#d" in fields[0]:
            pass
        elif len(fields) != 4:
            return -1
        try:
            total += int(fields[-1])
        except ValueError:
            return -1
    return total


def _fingerprint_parts(
    fp: str,
) -> tuple[frozenset, frozenset] | None:
    """(base parts, delta-tail parts) of a per-dataset fingerprint, or
    None when any part fails the grammar (legacy engine-wide strings
    stay unsplittable — they never enter the tail-superset relation)."""
    bases, deltas = set(), set()
    for part in fp.split("&"):
        fields = part.split("|")
        if len(fields) == 2 and "#d" in fields[0]:
            deltas.add(part)
        elif len(fields) == 4:
            bases.add(part)
        else:
            return None
    return frozenset(bases), frozenset(deltas)


class ReplicaRouter:
    """Replica selection for the search fan-out.

    The discovery pass publishes a ``dataset -> (replica urls)`` table
    here (only fingerprint-identical copies are grouped); ``pick``
    chooses among the live replicas by power-of-two-choices over the
    recent per-worker RTT record (the selection-granularity mirror of
    the transport's ``transport.rtt_ms`` histogram): sample two, take
    the faster, skip breaker-open routes. One slow or dead host then
    stops attracting traffic without any health-check protocol — the
    RTTs the scatter already measures are the health signal.
    """

    #: recent round-trips kept per replica for the p2c comparison and
    #: the adaptive hedge delay
    RTT_WINDOW = 128
    #: adaptive hedging needs this many completed calls before the p95
    #: means anything; until then no hedge fires
    HEDGE_MIN_SAMPLES = 8
    #: adaptive hedge delay never drops below this (a sub-ms p95 would
    #: hedge every call and double fleet load for nothing)
    HEDGE_FLOOR_S = 0.05

    def __init__(self, breaker: CircuitBreaker, *, rng=None):
        self.breaker = breaker
        # seeded: routing spread is reproducible under test
        self._rng = rng or random.Random(0xBEAC0)
        self._lock = threading.Lock()
        self._table: dict[str, tuple[str, ...]] = {}
        self._rtts: dict[str, collections.deque] = {}
        # migration cut-over pins: (dataset, url) pairs routed OUT.
        # publish() filters them inside its own critical section, so a
        # concurrent rediscovery republish can never resurrect a route
        # the cut-over just retired (the half-routed state the
        # migration invariant forbids).
        self._retired: set[tuple[str, str]] = set()

    # -- table --------------------------------------------------------------

    def publish(self, table: dict[str, tuple[str, ...]]) -> None:
        new = {ds: tuple(urls) for ds, urls in table.items()}
        with self._lock:
            if self._retired:
                new = {
                    ds: tuple(
                        u for u in urls if (ds, u) not in self._retired
                    )
                    for ds, urls in new.items()
                }
            changed = new != self._table
            self._table = new
        if changed:
            # flight-recorder: only actual topology changes are events
            # (the rediscovery loop republishes every pass — an
            # unchanged table is not a transition)
            publish_event(
                "routing.table_publish",
                datasets=len(new),
                replicas=sum(len(u) for u in new.values()),
            )

    def table(self) -> dict[str, tuple[str, ...]]:
        with self._lock:
            return dict(self._table)

    def replicas(self, dataset: str) -> tuple[str, ...]:
        with self._lock:
            return self._table.get(dataset, ())

    def replica_count(self) -> int:
        with self._lock:
            return sum(len(urls) for urls in self._table.values())

    def retire(self, dataset: str, url: str) -> None:
        """Route ``url`` out of ``dataset``'s replica set ATOMICALLY:
        the pin lands and the url leaves the live table inside ONE
        critical section — the migration cut-over's 'retire the source
        in the same critical section that bumps the table' contract.
        Retired pairs also survive republish (see :meth:`publish`)."""
        with self._lock:
            self._retired.add((dataset, url))
            urls = self._table.get(dataset)
            if urls and url in urls:
                self._table[dataset] = tuple(
                    u for u in urls if u != url
                )
        publish_event("routing.route_retired", dataset=dataset, url=url)

    def unretire(self, dataset: str, url: str) -> None:
        """Lift a cut-over pin (rollback, or the source finished
        dropping the dataset and no longer advertises it) — the next
        publish may route the pair again if a worker advertises it."""
        with self._lock:
            self._retired.discard((dataset, url))

    def retired(self) -> set[tuple[str, str]]:
        with self._lock:
            return set(self._retired)

    # -- RTT record ---------------------------------------------------------

    def note_rtt(self, url: str, seconds: float) -> None:
        with self._lock:
            ring = self._rtts.get(url)
            if ring is None:
                ring = self._rtts[url] = collections.deque(
                    maxlen=self.RTT_WINDOW
                )
            ring.append(seconds)

    def _rtt(self, url: str) -> float | None:
        """Median recent RTT, or None for an unmeasured replica (treated
        as fast, so fresh replicas get explored instead of starved)."""
        with self._lock:
            ring = self._rtts.get(url)
            if not ring:
                return None
            s = sorted(ring)
        return s[len(s) // 2]

    def median_rtt_ms(self, url: str) -> float | None:
        """Public median-RTT view (``/debug/status`` worker rollup)."""
        rtt = self._rtt(url)
        return None if rtt is None else round(rtt * 1e3, 2)

    def hedge_delay(self, hedge_delay_s: float | None) -> float | None:
        """Seconds to wait before racing a second replica, with the
        scan-pool semantics unchanged: >0 fixed, 0 adaptive (p95 of
        recent RTTs once enough samples exist), <0/None off. The
        brownout kill-switch (``set_hedging_enabled``) overrides all."""
        d = hedge_delay_s
        if d is None or d < 0 or not _hedging_enabled:
            return None
        if d > 0:
            return d
        with self._lock:
            all_rtts = [v for ring in self._rtts.values() for v in ring]
        if len(all_rtts) < self.HEDGE_MIN_SAMPLES:
            return None
        all_rtts.sort()
        return max(
            all_rtts[int(0.95 * (len(all_rtts) - 1))], self.HEDGE_FLOOR_S
        )

    # -- selection ----------------------------------------------------------

    def live(self, url: str) -> bool:
        """Pure observation — never consumes a half-open probe (the
        call-site ``allow`` gate does that once per attempted call)."""
        return self.breaker.state(url) != OPEN

    def pick(self, dataset: str, *, avoid=()) -> str | None:
        """The replica to route ``dataset`` to, or None when every copy
        is in ``avoid`` (failover exhausted the replica set)."""
        cands = [u for u in self.replicas(dataset) if u not in avoid]
        if not cands:
            return None
        # breaker-open routes are skipped while an alternative exists;
        # with every copy open, route anyway — the call-site gate
        # raises CircuitOpen cheaply and keeps the half-open probing
        live = [u for u in cands if self.live(u)] or cands
        if len(live) == 1:
            return live[0]
        a, b = self._rng.sample(live, 2)
        ra = self._rtt(a) or 0.0
        rb = self._rtt(b) or 0.0
        return a if ra <= rb else b


class ScanWorkerPool:
    """Coordinator-side round-robin scatter of ingest slice scans.

    The pipeline hands each planned slice to ``scan_blob``; failures
    (worker down, auth, scan error) raise WorkerError and the caller
    falls back to scanning locally — a missing worker degrades
    throughput, never correctness (reference analogue: a failed
    summariseSlice lambda's slice stays in the toUpdate set and is
    re-run). A worker that fails trips its circuit (one-strike breaker:
    open for ``cooldown_s``, then a half-open probe) so one wedged host
    cannot stall every slice for a full timeout each (the dead-worker
    exclusion the query-path scatter already has via discovery refresh).

    Scans are *hedged* (Dean & Barroso, The Tail at Scale): when the
    primary worker has not answered within the hedge delay — fixed, or
    adaptive at the p95 of recent scan RTTs — the same slice races on a
    second worker and the first response wins; the loser is abandoned
    (slice scans are idempotent reads, so duplicate execution only
    costs the loser's CPU). One slow host then bounds *its own* calls,
    not every slice routed to it.
    """

    #: adaptive hedging needs this many completed scans before the p95
    #: means anything; until then no hedge fires
    HEDGE_MIN_SAMPLES = 8
    #: adaptive hedge delay never drops below this (a sub-ms p95 would
    #: hedge every call and double cluster load for nothing)
    HEDGE_FLOOR_S = 0.05

    def __init__(
        self,
        worker_urls: list[str],
        *,
        token: str = "",
        timeout_s: float = 120.0,
        retries: int = 1,
        cooldown_s: float = 30.0,
        post_bytes=None,
        hedge_delay_s: float = 0.0,
        transport: PooledTransport | None = None,
        transport_config=None,
    ):
        if not worker_urls:
            raise ValueError("ScanWorkerPool needs at least one worker URL")
        self.worker_urls = list(worker_urls)
        self.token = token
        self.timeout_s = timeout_s
        self.retries = retries
        self.cooldown_s = cooldown_s
        self.hedge_delay_s = hedge_delay_s
        self._owns_transport = False
        if post_bytes is None:
            if transport is None:
                # built here -> owned here: close() releases the
                # sockets (a caller-passed transport stays caller-owned)
                transport = (
                    PooledTransport.from_config(transport_config)
                    if transport_config is not None
                    else PooledTransport()
                )
                self._owns_transport = True
            post_bytes = transport.post_bytes
        self.transport = transport
        self._post_bytes = post_bytes
        self._bytes_ok = bool(getattr(post_bytes, "accepts_bytes", False))
        self._next = 0
        # the round-4 ad-hoc _dead_until cooldown map, generalised: a
        # single failure opens the circuit for cooldown_s (scan slices
        # have a local fallback, so one strike is the right threshold),
        # then a half-open probe readmits the worker on success
        self.breaker = CircuitBreaker(
            failure_threshold=1, reset_timeout_s=cooldown_s
        )
        self._lock = threading.Lock()
        self._rtts: collections.deque = collections.deque(maxlen=128)
        self._hedges = 0
        self._hedge_wins = 0
        self._hedge_exec: ThreadPoolExecutor | None = None

    def close(self) -> None:
        """Release the hedge pool and any owned connection pool."""
        with self._lock:
            pool, self._hedge_exec = self._hedge_exec, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        if self._owns_transport and self.transport is not None:
            self.transport.close()

    def _pick(self) -> str:
        with self._lock:
            for _ in range(len(self.worker_urls)):
                url = self.worker_urls[self._next % len(self.worker_urls)]
                self._next += 1
                if self.breaker.allow(url):
                    return url
            # every worker's circuit is open: take the next anyway (it
            # may have recovered; correctness is covered by local
            # fallback)
            url = self.worker_urls[self._next % len(self.worker_urls)]
            self._next += 1
            return url

    def _pick_other(self, avoid: str) -> str | None:
        """A healthy worker other than ``avoid`` (the hedge target), or
        None when the fleet has no alternative."""
        with self._lock:
            for _ in range(len(self.worker_urls)):
                url = self.worker_urls[self._next % len(self.worker_urls)]
                self._next += 1
                if url != avoid and self.breaker.allow(url):
                    return url
        return None

    def _mark_dead(self, url: str) -> None:
        self.breaker.record_failure(url)

    def _auth_headers(self) -> dict | None:
        return (
            {"Authorization": f"Bearer {self.token}"} if self.token else None
        )

    # -- hedging ------------------------------------------------------------

    def _effective_hedge_delay(self) -> float | None:
        """Seconds to wait before racing a second worker, or None when
        hedging is off (disabled, single worker, or adaptive mode
        without enough RTT history yet)."""
        d = self.hedge_delay_s
        if (
            d is None
            or d < 0
            or len(self.worker_urls) < 2
            or not _hedging_enabled
        ):
            return None
        if d > 0:
            return d
        with self._lock:
            if len(self._rtts) < self.HEDGE_MIN_SAMPLES:
                return None
            s = sorted(self._rtts)
        return max(s[int(0.95 * (len(s) - 1))], self.HEDGE_FLOOR_S)

    def _hedge_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._hedge_exec is None:
                # sized for the ingest pipeline's concurrent run_slice
                # callers plus their hedges: a primary queued behind a
                # full pool must be rare (and is hedge-gated below)
                self._hedge_exec = ThreadPoolExecutor(
                    max_workers=max(8, 2 * len(self.worker_urls)),
                    thread_name_prefix="scan-hedge",
                )
            return self._hedge_exec

    def _note_hedge(self, primary: str, hedge: str) -> None:
        with self._lock:
            self._hedges += 1
        note_hedge()  # process-wide transport.hedges counter
        publish_event("scan.hedge", primary=primary, hedge=hedge)

    def stats(self) -> dict:
        with self._lock:
            return {
                "hedges": self._hedges,
                "hedge_wins": self._hedge_wins,
                "rtt_samples": len(self._rtts),
            }

    # -- the scan call ------------------------------------------------------

    def _scan_once(self, url: str, body, headers) -> tuple[int, bytes]:
        """One raw /scan exchange; successful RTTs feed the adaptive
        hedge delay."""
        t0 = time.perf_counter()
        status, out = self._post_bytes(
            f"{url}/scan", body, self.timeout_s, headers
        )
        if status == 200:
            with self._lock:
                self._rtts.append(time.perf_counter() - t0)
        return status, out

    def _settle(
        self, url: str, status: int, out: bytes, last
    ) -> tuple[bytes | None, Exception | None]:
        """Breaker bookkeeping for one answered scan: the blob on 200,
        else the WorkerError to remember."""
        if status == 200:
            self.breaker.record_success(url)
            return out, last
        err = WorkerError(f"{url}: http {status}: {out[:200]!r}")
        if status in (401, 403):
            self._mark_dead(url)
        else:
            # any other HTTP answer proves the worker is ALIVE
            # (the breaker tracks reachability, not scan success —
            # scan errors are handled by retry + local fallback);
            # recording an outcome also releases a half-open probe
            # so a 500-answering worker is not excluded forever
            self.breaker.record_success(url)
        return None, err

    def scan_blob(self, payload: SliceScanPayload) -> bytes:
        """One slice scan on some worker -> the shard's npz blob
        (columnar.dumps_index form), undecoded."""
        # serialize ONCE: a bytes-capable transport ships these bytes
        # verbatim; legacy injected transports still get the dict
        body = (
            payload.dumps().encode()
            if self._bytes_ok
            else json.loads(payload.dumps())
        )
        headers = self._auth_headers()
        last: Exception | None = None
        for _attempt in range(self.retries + 1):
            url = self._pick()
            delay = self._effective_hedge_delay()
            if delay is None:
                try:
                    status, out = self._scan_once(url, body, headers)
                except Exception as e:
                    last = WorkerError(f"{url}: {e}")
                    self._mark_dead(url)
                    continue
                got, last = self._settle(url, status, out, last)
                if got is not None:
                    return got
                continue
            got, last = self._scan_hedged(url, body, headers, delay, last)
            if got is not None:
                return got
        raise last

    def _scan_hedged(
        self, url: str, body, headers, delay: float, last
    ) -> tuple[bytes | None, Exception | None]:
        """One hedged attempt: primary on a pool thread; if it has not
        answered within ``delay``, race a second worker. First response
        wins; the loser keeps running and is ignored."""
        pool = self._hedge_pool()
        started = threading.Event()

        def primary():
            # stamps actual start: under a saturated pool the submit
            # may queue, and a queued primary must not trigger a hedge
            # (the delay would measure queue wait, not the worker, and
            # the hedge would pile more load onto the same full pool)
            started.set()
            return self._scan_once(url, body, headers)

        futs = {pool.submit(primary): url}
        done, _pending = futures_mod.wait(futs, timeout=delay)
        if not done and started.is_set():
            other = self._pick_other(url)
            if other is not None:
                self._note_hedge(url, other)
                futs[
                    pool.submit(self._scan_once, other, body, headers)
                ] = other
        pending = set(futs)
        while pending:
            done, pending = futures_mod.wait(
                pending, return_when=futures_mod.FIRST_COMPLETED
            )
            for f in done:
                u = futs[f]
                try:
                    status, out = f.result()
                except Exception as e:
                    last = WorkerError(f"{u}: {e}")
                    self._mark_dead(u)
                    continue
                got, last = self._settle(u, status, out, last)
                if got is not None:
                    if u != url:  # the hedge beat the primary
                        with self._lock:
                            self._hedge_wins += 1
                        publish_event(
                            "scan.hedge_won", winner=u, primary=url
                        )
                    return got, last
        return None, last

    def scan(self, payload: SliceScanPayload):
        """One slice scan on some worker -> VariantIndexShard."""
        from ..index.columnar import loads_index

        return loads_index(self.scan_blob(payload))

    #: reload is a tiny control message — never let it inherit the
    #: (possibly minutes-long) slice-scan timeout
    RELOAD_TIMEOUT_S = 10.0

    def reload_workers(self, *, post=None) -> int:
        """Best-effort concurrent POST /reload to every worker
        (shared-storage fleets re-pin freshly ingested shards without a
        restart); returns how many workers acknowledged. Concurrent with
        a short timeout so one wedged worker cannot stall ingest
        completion, and non-200 answers (404 = reload_fn not wired,
        500 = reload failed) are logged — a fleet silently serving stale
        shards is exactly the failure this call exists to prevent.

        Outcomes feed the scan breaker: any HTTP answer proves the
        worker reachable again (revival after a cooldown — e.g. an
        operator fixed a bad token), except 401/403 which re-confirm
        the auth failure; a transport error keeps/opens the circuit."""
        headers = self._auth_headers()
        if post is None:
            post = (
                self.transport.post_json
                if self.transport is not None
                else urllib_post
            )

        def one(url: str) -> bool:
            try:
                status, doc = post(
                    f"{url}/reload", {}, self.RELOAD_TIMEOUT_S, headers
                )
            except Exception:
                log.warning("worker %s reload failed", url, exc_info=True)
                self._mark_dead(url)
                return False
            if status in (401, 403):
                self._mark_dead(url)
            else:
                self.breaker.record_success(url)
            if status != 200:
                log.warning(
                    "worker %s reload answered http %s: %s",
                    url,
                    status,
                    doc,
                )
                return False
            return True

        with ThreadPoolExecutor(min(8, len(self.worker_urls))) as pool:
            ok = sum(pool.map(one, self.worker_urls))
        if ok < len(self.worker_urls):
            log.warning(
                "only %d/%d workers reloaded; the others serve stale "
                "shards until their next reload/restart",
                ok,
                len(self.worker_urls),
            )
        return ok


class WorkerError(RuntimeError):
    pass


class MeshDispatchTier:
    """Pod-local single-launch dispatch over a mesh-sharded fused index.

    The reference answers a k-dataset query with a 500-thread Lambda
    scatter and a DynamoDB counter fan-in; our HTTP tier mirrors that
    shape — k RTTs — even when the k shards are chips in one pod. This
    tier collapses that case: the local engine's shards stack into a
    :class:`parallel.mesh.MeshFusedIndex` (dataset groups sharded over
    ``jax.make_mesh`` with NamedSharding), and a query whose datasets
    all live on the mesh costs ONE compiled launch — boolean OR,
    count/allele psum, and the record-granularity hit-row gather all
    inside the program (Pallas async-remote-copy ring on TPU,
    all_gather elsewhere). Queries ride the local engine's
    MicroBatcher (``submit_many``), so coalescing across concurrent
    requests and the launch/fetch pipeline apply unchanged, and the
    batcher's deadline-bounded waits keep the resilience contract.

    The tier is an *optimisation* the :class:`DistributedEngine`
    consults per query: dataset groups it cannot resolve (not built
    yet, stale after an ingest, plane-reading granularities, fewer than
    ``min_shards`` targets) keep the existing local/pooled-HTTP paths,
    and a mesh-path failure falls back to the scatter once and trips
    the ``mesh.fallbacks`` counter.
    """

    #: LEGACY warm tiers, kept for back-compat introspection only:
    #: :meth:`warmup` now pre-compiles every serving rung of the
    #: process TierLadder (``kernel.active_ladder().mesh_warm_rungs``
    #: — ISSUE 17), so the warm set and the slice-tier padding read
    #: the same single source and a ladder edit cannot silently
    #: reintroduce mid-request compiles (the warmup-ladder lint in
    #: tools/check_launch_recording.py asserts the parity)
    WARM_TIERS = (8, 64)

    def __init__(
        self,
        engine,
        *,
        min_shards: int = 2,
        axis: str = "d",
        devices=None,
    ):
        self.engine = engine
        self.min_shards = max(1, int(min_shards))
        self.axis = axis
        self._devices = devices
        self._lock = threading.Lock()
        # (MeshFusedIndex, {key: sid}, {key: shard}, {ds: [keys]}, fp,
        #  {key: plane_index})
        self._state: tuple | None = None
        self._building = False
        # fingerprint a build pass declined (too few shards / build
        # failure): don't spawn a rebuild thread per query for an
        # index set that cannot produce a tier
        self._skip_fp: str | None = None
        self._dispatches = 0
        self._fallbacks = 0
        self._gather_rows = 0
        # why queries fell off the tier, by reason — the operator's
        # answer to "the mesh dispatch rate dropped, what happened?"
        # (mesh.refusals{reason} series): planes = plane-reading shape
        # the stack cannot serve (no planes stacked / wildcard-ref
        # host semantics), stale = built but a publish outran it,
        # min_shards = too few local targets to beat per-shard
        # dispatch, unbuilt = no stack yet (incl. <2 devices and
        # declined builds)
        self._refusals: dict[str, int] = {}
        # close() raced against an in-flight background build: the
        # build re-checks this before publishing/registering so a dead
        # tier can never leave a phantom plane-byte reservation (or a
        # resurrected state) behind
        self._tier_closed = False
        # wall time the serving state was published (stack age on the
        # /device/status stacks surface)
        self._built_at: float | None = None

    # -- availability / build ----------------------------------------------

    def available(self) -> bool:
        """>=2 devices visible: a 1-device 'pod' would only re-spell the
        fused single-device stack, which the engine already serves."""
        try:
            import jax

            devs = self._devices if self._devices is not None else jax.devices()
        except Exception:
            return False
        return len(devs) >= 2

    def _snapshot(self):
        """(keys, shards, planes_of) the stack would build from, via
        the engine's locked snapshot (never iterating ``_indexes``
        mid-ingest). ``planes_of`` maps keys to the per-dataset device
        plane index of the SAME publish — materialisation's host/
        device fallback for shapes the stacked planes cannot answer
        exactly."""
        snap = getattr(self.engine, "index_snapshot", None)
        if snap is not None:
            triples = snap()
            return (
                [k for k, _s, _p in triples],
                [s for _k, s, _p in triples],
                {k: p for k, _s, p in triples},
            )
        snap = getattr(self.engine, "shard_snapshot", None)
        if snap is None:
            return [], [], {}
        pairs = snap()
        return (
            [k for k, _s in pairs],
            [s for _k, s in pairs],
            {},
        )

    def _base_fp(self) -> str:
        """The BASE-shard fingerprint: stable across delta publishes
        (only compaction/re-ingest bumps it), so a delta publish does
        NOT cold-start this tier — the stack keeps serving base rows
        and the delta tail is served per-shard in :meth:`search`.
        Engines without a delta registry fall back to the full
        fingerprint (identical staleness behaviour to before)."""
        base = getattr(self.engine, "base_fingerprint", None)
        if base is not None:
            return base()
        return self.engine.index_fingerprint()

    def _ready(self, wait: bool = False):
        """The current state, or None while unbuilt/stale (the caller
        then keeps the scatter paths — freshness beats the mesh win).
        A stale state arms a BACKGROUND rebuild; ``wait=True`` (warmup)
        builds inline on the caller's clock."""
        if not self.available():
            return None
        fp = self._base_fp()
        while True:
            with self._lock:
                if self._tier_closed:
                    return None
                state = self._state
                if state is not None and state[4] == fp:
                    return state
                if self._skip_fp == fp and not wait:
                    return None
                if not self._building:
                    self._building = True
                    break
                if not wait:
                    return None
            # wait=True with a background build in flight: JOIN it
            # instead of racing a duplicate full stack build (transient
            # 2x device memory, doubled journal events), then re-check
            time.sleep(0.05)
        if wait:
            return self._build(fp)
        threading.Thread(
            target=self._build, args=(fp,), name="mesh-tier-build",
            daemon=True,
        ).start()
        return None

    def _build(self, fp: str):
        try:
            from .mesh import MeshFusedIndex, make_mesh

            keys, shards, planes_of = self._snapshot()
            if len(keys) < self.min_shards:
                with self._lock:
                    self._skip_fp = fp
                return None
            mesh = make_mesh(devices=self._devices, axis=self.axis)
            eng_cfg = getattr(self.engine.config, "engine", None)
            reg = getattr(self.engine, "register_plane_bytes", None)
            # the PREVIOUS stack's registered bytes: it keeps serving
            # until the new state publishes, so it stays accounted
            # through the build (and is what a failed build restores)
            with self._lock:
                prev_bytes = (
                    getattr(self._state[0], "plane_bytes_device", 0)
                    if self._state is not None
                    else 0
                )
            # stack the genotype planes with their datasets when the
            # knob allows, every shard has them, and the per-device
            # slice fits the HBM headroom left by the resident
            # per-dataset planes (the engine's own mesh gate, applied
            # through the index's one-source-of-truth byte math)
            with_planes = getattr(eng_cfg, "mesh_planes", True) and all(
                s.gt_bits is not None for s in shards
            )
            if with_planes:
                per_dev = MeshFusedIndex.plane_bytes_per_device(
                    shards, n_dev=int(mesh.devices.size)
                )
                budget = (
                    getattr(eng_cfg, "plane_hbm_budget_gb", 11.0) * 1e9
                )
                # ATOMIC check-and-reserve BEFORE the multi-second
                # stack build (the engine's own upload-gate
                # discipline): the headroom test and the ledger write
                # happen under one lock hold, and the reservation
                # covers the old still-serving stack PLUS the build in
                # flight — a per-dataset plane upload admitted
                # mid-build sees these bytes, so the two gates cannot
                # both pass on the same headroom
                reserve = getattr(
                    self.engine, "try_reserve_plane_bytes", None
                )
                if reserve is not None:
                    with_planes = reserve(
                        self, prev_bytes + per_dev, budget
                    )
                else:
                    resident = getattr(
                        self.engine, "plane_hbm_resident", lambda: 0
                    )()
                    with_planes = per_dev + resident <= budget
                if not with_planes:
                    log.info(
                        "mesh tier planes skipped: %d B/device does "
                        "not fit the %.1f GB plane budget headroom",
                        per_dev,
                        budget / 1e9,
                    )
            index = MeshFusedIndex(
                shards,
                mesh,
                axis=self.axis,
                with_planes=with_planes,
                slice_batch=getattr(eng_cfg, "mesh_slice", None),
                owner_outputs=getattr(
                    eng_cfg, "mesh_owner_outputs", None
                ),
            )
            sid_of = {k: i for i, k in enumerate(keys)}
            shard_of = dict(zip(keys, shards))
            keys_by_ds: dict[str, list] = {}
            for k in keys:
                keys_by_ds.setdefault(k[0], []).append(k)
            state = (index, sid_of, shard_of, keys_by_ds, fp, planes_of)
            with self._lock:
                if self._tier_closed:
                    # close() won the race: discard the build outright
                    if reg is not None:
                        reg(self, 0)
                    return None
                self._state = state
                self._built_at = time.time()
            # settle the bidirectional budget accounting on the NEW
            # stack alone (keyed on the tier, so this replaces the
            # build-window reservation — and a plane-less rebuild
            # releases the old stack's bytes); later per-dataset
            # uploads then cannot overcommit the device by the stack
            if reg is not None:
                reg(self, index.plane_bytes_device)
                with self._lock:
                    raced_close = self._tier_closed
                if raced_close:
                    # close() landed between the publish above and the
                    # settle: its release must win, not our registration
                    reg(self, 0)
                    return None
            publish_event(
                "mesh.tier_ready",
                shards=len(keys),
                devices=index.n_dev,
                planes=index.has_planes,
            )
            log.info(
                "mesh dispatch tier ready: %d shards over %d devices"
                " (planes %s)",
                len(keys),
                index.n_dev,
                "stacked" if index.has_planes else "off",
            )
            return state
        except Exception:
            log.exception("mesh dispatch tier build failed; scatter serves")
            with self._lock:
                self._skip_fp = fp
            # roll the build-window plane reservation back to whatever
            # stack is actually still serving (re-derived from state, so
            # this is correct wherever in the build the failure landed)
            reg = getattr(self.engine, "register_plane_bytes", None)
            if reg is not None:
                with self._lock:
                    prev = (
                        getattr(self._state[0], "plane_bytes_device", 0)
                        if self._state is not None
                        else 0
                    )
                reg(self, prev)
            return None
        finally:
            with self._lock:
                self._building = False

    def close(self) -> None:
        """Drop the tier's state and release its plane-stack bytes from
        the engine's budget ledger — a discarded tier must not keep the
        ledger over-counting (and the ledger's strong reference would
        otherwise pin the stack's device arrays alive). The flag is set
        BEFORE the release so an in-flight background build observes it
        at its publish/settle re-checks and discards itself."""
        with self._lock:
            self._tier_closed = True
            self._state = None
        reg = getattr(self.engine, "register_plane_bytes", None)
        if reg is not None:
            reg(self, 0)

    def warmup(self) -> int:
        """Build inline and pre-compile the tier's batch-tier programs;
        returns the program count (0 when the tier cannot engage).
        Runs inside a flight-recorder warmup phase so the compile
        tracker stamps these shapes as expected (ISSUE 14)."""
        with device_warmup_phase():
            return self._warmup()

    def _warmup(self) -> int:
        state = self._ready(wait=True)
        if state is None:
            return 0
        from ..ops.kernel import QuerySpec, active_ladder, encode_queries

        index = state[0]
        eng = self.engine.config.engine
        n = 0
        spec = QuerySpec("1", 1, 1, 1, 2)
        # the sliced layout keys programs on the PER-DEVICE slice tier:
        # a single-hot-shard batch of t slices to C=t, while the common
        # pod fan-out (<= one query per device) slices to C=1 (the
        # spread batch) — warm EVERY serving rung of the process
        # ladder so no coalesced burst pays a mid-request shard_map
        # compile (rungs past MESH_WARM_CAP are bulk shapes outside
        # the serving path, same exposure as the legacy ladder)
        spread = [
            g * index.d_local
            for g in range(index.n_dev)
            if g * index.d_local < index.n_shards
        ]
        batches = [
            [0] * t for t in active_ladder().mesh_warm_rungs() if t > 1
        ] + [spread]
        for sids in batches:
            index.run_mesh_queries(
                encode_queries([spec] * len(sids), shard_ids=sids),
                window_cap=eng.window_cap,
                record_cap=eng.record_cap,
            )
            n += 1
        if index.has_planes:
            # the plane program at the SAME shapes as the match warm —
            # a selected-samples burst coalescing to any warmed tier
            # must not pay a mid-request shard_map compile any more
            # than a boolean one would
            for sids in batches:
                index.run_mesh_queries(
                    encode_queries([spec] * len(sids), shard_ids=sids),
                    window_cap=eng.window_cap,
                    record_cap=eng.record_cap,
                    sample_masks=np.zeros(
                        (len(sids), index.plane_words), np.uint32
                    ),
                    mask_counts=np.zeros(len(sids), np.bool_),
                )
                n += 1
        return n

    # -- per-query consult ---------------------------------------------------

    def _note_refusal(self, reason: str) -> None:
        with self._lock:
            self._refusals[reason] = self._refusals.get(reason, 0) + 1

    def _is_plane_query(self, payload) -> bool:
        """Plane-reading response shape — the predicate IS the
        engine's (_wants_planes), not a copy that could drift."""
        wants_planes = getattr(self.engine, "_wants_planes", None)
        return payload.selected_samples_only or (
            wants_planes is not None and wants_planes(payload)
        )

    def resolve(self, dataset_ids, payload) -> set:
        """The subset of ``dataset_ids`` this tier will serve for this
        query — empty when the tier should not engage (unbuilt/stale
        stack, a plane-reading shape the stack cannot answer, below
        ``min_shards``). Every refusal is reason-labeled into the
        ``mesh.refusals`` series so operators can see why traffic
        falls off the tier."""
        if not dataset_ids:
            return set()
        state = self._ready()
        if state is None:
            with self._lock:
                built = self._state is not None
            if built:
                self._note_refusal("stale")
                plan_stage("mesh", decision="refused", reason="stale")
            else:
                self._note_refusal("unbuilt")
                plan_stage("mesh", decision="refused", reason="unbuilt")
            return set()
        index = state[0]
        if self._is_plane_query(payload):
            # plane shapes ride the single launch when the stack
            # carries the genotype planes AND device row-matching is
            # exact for this query (an N-wildcard ref needs host regex
            # semantics — the engine's own predicate decides, payload
            # doubles as the spec arg since only reference_bases is
            # read); otherwise they keep the per-dataset engine paths
            ref_ok = getattr(self.engine, "_device_ref_ok", None)
            if not index.has_planes or (
                ref_ok is not None and not ref_ok(payload, payload)
            ):
                self._note_refusal("planes")
                ledger = getattr(self.engine, "plane_ledger", None)
                headroom = (
                    ledger().get("headroomBytes")
                    if callable(ledger)
                    else None
                )
                plan_stage(
                    "mesh",
                    decision="refused",
                    reason="planes",
                    has_planes=bool(index.has_planes),
                    headroom_bytes=headroom,
                )
                return set()
        _index, _sid_of, _shard_of, keys_by_ds, _fp = state[:5]
        covered = {ds for ds in dataset_ids if ds in keys_by_ds}
        n_targets = sum(len(keys_by_ds[ds]) for ds in covered)
        if n_targets < self.min_shards:
            self._note_refusal("min_shards")
            plan_stage(
                "mesh",
                decision="refused",
                reason="min_shards",
                targets=n_targets,
                min_shards=self.min_shards,
            )
            return set()
        return covered

    def search(
        self, payload: VariantQueryPayload, dataset_ids
    ) -> list[VariantSearchResponse]:
        """Answer ``dataset_ids`` (a :meth:`resolve` result) with one
        mesh launch. Raises on any failure — the caller owns the
        fall-back-once-to-scatter contract."""
        from ..engine import host_match_rows, materialize_response
        from ..ops.kernel import QuerySpec, encode_queries

        fault_point("mesh.dispatch")
        deadline = current_deadline()
        deadline.check("mesh.dispatch")
        with self._lock:
            state = self._state
        if state is None:
            raise WorkerError("mesh tier state gone")
        index, sid_of, shard_of, keys_by_ds, _fp = state[:5]
        planes_of = state[5] if len(state) > 5 else {}
        plane_q = self._is_plane_query(payload)
        spec_base = QuerySpec(
            chrom=payload.reference_name,
            start_min=payload.start_min,
            start_max=payload.start_max,
            end_min=payload.end_min,
            end_max=payload.end_max,
            reference_bases=payload.reference_bases,
            alternate_bases=payload.alternate_bases,
            variant_type=payload.variant_type,
            variant_min_length=payload.variant_min_length,
            variant_max_length=payload.variant_max_length,
        )
        targets = []
        for ds in sorted(dataset_ids):
            for key in keys_by_ds.get(ds, ()):
                shard = shard_of[key]
                native = shard.meta.get("chrom_native", {}).get(
                    payload.reference_name
                )
                if native is None:
                    continue  # no matching chromosome in this VCF
                targets.append((key, shard, native, sid_of[key]))
        # the delta tail: shards published since the stack was built
        # (base fingerprint unchanged, so the stack is NOT stale — the
        # tail just isn't in it). Deltas are small and host-served, so
        # they ride per-shard host matching next to the single mesh
        # launch instead of cold-starting the tier per ingest.
        delta_targets = []
        indexes_for = getattr(self.engine, "indexes_for", None)
        if indexes_for is not None:
            for ds, vcf, (shard, _di, pl) in indexes_for(
                sorted(dataset_ids)
            ):
                if (ds, vcf) in sid_of:
                    continue  # base rows: the mesh launch serves them
                native = shard.meta.get("chrom_native", {}).get(
                    payload.reference_name
                )
                if native is None:
                    continue
                delta_targets.append(((ds, vcf), shard, native, pl))
        if not targets and not delta_targets:
            return []
        eng = self.engine.config.engine
        responses = []
        gathered = 0

        def _sel_idx(shard, ds):
            # the engine's own name->index resolution, per shard
            if not payload.selected_samples_only:
                return None
            return self.engine._selected_idx(shard, payload, ds)

        if targets:
            specs = [spec_base] * len(targets)
            sids = [sid for _k, _s, _n, sid in targets]
            sel_idx_of: dict = {}
            masks = None
            mask_counts = None
            if plane_q:
                # per-query sample masks, sharded WITH the batch: the
                # owning device reduces each query's matched rows under
                # ITS mask inside the same single launch. Selected-
                # samples queries restrict to the named samples (and
                # switch to genotype-derived counting when the count
                # planes are stacked); extraction shapes take the
                # full-cohort mask and keep the INFO-column counts —
                # materialize only consumes their or_words.
                from ..ops.plane_kernel import sample_mask_words

                W = index.plane_words
                masks = np.zeros((len(targets), W), np.uint32)
                mask_counts = np.zeros(len(targets), np.bool_)
                for i, (key, shard, _native, _sid) in enumerate(targets):
                    if payload.selected_samples_only:
                        sel = _sel_idx(shard, key[0])
                        sel_idx_of[key] = sel
                        masks[i] = sample_mask_words(sel, W)
                        mask_counts[i] = index.has_count_planes
                    else:
                        masks[i] = 0xFFFFFFFF
            batcher = getattr(self.engine, "batcher", None)
            if batcher is not None:
                # the serving micro-batcher coalesces concurrent pod
                # queries into the same launch and bounds the wait by
                # the request deadline (the mesh wait IS deadline-scoped)
                res = batcher.submit_many(
                    index,
                    specs,
                    shard_ids=sids,
                    window_cap=eng.window_cap,
                    record_cap=eng.record_cap,
                    sample_masks=masks,
                    mask_counts=mask_counts,
                )
            else:
                fault_point("kernel.launch")
                res = index.run_mesh_queries(
                    encode_queries(specs, shard_ids=sids),
                    window_cap=eng.window_cap,
                    record_cap=eng.record_cap,
                    sample_masks=masks,
                    mask_counts=mask_counts,
                )
            for i, (key, shard, native, _sid) in enumerate(targets):
                sel_idx = sel_idx_of.get(key)
                fused = None
                if res.overflow[i] or res.n_matched[i] > eng.record_cap:
                    # window/record overflow: uncapped host matcher,
                    # the same contract as every device kernel path
                    rows = host_match_rows(
                        shard,
                        spec_base,
                        ref_wildcard=payload.selected_samples_only,
                    )
                else:
                    keep = res.rows[i] >= 0
                    rows = res.rows[i][keep]
                    gathered += int(rows.size)
                    # the fused triple is only exact for this shard
                    # when its count-plane availability matches the
                    # stack-wide static (a shard WITH count planes in
                    # a stack that ran has_counts=False was counted
                    # full-cohort on device) — extraction shapes only
                    # read or_words, which is count-plane-invariant
                    if (
                        plane_q
                        and res.or_words is not None
                        and (
                            not payload.selected_samples_only
                            or index.has_count_planes
                            or not shard.has_count_planes
                        )
                    ):
                        # or_words come back stack-wide (plane_words =
                        # the widest shard); materialise in this
                        # shard's own width (tail words are zero by
                        # construction)
                        w_shard = shard.gt_bits.shape[1]
                        fused = (
                            res.pc_call[i][keep],
                            res.pc_tok[i][keep],
                            np.asarray(res.or_words[i])
                            .view(np.uint32)[:w_shard],
                        )
                responses.append(
                    materialize_response(
                        shard,
                        rows,
                        payload,
                        chrom_label=native,
                        dataset_id=key[0],
                        vcf_location=key[1],
                        selected_idx=sel_idx,
                        plane_index=(
                            planes_of.get(key) if plane_q else None
                        ),
                        fused=fused,
                    )
                )
        # the delta tail: the engine's L0 mini-index is consulted
        # FIRST — a past-threshold tail rides one batched fused_l0
        # launch and only the residue it does not cover (or overflow,
        # marked None) host-scans. l0_pre_rows owns the delta_shards
        # charging rule (only host-walked shards charge), so this
        # tier and the engine's own tail leg cannot diverge on it.
        l0_rows: dict = {}
        l0_fn = getattr(self.engine, "l0_pre_rows", None)
        if delta_targets and l0_fn is not None:
            l0_rows = l0_fn(
                [(key, shard) for key, shard, _n, _p in delta_targets],
                spec_base,
                payload,
            )
        elif delta_targets:
            # engines without an L0 registry: every tail shard below
            # host-walks and charges
            charge_cost(delta_shards=len(delta_targets))
        # how much of the tail rode the device launch vs host-walked:
        # with per-key L0 blocks (ISSUE 20) a key mid-restack simply
        # falls out of coverage for a beat, and this split is the
        # per-request signal that shows it
        l0_covered = sum(1 for v in l0_rows.values() if v is not None)
        for key, shard, native, pl in delta_targets:
            rows = l0_rows.get(key)
            if rows is None:
                rows = host_match_rows(
                    shard,
                    spec_base,
                    ref_wildcard=payload.selected_samples_only,
                )
            responses.append(
                materialize_response(
                    shard,
                    rows,
                    payload,
                    chrom_label=native,
                    dataset_id=key[0],
                    vcf_location=key[1],
                    selected_idx=_sel_idx(shard, key[0]),
                    plane_index=pl if plane_q else None,
                )
            )
        with self._lock:
            self._dispatches += 1
            self._gather_rows += gathered
        # the dispatch_tier note belongs to DistributedEngine.search —
        # it knows whether this query was mesh-only or "mixed" with a
        # scatter leg; writing it here would overwrite that label
        annotate(
            mesh_shards=len(targets),
            mesh_delta_tail=len(delta_targets),
            mesh_tail_l0=l0_covered,
            mesh_planes=plane_q,
        )
        plan_stage(
            "mesh",
            decision="served",
            shards=len(targets),
            delta_tail=len(delta_targets),
            tail_l0=l0_covered,
            planes=plane_q,
        )
        return responses

    def note_fallback(self) -> None:
        with self._lock:
            self._fallbacks += 1

    def stats(self) -> dict:
        with self._lock:
            state = self._state
            built_at = self._built_at
            out = {
                "dispatches": self._dispatches,
                "fallbacks": self._fallbacks,
                "gather_rows": self._gather_rows,
                "refusals": dict(self._refusals),
            }
        out["ready"] = state is not None
        out["shards"] = len(state[1]) if state is not None else 0
        out["devices"] = state[0].n_dev if state is not None else 0
        out["planes"] = bool(state[0].has_planes) if state else False
        # stack identity + age (the /device/status stacks surface):
        # which publish this stack serves and how long it has stood
        out["fingerprint"] = state[4] if state is not None else ""
        out["ageS"] = (
            round(time.time() - built_at, 1)
            if state is not None and built_at is not None
            else None
        )
        return out


class FleetView:
    """Fleet-wide telemetry federation (ISSUE 12): the coordinator's
    collected view of every worker's ``/ops/digest``, served at
    ``/fleet/status``. Digests are polled lazily at a bounded cadence —
    a ``snapshot()`` older than ``interval_s`` refreshes inline, so an
    unqueried fleet pays nothing and a dashboard polling every second
    still only touches workers once per interval (the low-cadence
    poller the rediscovery loop's shape suggested, without another
    standing thread). Polls ride the engine's authenticated transport:
    the digest exchange lives inside the existing worker-token
    boundary, widening nothing.

    The fleet-level ``diagnosis`` names the **stalest replica** (most
    fingerprint-losing dataset copies by the freshness heuristic, else
    the deepest standing delta tail), the **hottest worker** (highest
    median RTT from the router's own measurements), the **divergent
    datasets** (replicas advertising different copies), and the
    unreachable workers — the federated signal layer ROADMAP items 4
    (quota convergence) and 5 (live migration) ride on.
    """

    #: per-digest GET budget: a digest is a small control message and
    #: must never inherit the minutes-long search timeout
    DIGEST_TIMEOUT_S = 5.0

    def __init__(self, engine, *, interval_s: float = 10.0,
                 clock=time.monotonic):
        self.engine = engine
        self.interval_s = max(0.5, float(interval_s))
        self._clock = clock
        self._lock = threading.Lock()
        # single-flight refresh: concurrent stale snapshot() calls must
        # not each run a full worker sweep (non-blocking acquire — the
        # loser serves the cached view the winner is refreshing)
        self._poll_lock = threading.Lock()
        # url -> {"digest": dict|None, "error": str|None, "tMono": t}
        self._digests: dict[str, dict] = {}
        self._polls = 0
        self._last_poll: float | None = None

    def _poll_one(self, url: str) -> tuple[str, dict, bool]:
        t = self._clock()
        try:
            status, doc = self.engine._get_auth(
                f"{url}/ops/digest",
                min(self.DIGEST_TIMEOUT_S, self.engine.timeout_s),
            )
        except Exception as e:
            return (
                url,
                {
                    "digest": None,
                    "error": f"{type(e).__name__}: {e}",
                    "tMono": t,
                },
                False,
            )
        if status == 200 and isinstance(doc, dict):
            return url, {"digest": doc, "error": None, "tMono": t}, True
        return (
            url,
            {"digest": None, "error": f"http {status}", "tMono": t},
            False,
        )

    def poll(self) -> int:
        """One collection pass over every configured worker; returns
        how many answered. Workers are swept CONCURRENTLY so the pass
        is bounded by one digest timeout, not N of them — /fleet/status
        bypasses admission and deadlines, so an inline refresh stalling
        ~5 s per dead worker sequentially would be exactly the probe
        hang the bypass exists to avoid. Failures are recorded per
        worker (an unreachable worker is a fleet-status FINDING, not an
        error)."""
        urls = list(self.engine.worker_urls)
        ok = 0
        if urls:
            with ThreadPoolExecutor(
                min(8, len(urls)), thread_name_prefix="fleet-digest"
            ) as pool:
                results = list(pool.map(self._poll_one, urls))
            with self._lock:
                for url, entry, answered in results:
                    self._digests[url] = entry
                    ok += int(answered)
        with self._lock:
            self._polls += 1
            self._last_poll = self._clock()
            for u in list(self._digests):
                if u not in urls:  # decommissioned mid-flight
                    del self._digests[u]
        return ok

    def _divergence(self, rows: dict) -> tuple[dict, dict]:
        """({dataset: {url: fp}} for divergent datasets,
        {url: stale-copy count}) over the cached digests."""
        by_ds: dict[str, dict[str, str]] = {}
        for url, e in rows.items():
            d = e.get("digest")
            if not d:
                continue
            for ds, fp in (d.get("datasetFingerprints") or {}).items():
                by_ds.setdefault(ds, {})[url] = fp
        divergent: dict[str, dict[str, str]] = {}
        stale_counts: dict[str, int] = {}
        for ds, fps in sorted(by_ds.items()):
            if len(set(fps.values())) <= 1:
                continue
            divergent[ds] = dict(sorted(fps.items()))
            win = max(
                fps.values(),
                key=lambda fp: (_fingerprint_freshness(fp), fp),
            )
            for url, fp in fps.items():
                if fp != win:
                    stale_counts[url] = stale_counts.get(url, 0) + 1
        return divergent, stale_counts

    def stats(self) -> dict:
        """The ``fleet.*`` metric values — cached state only, a
        /metrics scrape must never trigger worker network IO."""
        with self._lock:
            rows = {u: dict(e) for u, e in self._digests.items()}
            polls = self._polls
        divergent, _stale = self._divergence(rows)
        return {
            "polls": polls,
            "reachable": sum(
                1 for e in rows.values() if e.get("digest") is not None
            ),
            "divergent": len(divergent),
        }

    def snapshot(self) -> dict:
        """The ``/fleet/status`` document (refreshes inline when the
        cached digests are older than ``interval_s``)."""
        with self._lock:
            last = self._last_poll
        if last is None or self._clock() - last >= self.interval_s:
            # single-flight: only one caller refreshes; a concurrent
            # snapshot serves the cached view instead of doubling the
            # worker sweep
            if self._poll_lock.acquire(blocking=False):
                try:
                    self.poll()
                except Exception:  # a broken poll must not 500 status
                    log.exception("fleet digest poll failed")
                finally:
                    self._poll_lock.release()
        with self._lock:
            rows = {u: dict(e) for u, e in self._digests.items()}
            polls = self._polls
            last = self._last_poll
        now = self._clock()
        divergent, stale_counts = self._divergence(rows)
        workers: dict[str, dict] = {}
        tail_rows: dict[str, int] = {}
        for url in sorted(rows):
            e = rows[url]
            d = e.get("digest")
            w: dict = {
                "reachable": d is not None,
                "ageS": round(now - e["tMono"], 1),
                "medianRttMs": self.engine.router.median_rtt_ms(url),
                "staleDatasets": stale_counts.get(url, 0),
            }
            if d is not None:
                w["digest"] = d
                w["deltaTailRows"] = sum(
                    int(t.get("rows", 0))
                    for t in (d.get("deltaTails") or {}).values()
                )
                tail_rows[url] = w["deltaTailRows"]
            else:
                w["error"] = e.get("error")
            workers[url] = w
        # stalest replica: fingerprint-divergence losers first (the
        # replica serving outdated copies), else the deepest standing
        # delta tail (furthest behind its own compaction)
        stalest = None
        if stale_counts:
            stalest = max(
                sorted(stale_counts), key=lambda u: stale_counts[u]
            )
        elif any(tail_rows.values()):
            stalest = max(sorted(tail_rows), key=lambda u: tail_rows[u])
        rtts = {
            u: w["medianRttMs"]
            for u, w in workers.items()
            if w.get("medianRttMs") is not None
        }
        # worst-compiling replica: the digest's midRequestCompiles field
        # (a replica silently recompiling per request burns its latency
        # budget on XLA, not on serving — name it fleet-wide)
        compiles = {
            u: int((w.get("digest") or {}).get("midRequestCompiles", 0))
            for u, w in workers.items()
        }
        worst_compiling = None
        if any(compiles.values()):
            worst_compiling = max(
                sorted(compiles), key=lambda u: compiles[u]
            )
        # live migrations ride the digest (ISSUE 16): phase + ages per
        # in-flight migration, and the diagnosis names a STUCK one
        # (phase age beyond the controller's stuck bound — the
        # stalest-replica pattern applied to protocol progress)
        migrations: list[dict] = []
        stuck = None
        ctl = getattr(self.engine, "migrations", None)
        if ctl is not None:
            migrations = ctl.status()
            stuck = ctl.stuck()
        return {
            "intervalS": self.interval_s,
            "polls": polls,
            "lastPollAgeS": (
                None if last is None else round(now - last, 1)
            ),
            "workers": workers,
            "migrations": migrations,
            "diagnosis": {
                "stalestReplica": stalest,
                "hottestWorker": (
                    max(sorted(rtts), key=lambda u: rtts[u])
                    if rtts
                    else None
                ),
                "divergentDatasets": divergent,
                "unreachableWorkers": sorted(
                    u for u, w in workers.items() if not w["reachable"]
                ),
                "stuckMigration": stuck,
                "worstCompilingReplica": worst_compiling,
            },
        }


class DistributedEngine:
    """Coordinator: VariantEngine interface over remote workers (+ an
    optional local engine for locally-resident shards).

    Dataset routing is discovered from each worker's ``/datasets`` and
    refreshed on demand. A dataset served by several workers keeps its
    FULL replica list (fingerprint-checked — only identical copies are
    grouped): a :class:`ReplicaRouter` picks among live replicas by
    power-of-two-choices over recent RTTs, ``search`` fails over to the
    next replica when a worker errors or its circuit is open, and slow
    primaries are hedged by a second replica after the hedge delay
    (``transport.replica_hedge`` / ``hedge_delay_s``). When no replica
    of a dataset is reachable the search degrades to partial results
    (``resilience.partial_results``) instead of failing outright, and a
    background rediscovery loop heals routes without a manual reload —
    the fault tolerance the reference got for free from Lambda invoke
    retries landing on a fresh instance.
    """

    #: background rediscovery cadence once a route failure armed the
    #: healing loop (it exits when every configured worker answers)
    REDISCOVERY_INTERVAL_S = 2.0

    def __init__(
        self,
        worker_urls: list[str],
        *,
        local=None,
        config=None,
        timeout_s: float = 600.0,
        retries: int = 2,
        max_threads: int = 64,
        post=None,
        get=None,
        token: str = "",
        breaker: CircuitBreaker | None = None,
        transport: PooledTransport | None = None,
    ):
        from ..config import BeaconConfig, TransportConfig

        # full VariantEngine interface: the API layer reads engine.config
        self.config = config or (
            local.config if local is not None else BeaconConfig()
        )
        self.worker_urls = list(worker_urls)
        self.local = local
        self.timeout_s = timeout_s
        self.retries = retries
        self.max_threads = max_threads
        tcfg = getattr(self.config, "transport", None) or TransportConfig()
        self.transport_config = tcfg
        # default data plane: the pooled keep-alive transport (one
        # instance per engine — connections die with close()); injected
        # post/get callables take precedence (test seams, gRPC swaps)
        self._owns_transport = False
        if (post is None or get is None) and transport is None:
            transport = PooledTransport.from_config(tcfg)
            self._owns_transport = True
        self.transport = transport
        self._post = post if post is not None else transport.post_json
        self._get = get if get is not None else transport.get_json
        # a bytes-capable transport receives the payload's serialized
        # JSON verbatim (no dict round-trip on the hot path); legacy
        # injected transports keep their dict contract
        self._post_bytes_ok = bool(
            getattr(self._post, "accepts_bytes", False)
        )
        self._short_circuits = 0
        self._sc_lock = threading.Lock()
        # does the (possibly injected) transport accept a 4th headers
        # arg? Decided once here so the per-call path never plays
        # TypeError roulette with a swapped gRPC/DCN transport
        import inspect

        try:
            params = inspect.signature(post).parameters
            self._post_takes_headers = len(params) >= 4 or any(
                p.kind == inspect.Parameter.VAR_POSITIONAL
                or p.kind == inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):  # builtins/C callables
            self._post_takes_headers = True
        # self.config is always resolved by now (explicit > local's >
        # default), so the token fallback must read it — reading the raw
        # `config` param would silently drop a token that arrived via
        # local.config.auth.worker_token
        self._token = token or self.config.auth.worker_token
        # per-worker circuit breaker (reference analogue: the invoke
        # retry/backoff AWS applies per lambda): consecutive /search
        # failures open the route, calls fast-fail instead of eating the
        # full timeout each, and a half-open probe readmits the worker.
        # Injectable for tests (fake clock drives transitions).
        res = getattr(self.config, "resilience", None)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=getattr(
                res, "breaker_failure_threshold", 5
            ),
            reset_timeout_s=getattr(res, "breaker_reset_s", 30.0),
            half_open_probes=getattr(res, "breaker_half_open_probes", 1),
        )
        self._routes_lock = threading.Lock()
        self._discovered = False  # a discovery pass has published
        self._fingerprints: dict[str, str] = {}
        # per-worker last-known /datasets contribution + who answered
        # the most recent pass (the rediscovery loop's healed signal —
        # retained fingerprints must not masquerade as reachability)
        self._last_seen: dict[str, list[tuple[str, str]]] = {}
        self._reachable: set[str] = set()
        self._retention_warned: set[str] = set()
        # monotonic stamp of the last completed discovery pass — the
        # /debug/status replica-table staleness signal
        self._last_publish_mono: float | None = None
        # replica selection (p2c over RTTs, breaker-aware) owns the
        # dataset -> replica-urls table; every /search routing decision
        # goes through router.pick — never by indexing a routes dict
        # (tools/check_transport_usage.py enforces that statically)
        self.router = ReplicaRouter(self.breaker)
        self._failovers = 0
        self._partials = 0
        self._rediscoveries = 0
        self._closed = threading.Event()
        self._rediscover_thread: threading.Thread | None = None
        self._hedge_exec: ThreadPoolExecutor | None = None
        # persistent scatter pool (no per-search thread churn)
        self._pool = ThreadPoolExecutor(
            max_workers=max_threads, thread_name_prefix="dispatch"
        )
        # pod-local mesh dispatch (consulted per query in search()):
        # dataset groups resolvable on the local device mesh ride ONE
        # compiled launch instead of the thread/HTTP scatter. Cheap to
        # construct — device probing and the stack build are deferred
        # to first use / warmup.
        self.mesh_tier: MeshDispatchTier | None = None
        eng_cfg = getattr(self.config, "engine", None)
        if local is not None and getattr(eng_cfg, "mesh_dispatch", True):
            self.mesh_tier = MeshDispatchTier(
                local,
                min_shards=getattr(eng_cfg, "mesh_min_shards", 2),
                axis=getattr(eng_cfg, "mesh_axis", "d"),
            )
        # fleet telemetry federation (ISSUE 12): worker /ops/digest
        # collection + the /fleet/status rollup. Construction is free —
        # digests are only polled when the view is read (lazily, at
        # most once per interval).
        obs_cfg = getattr(self.config, "observability", None)
        self.fleet = FleetView(
            self,
            interval_s=getattr(obs_cfg, "fleet_digest_interval_s", 10.0),
        )
        # per-worker in-flight /search legs (guarded by _sc_lock): the
        # migration cut-over drains a retired source to zero before
        # the source may drop the dataset — a leg started before the
        # retire must finish against a worker that still has the rows
        self._inflight: dict[str, int] = {}
        # live shard migration (ISSUE 16): copy -> dual-serve ->
        # canary-verify -> cut-over, exposed at /fleet/migrate.
        # Constructed lazily-cheap like the fleet view; import here
        # (not module top) because migration.py never imports dispatch
        # but keeping the one-way edge explicit costs nothing.
        from .migration import MigrationController

        self.migrations = MigrationController(self)

    # headers are passed only when there is something to carry (a
    # configured token, an ambient trace id) AND the transport's
    # signature accepts them — legacy 3-arg injected transports keep
    # working, they just don't propagate the trace header. A token with
    # a 3-arg transport still passes headers (auth is correctness; the
    # loud TypeError beats silently-unauthenticated calls).
    def _post_auth(self, url: str, doc: dict, timeout_s: float):
        headers: dict = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        ctx = current_context()
        if ctx is not None and self._post_takes_headers:
            # every coordinator->worker hop carries the request's trace
            # id so worker-side spans share it (the Dapper propagation
            # the reference's SNS fan-out never had)
            headers[TRACE_HEADER] = ctx.trace_id
        if headers:
            return self._post(url, doc, timeout_s, headers)
        return self._post(url, doc, timeout_s)

    def _get_auth(self, url: str, timeout_s: float):
        if self._token:
            return self._get(
                url, timeout_s, {"Authorization": f"Bearer {self._token}"}
            )
        return self._get(url, timeout_s)

    def warmup(self) -> int:
        """Pre-compile the local engine's kernel programs (remote
        workers warm their own at their server start); returns the
        program count — the coordinator deployment must not be the one
        shape the soak-tail fix skips."""
        warm = getattr(self.local, "warmup", None)
        n = warm() if warm else 0
        if self.mesh_tier is not None:
            n += self.mesh_tier.warmup()
        return n

    def register_metrics(self, registry) -> None:
        """Coordinator telemetry: per-worker breaker series, the data
        plane's transport series (connection reuse, RTT histogram,
        hedges) and short-circuit counter, plus the local engine's
        instruments (batcher, response cache, dispatch counters) when
        one is wired."""
        register_breaker_metrics(registry, lambda: self.breaker)
        register_transport_metrics(registry)
        register_dispatch_metrics(registry, self.dispatch_stats)
        reg = getattr(self.local, "register_metrics", None)
        if reg is not None:
            reg(registry)

    @property
    def short_circuits(self) -> int:
        """Boolean fan-outs answered before the full worker drain."""
        with self._sc_lock:
            return self._short_circuits

    def dispatch_stats(self) -> dict:
        """The fan-out counters behind the ``dispatch.*`` / ``routing.*``
        series (register_dispatch_metrics reads through this so a
        swapped engine stays observable)."""
        mesh = (
            self.mesh_tier.stats() if self.mesh_tier is not None else {}
        )
        fleet = self.fleet.stats()
        mig = self.migrations.counters()
        with self._sc_lock:
            return {
                "short_circuits": self._short_circuits,
                "failovers": self._failovers,
                "partial_responses": self._partials,
                "rediscoveries": self._rediscoveries,
                "replicas": self.router.replica_count(),
                "mesh_dispatches": mesh.get("dispatches", 0),
                "mesh_fallbacks": mesh.get("fallbacks", 0),
                "mesh_gather_rows": mesh.get("gather_rows", 0),
                "mesh_refusals": mesh.get("refusals", {}),
                "fleet_polls": fleet.get("polls", 0),
                "fleet_reachable": fleet.get("reachable", 0),
                "fleet_divergent": fleet.get("divergent", 0),
                "migration_started": mig.get("started", 0),
                "migration_completed": mig.get("completed", 0),
                "migration_rolled_back": mig.get("rolled_back", 0),
                "migration_bytes_copied": mig.get("bytes_copied", 0),
            }

    def route_table_age_s(self) -> float | None:
        """Seconds since the last completed discovery pass published
        the replica table (None before first discovery) — the
        staleness signal ``/debug/status`` reports."""
        with self._routes_lock:
            t = self._last_publish_mono
        return None if t is None else time.monotonic() - t

    def worker_stats(self) -> dict[str, dict]:
        """Per-worker health rollup for ``/debug/status``: breaker
        state, recent median RTT, and whether the latest discovery
        pass reached it. Local state only — never a worker call."""
        with self._routes_lock:
            reachable = set(self._reachable)
        return {
            url: {
                "state": self.breaker.state(url),
                "medianRttMs": self.router.median_rtt_ms(url),
                "reachable": url in reachable,
            }
            for url in self.worker_urls
        }

    def unavailable_datasets(self) -> list[str]:
        """Datasets in the route table with no live replica (every
        copy's circuit open) — served as partial results until the
        background rediscovery heals a route. Local state only
        (breaker observation), so ``/ready`` can report it without a
        worker round-trip."""
        return sorted(
            ds
            for ds, urls in self.router.table().items()
            if urls and not any(self.router.live(u) for u in urls)
        )

    def close(self) -> None:
        """Release the scatter/hedge pools, stop the rediscovery loop,
        and drop the pooled worker connections (engines are long-lived;
        call this when rebuilding one on config/route changes)."""
        self._closed.set()
        self.migrations.close()
        if self.mesh_tier is not None:
            self.mesh_tier.close()
        self._pool.shutdown(wait=False, cancel_futures=True)
        # under _sc_lock, paired with _hedge_pool's closed check: a
        # hedge executor created concurrently with close() must not
        # escape shutdown (its non-daemon threads would outlive the
        # engine and stall interpreter exit)
        with self._sc_lock:
            hedge, self._hedge_exec = self._hedge_exec, None
        if hedge is not None:
            hedge.shutdown(wait=False, cancel_futures=True)
        if self._owns_transport and self.transport is not None:
            self.transport.close()

    def __enter__(self) -> "DistributedEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- discovery ----------------------------------------------------------

    @staticmethod
    def _group_replicas(ds: str, entries: list[tuple[str, str]]) -> tuple:
        """The replica urls for one dataset, grouped by per-dataset
        fingerprint: identical shard copies are interchangeable, and so
        are **tail-superset** copies (ROADMAP 4a): same base artifacts,
        delta tails forming a subset chain — a replica mid-rolling-
        ingest (deeper tail) is a FRESHER copy of the same dataset,
        not a divergence loser, and the migration dual-serve window
        (target standing one delta behind the source for an instant)
        rides the same relation. On a real mismatch the newest copy
        wins (row-count freshness, :func:`_fingerprint_freshness`) and
        the stale workers are excluded from this dataset's routes —
        failover to a divergent copy would silently change the answer
        mid-request."""
        by_fp: dict[str, list[str]] = {}
        for url, fp in entries:
            by_fp.setdefault(fp, []).append(url)
        if len(by_fp) == 1:
            return tuple(next(iter(by_fp.values())))
        parts = {fp: _fingerprint_parts(fp) for fp in by_fp}
        if all(p is not None for p in parts.values()):
            bases = {p[0] for p in parts.values()}
            tails = sorted(
                (p[1] for p in parts.values()), key=len
            )
            chain = all(
                a <= b for a, b in zip(tails, tails[1:])
            )
            if len(bases) == 1 and chain:
                # every copy is routable; deepest tail first so the
                # back-compat primary view (routes()[ds] = urls[0])
                # points at the freshest copy
                ordered = sorted(
                    by_fp,
                    key=lambda fp: (_fingerprint_freshness(fp), fp),
                    reverse=True,
                )
                publish_event(
                    "routing.tail_superset",
                    dataset=ds,
                    copies=len(by_fp),
                    replicas=sum(len(u) for u in by_fp.values()),
                )
                return tuple(
                    u for fp in ordered for u in sorted(by_fp[fp])
                )
        win = max(by_fp, key=lambda fp: (_fingerprint_freshness(fp), fp))
        losers = sorted(
            u for fp, urls in by_fp.items() if fp != win for u in urls
        )
        log.warning(
            "dataset %s: divergent index copies across workers — routing "
            "to the newest copy on %s, excluding stale %s (re-ingest or "
            "POST /reload the excluded workers)",
            ds,
            sorted(by_fp[win]),
            losers,
        )
        return tuple(by_fp[win])

    def _discover(self) -> dict[str, tuple[str, ...]]:
        found: dict[str, list[tuple[str, str]]] = {}  # url -> [(ds, fp)]
        fps: dict[str, str] = {}
        for url in self.worker_urls:
            try:
                status, doc = self._get_auth(f"{url}/datasets", self.timeout_s)
            except urllib.error.HTTPError as e:
                if e.code in (401, 403):
                    # auth failure must not masquerade as a network
                    # problem: an operator chasing 'unreachable' would
                    # debug routing, not the token
                    log.error(
                        "worker %s rejected coordinator credentials "
                        "(http %s): check BEACON_WORKER_TOKEN / --token",
                        url,
                        e.code,
                    )
                else:
                    log.warning("worker %s unreachable: %s", url, e)
                continue
            except Exception as e:
                log.warning("worker %s unreachable: %s", url, e)
                continue
            if status in (401, 403):
                log.error(
                    "worker %s rejected coordinator credentials (http %s): "
                    "check BEACON_WORKER_TOKEN / --token",
                    url,
                    status,
                )
                continue
            if status != 200:
                continue
            fps[url] = doc.get("fingerprint", "")
            # answering discovery REVIVES an open/half-open route (the
            # rediscovery loop's whole point; like reload_workers'
            # answered -> record_success revival) — but must NOT touch
            # a CLOSED circuit's failure count: /datasets answering
            # says nothing about /search health, and resetting the
            # count every pass would keep a search-broken worker's
            # breaker from ever opening
            if self.breaker.state(url) != CLOSED:
                self.breaker.record_success(url)
            ds_fps = doc.get("dataset_fingerprints") or {}
            found[url] = [
                (ds, str(ds_fps.get(ds, fps[url])))
                for ds in doc.get("datasets", [])
            ]
        with self._routes_lock:
            # per-worker retention: a worker that ANSWERED owns its
            # route contribution outright (dropping a dataset it no
            # longer advertises is correct); a worker that did NOT
            # answer keeps its last-known-good contribution — a
            # partially-successful pass must not silently vanish a
            # dead worker's datasets from the table (they must keep
            # degrading to marked partial results, not to unmarked
            # empty answers)
            merged: dict[str, list[tuple[str, str]]] = {}
            for url in self.worker_urls:
                per = found.get(url)
                if per is None:
                    per = self._last_seen.get(url, [])
                    # warn ONCE per outage, not once per rediscovery
                    # pass (a decommissioned URL left in worker_urls
                    # would otherwise spam this line forever)
                    if per and url not in self._retention_warned:
                        self._retention_warned.add(url)
                        log.warning(
                            "worker %s unreachable during discovery; "
                            "keeping its last-known-good routes "
                            "(%d dataset(s), may be stale) until it "
                            "answers",
                            url,
                            len(per),
                        )
                else:
                    self._retention_warned.discard(url)
                for ds, fp in per:
                    merged.setdefault(ds, []).append((url, fp))
            table = {
                ds: self._group_replicas(ds, entries)
                for ds, entries in merged.items()
            }
            self._discovered = True
            self._last_seen.update(found)
            self._reachable = set(found)
            # last-known fingerprints are retained for unreachable
            # workers too: the aggregate index identity (cache keys)
            # must not flap with reachability
            self._fingerprints.update(fps)
            self._last_publish_mono = time.monotonic()
            self.router.publish(table)
        # the router's view, not the locally computed table: publish()
        # filters migration cut-over pins inside its critical section,
        # and callers must never see a retired route resurrected
        return self.router.table()

    def replica_table(
        self, refresh: bool = False
    ) -> dict[str, tuple[str, ...]]:
        """dataset -> replica urls, discovering on first use."""
        with self._routes_lock:
            discovered = self._discovered
        if not discovered or refresh:
            return self._discover()
        return self.router.table()

    def routes(self, refresh: bool = False) -> dict[str, str]:
        """dataset -> primary worker url (back-compat view of the
        replica table; routing decisions go through the router)."""
        return {
            ds: urls[0]
            for ds, urls in self.replica_table(refresh).items()
            if urls
        }

    # -- fleet membership (the migration grow/shrink seam) -------------------

    def add_worker(self, url: str) -> bool:
        """Admit ``url`` to the fleet and run a discovery pass so its
        datasets enter the routing table (the migration dual-serve
        publish). Returns False when already a member."""
        with self._routes_lock:
            if url in self.worker_urls:
                return False
            self.worker_urls.append(url)
        # discovery takes _routes_lock itself — must run outside it
        self._discover()
        return True

    def remove_worker(self, url: str) -> bool:
        """Drop ``url`` from the fleet and republish routes without
        its contribution (its last-known-good retention included)."""
        with self._routes_lock:
            if url not in self.worker_urls:
                return False
            self.worker_urls.remove(url)
            self._last_seen.pop(url, None)
            self._fingerprints.pop(url, None)
            self._reachable.discard(url)
            self._retention_warned.discard(url)
        self._discover()
        return True

    def inflight(self, url: str) -> int:
        """In-flight /search legs against ``url`` right now — the
        cut-over drain signal (a retired source must answer its
        started legs before it may drop the dataset)."""
        with self._sc_lock:
            return self._inflight.get(url, 0)

    # -- background rediscovery --------------------------------------------

    def _nudge_rediscovery(self) -> None:
        """Arm the healing loop (worker failure / breaker-open saw a
        dead route): one daemon thread re-runs discovery until every
        configured worker answers again, so routes heal without a
        manual reload_workers. Idempotent while a loop is running."""
        if self._closed.is_set():
            return
        with self._routes_lock:
            t = self._rediscover_thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(
                target=self._rediscover_loop,
                daemon=True,
                name="dispatch-rediscovery",
            )
            self._rediscover_thread = t
        t.start()

    def _rediscover_loop(self) -> None:
        delay = self.REDISCOVERY_INTERVAL_S
        while not self._closed.wait(delay):
            # a permanently-gone worker (decommissioned URL still in
            # worker_urls) must not spin full-rate discovery forever:
            # back off toward a slow steady probe
            delay = min(delay * 2, max(30.0, self.REDISCOVERY_INTERVAL_S))
            try:
                self._discover()
            except Exception:
                log.exception("route rediscovery pass failed")
            with self._sc_lock:
                self._rediscoveries += 1
            with self._routes_lock:
                # healed = every configured worker ANSWERED the latest
                # pass (not merely has a retained fingerprint from
                # before it died)
                reachable = len(self._reachable)
                healed = all(
                    url in self._reachable for url in self.worker_urls
                )
            publish_event(
                "routing.rediscovery",
                healed=healed,
                reachable=reachable,
                workers=len(self.worker_urls),
            )
            if healed:
                return

    def datasets(self) -> list[str]:
        out = set(self.routes())
        if self.local is not None:
            out |= set(self.local.datasets())
        return sorted(out)

    def index_fingerprint(self) -> str:
        self.routes()
        with self._routes_lock:
            parts = [
                f"{url}={fp}"
                for url, fp in sorted(self._fingerprints.items())
            ]
        if self.local is not None:
            parts.append(f"local={self.local.index_fingerprint()}")
        return "&&".join(parts)

    # -- query path ---------------------------------------------------------

    def _call_worker(
        self, url: str, payload: VariantQueryPayload, deadline=None,
        ctx=None,
    ):
        # the request context rides in explicitly like the deadline
        # (pool thread: the submitting request's thread-locals are not
        # visible) and is re-installed so the trace header and outcome
        # notes work from here down
        with request_context(ctx if ctx is not None else current_context()):
            return self._call_worker_traced(url, payload, deadline)

    def call_replica(
        self, url: str, payload: VariantQueryPayload
    ) -> list[VariantSearchResponse]:
        """One direct ``/search`` against a SPECIFIC replica — no
        failover, no hedging, no routing. The canary prober's
        per-replica probe seam (canary.py): the whole point is to
        exercise exactly one copy and judge its answer, which the
        routed paths' fault tolerance would mask. Probe RTTs do NOT
        feed the router's rings: sub-millisecond boolean probes would
        otherwise dominate the p2c comparison and drag the adaptive
        hedge p95 to probe scale on an idle fleet — every real query
        would then hedge immediately when traffic resumes."""
        return self._call_worker_traced(url, payload, note_rtt=False)

    def _call_worker_traced(
        self, url: str, payload: VariantQueryPayload, deadline=None,
        *, note_rtt: bool = True,
    ):
        # in-flight leg accounting brackets the WHOLE leg (retries
        # included): the migration cut-over drains inflight(url) to
        # zero before the retired source may drop the dataset
        with self._sc_lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1
        try:
            return self._call_worker_leg(
                url, payload, deadline, note_rtt=note_rtt
            )
        finally:
            with self._sc_lock:
                n = self._inflight.get(url, 0) - 1
                if n <= 0:
                    self._inflight.pop(url, None)
                else:
                    self._inflight[url] = n

    def _call_worker_leg(
        self, url: str, payload: VariantQueryPayload, deadline=None,
        *, note_rtt: bool = True,
    ):
        if not self.breaker.allow(url):
            # fast-fail: the route failed repeatedly and its reset
            # window hasn't lapsed — don't spend timeout_s finding out.
            # An open route also arms the background rediscovery loop
            # (the worker may have restarted with fresh shards).
            annotate(breaker="open")
            plan_stage(
                "worker",
                decision="fast_fail",
                reason="breaker_open",
                worker=url,
            )
            self._nudge_rediscovery()
            raise CircuitOpen(f"worker {url}: circuit open")
        # serialize ONCE: the pooled transport ships these bytes
        # verbatim (the old path built a dict just for the transport to
        # re-dumps it); injected dict-contract transports still get one
        doc = (
            payload.dumps().encode()
            if self._post_bytes_ok
            else json.loads(payload.dumps())
        )
        # the request deadline is passed EXPLICITLY by search(): this
        # runs on a pool thread, where the submitting request's
        # thread-local scope is not visible
        if deadline is None:
            deadline = current_deadline()
        last = None
        # one span per worker leg (its own root tree on this pool
        # thread, tied to the request by trace id): on success the
        # worker's side-channel span summary grafts in as child spans,
        # so /_trace?trace_id= shows the coordinator->worker waterfall
        # with network time separated from worker-stage time
        with span("dispatch.worker_call", url=url) as wsp:
            for attempt in range(self.retries + 1):
                timeout_s = deadline.clamp(self.timeout_s)
                if timeout_s is not None and timeout_s <= 0:
                    deadline.check(f"worker {url} call")
                t0 = time.perf_counter()
                try:
                    fault_point("worker.http", url)
                    status, out = self._post_auth(
                        f"{url}/search", doc, timeout_s
                    )
                except Exception as e:
                    last = WorkerError(f"{url}: {e}")
                else:
                    if status == 200:
                        # successful RTTs feed the router's p2c
                        # comparison and the adaptive replica-hedge
                        # delay — and the request's cost vector: the
                        # worker was occupied that long on this
                        # request's behalf (ISSUE 11)
                        rtt_s = time.perf_counter() - t0
                        if note_rtt:
                            self.router.note_rtt(url, rtt_s)
                        charge_cost(worker_rtt_ms=rtt_s * 1e3)
                        self.breaker.record_success(url)
                        _graft_worker_spans(
                            wsp, url, out.get("meta"), rtt_s
                        )
                        return [
                            VariantSearchResponse(**r)
                            for r in out.get("responses", [])
                        ]
                    last = WorkerError(
                        f"{url}: http {status}: {out.get('error')}"
                    )
                if attempt < self.retries:  # no dead sleep after final try
                    time.sleep(min(0.05 * (attempt + 1), 1.0))
        if deadline.expired():
            # the REQUEST ran out of time, not the worker out of
            # health: a deadline-clamped timeout must not count against
            # the route (tight-deadline traffic would open the circuit
            # on a perfectly healthy worker and 503 everyone else)
            raise DeadlineExceeded(
                f"worker {url}: request deadline expired"
            ) from last
        self.breaker.record_failure(url)
        self._nudge_rediscovery()
        raise last

    # -- replica hedging + failover ----------------------------------------

    def _hedge_pool(self) -> ThreadPoolExecutor:
        with self._sc_lock:
            if self._hedge_exec is None:
                if self._closed.is_set():
                    # a leg draining through close() must not create an
                    # executor nothing will ever shut down
                    raise WorkerError("engine closed")
                # every multi-replica leg's PRIMARY rides this pool
                # when hedging is armed, so it must never cap fan-out
                # below the scatter pool: size for max_threads
                # primaries plus their hedges (threads spawn lazily —
                # idle fleets never pay for the ceiling). The
                # started-event gate below still stops a queued
                # primary from triggering load-doubling hedges if the
                # pool somehow saturates.
                self._hedge_exec = ThreadPoolExecutor(
                    max_workers=max(8, 2 * self.max_threads),
                    thread_name_prefix="dispatch-hedge",
                )
            return self._hedge_exec

    def _hedge_candidate(
        self, ds_list: list[str], avoid: set[str]
    ) -> str | None:
        """A live replica (other than ``avoid``) serving EVERY dataset
        in the group, fastest-first, or None when the group has no
        common alternative (single-replica fleets never hedge)."""
        common: set[str] | None = None
        for ds in ds_list:
            urls = set(self.router.replicas(ds))
            common = urls if common is None else common & urls
        cands = sorted((common or set()) - avoid)
        live = [u for u in cands if self.router.live(u)]
        if not live:
            return None
        return min(live, key=lambda u: self.router._rtt(u) or 0.0)

    def _call_replicas(
        self, url: str, payload: VariantQueryPayload, deadline, tried: set
    ) -> list[VariantSearchResponse]:
        """One replica-hedged /search leg (Dean & Barroso promoted from
        scan slices to full searches): the primary runs on the hedge
        pool; if it has not answered within the hedge delay, the same
        sub-query races on a second replica and the first success wins.
        /search is an idempotent read, so the loser's duplicate
        execution only costs its CPU — the hedge still only fires once
        the primary actually STARTED (a primary queued behind a full
        pool must not trigger load-doubling hedges), mirroring the
        transport's started/not-started replay discipline. A hedge
        target that also failed is added to ``tried`` so failover does
        not re-try it."""
        delay = None
        if getattr(self.transport_config, "replica_hedge", True):
            delay = self.router.hedge_delay(
                getattr(self.transport_config, "hedge_delay_s", 0.0)
            )
        other = (
            self._hedge_candidate(payload.dataset_ids or [], {url} | tried)
            if delay is not None
            else None
        )
        if delay is None or other is None:
            return self._call_worker_traced(url, payload, deadline)
        pool = self._hedge_pool()
        ctx = current_context()
        started = threading.Event()

        def primary():
            started.set()
            return self._call_worker(url, payload, deadline, ctx)

        futs = {pool.submit(primary): url}
        done, _pending = futures_mod.wait(futs, timeout=delay)
        if not done and started.is_set():
            note_hedge()  # process-wide transport.hedges counter
            annotate(replica_hedge=True)
            plan_stage(
                "worker", decision="hedged", primary=url, hedge=other
            )
            publish_event("dispatch.hedge", primary=url, hedge=other)
            futs[
                pool.submit(self._call_worker, other, payload, deadline, ctx)
            ] = other
        pending = set(futs)
        last: Exception | None = None
        while pending:
            done, pending = futures_mod.wait(
                pending, return_when=futures_mod.FIRST_COMPLETED
            )
            for f in done:
                u = futs[f]
                try:
                    out = f.result()
                except Exception as e:
                    last = e
                    if u != url:
                        tried.add(u)
                    continue
                if u != url:  # the hedge answered first
                    publish_event(
                        "dispatch.hedge_won", winner=u, primary=url
                    )
                return out
        raise last

    def _search_group(
        self, url, ds_list, payload: VariantQueryPayload, deadline, ctx
    ):
        # like _call_worker: the request context rides in explicitly
        # (pool thread) so trace headers and outcome notes keep working
        with request_context(ctx if ctx is not None else current_context()):
            return self._search_group_traced(url, ds_list, payload, deadline)

    def _search_group_traced(
        self, url: str, ds_list: list[str], payload, deadline
    ) -> tuple[list[VariantSearchResponse], list[str], Exception | None]:
        """One scatter leg with automatic failover: the group's primary
        is tried first (hedged); on a worker error or open circuit each
        dataset re-routes to its next untried replica — never the same
        copy twice — until ``resilience.failover_retries`` extra
        replicas have been spent or the replica set is exhausted.
        Returns ``(responses, failed_datasets, first_error)``; only a
        deadline expiry raises (no time left to fail over)."""
        res = getattr(self.config, "resilience", None)
        max_extra = getattr(res, "failover_retries", 2)
        responses: list[VariantSearchResponse] = []
        failed: list[str] = []
        first_err: Exception | None = None
        work = [(url, list(ds_list), {url})]
        while work:
            u, dss, tried = work.pop()
            sub = dataclasses.replace(payload, dataset_ids=dss)
            try:
                responses.extend(
                    self._call_replicas(u, sub, deadline, tried)
                )
                continue
            except DeadlineExceeded:
                raise  # the request is out of time — no failover
            except (WorkerError, CircuitOpen) as e:
                if first_err is None:
                    first_err = e
            if len(tried) > max_extra:
                # primary + max_extra replicas all failed: give these
                # datasets up to the partial-results path
                failed.extend(dss)
                continue
            regroup: dict[str, list[str]] = {}
            for ds in dss:
                nxt = self.router.pick(ds, avoid=tried)
                if nxt is None:
                    failed.append(ds)
                else:
                    regroup.setdefault(nxt, []).append(ds)
            for nu, nds in sorted(regroup.items()):
                with self._sc_lock:
                    self._failovers += 1
                annotate(failover=True)
                plan_stage(
                    "worker",
                    decision="failover",
                    failed=u,
                    to=nu,
                    datasets=len(nds),
                )
                publish_event(
                    "dispatch.failover",
                    failed=u,
                    to=nu,
                    datasets=len(nds),
                )
                work.append((nu, nds, tried | {nu}))
        return responses, failed, first_err

    def search(
        self, payload: VariantQueryPayload
    ) -> list[VariantSearchResponse]:
        with span("dispatch.search") as sp:
            current_deadline().check("dispatch.search")
            table = self.replica_table()
            wanted = payload.dataset_ids or self.datasets()
            local_ds = (
                set(self.local.datasets()) if self.local is not None else set()
            )
            if any(ds not in local_ds and ds not in table for ds in wanted):
                # an explicitly requested dataset may have been ingested
                # after the last discovery: refresh once before treating
                # it as unknown (a stale skip would be indistinguishable
                # from 'no variants found')
                table = self.replica_table(refresh=True)
            # pod-local mesh consult: dataset groups resolvable on the
            # local device mesh ride ONE compiled launch (below, on
            # this thread, concurrent with the worker scatter) instead
            # of the thread/HTTP scatter
            mesh_ds: set = set()
            tier = self.mesh_tier
            if tier is not None:
                try:
                    mesh_ds = tier.resolve(
                        [ds for ds in wanted if ds in local_ds], payload
                    )
                except Exception:
                    log.exception("mesh tier resolve failed")
                    mesh_ds = set()
            by_worker: dict[str, list[str]] = {}
            local_wanted: list[str] = []
            for ds in wanted:
                if ds in mesh_ds:
                    continue
                if ds in local_ds:
                    local_wanted.append(ds)
                elif ds in table:
                    # p2c primary pick; failover inside the group leg
                    # walks the remaining replicas
                    primary = self.router.pick(ds)
                    if primary is not None:
                        by_worker.setdefault(primary, []).append(ds)
                # still-unknown datasets are skipped, like unmatched
                # chromosomes (get_matching_chromosome filter)

            tasks = sorted(by_worker.items())
            # a boolean-granularity fan-out with no resultset detail
            # requested is a logical OR: the first hit anywhere decides
            # the answer, so the rest of the scatter is abandoned.
            # include_datasets != NONE keeps the full drain — the
            # caller asked for per-dataset responses, and engine-level
            # parity with a single engine must hold for them
            # (knob: transport.bool_short_circuit)
            short_circuit_ok = (
                payload.requested_granularity == "boolean"
                and payload.include_datasets == "NONE"
                and getattr(
                    self.transport_config, "bool_short_circuit", True
                )
            )
            short_circuited = False
            responses: list[VariantSearchResponse] = []
            unavailable: list[str] = []
            group_err: Exception | None = None
            deadline = current_deadline()
            ctx = current_context()
            futures: dict = {}
            if tasks:
                futures = {
                    self._pool.submit(
                        self._search_group, url, ds_list, payload,
                        deadline, ctx,
                    ): url
                    for url, ds_list in tasks
                }
            # which tier is serving this query (the slow-query log's
            # dispatch attribution)
            if mesh_ds:
                tier_label = (
                    "mesh" if not (tasks or local_wanted) else "mixed"
                )
                annotate(dispatch_tier=tier_label)
                plan_stage(
                    "tier",
                    decision=tier_label,
                    mesh_datasets=len(mesh_ds),
                    worker_groups=len(tasks),
                )
            elif tasks:
                annotate(dispatch_tier="http")
                plan_stage(
                    "tier", decision="http", worker_groups=len(tasks)
                )
            elif local_wanted:
                annotate(dispatch_tier="local")
                plan_stage("tier", decision="local")
            # the POD-LOCAL mesh leg runs on this thread concurrently
            # with the worker scatter: one compiled launch answers the
            # whole local dataset group. A mesh failure falls back ONCE
            # to the scatter planes (pooled HTTP where a worker route
            # exists, the local engine's own dispatch otherwise) and
            # trips mesh.fallbacks; a deadline expiry is the REQUEST's
            # fault and never falls back (no time left to re-run).
            first_err: BaseException | None = None
            if mesh_ds:
                try:
                    responses.extend(tier.search(payload, mesh_ds))
                except DeadlineExceeded as e:
                    first_err = e
                except Exception as e:
                    tier.note_fallback()
                    annotate(mesh_fallback=True)
                    plan_stage(
                        "fallback",
                        decision="scatter",
                        reason="mesh_error",
                        datasets=len(mesh_ds),
                    )
                    publish_event(
                        "mesh.fallback",
                        datasets=len(mesh_ds),
                        error=type(e).__name__,
                    )
                    log.warning(
                        "mesh tier failed for %d dataset(s); falling "
                        "back to the scatter path (%s)",
                        len(mesh_ds),
                        e,
                    )
                    fb_by_worker: dict[str, list[str]] = {}
                    for ds in sorted(mesh_ds):
                        if ds in table:
                            primary = self.router.pick(ds)
                            if primary is not None:
                                fb_by_worker.setdefault(
                                    primary, []
                                ).append(ds)
                                continue
                        if ds in local_ds:
                            local_wanted.append(ds)
                    for url, ds_list in sorted(fb_by_worker.items()):
                        futures[
                            self._pool.submit(
                                self._search_group, url, ds_list,
                                payload, deadline, ctx,
                            )
                        ] = url
            # the LOCAL shard search runs on this thread CONCURRENTLY
            # with the worker fan-out (it used to wait for the full
            # drain) — the coordinator's own datasets no longer sit
            # behind the slowest worker's RTT
            if local_wanted:
                try:
                    responses.extend(
                        self.local.search(
                            dataclasses.replace(
                                payload, dataset_ids=local_wanted
                            )
                        )
                    )
                except Exception as e:
                    # recorded, not raised: the worker futures must
                    # still be drained (stranded tasks starve the pool)
                    first_err = e
            pending = set(futures)
            # hit_seen is order-independent: once ANY leg of a boolean
            # OR reports a hit, the aggregate answer is decided — a
            # sibling's error cannot change it and must not fail the
            # query, whether it arrived before or after the hit
            hit_seen = short_circuit_ok and any(
                r.exists for r in responses
            )
            if not hit_seen:
                # fan-in consumes futures AS COMPLETED (incremental
                # aggregation, a hit can short-circuit) but still
                # settles every one before raising: a fast-failing
                # worker must not strand slow siblings' tasks in the
                # shared pool. The drain is deadline-bounded: on expiry
                # still-running futures are left to finish on the pool
                # (bounded by their own clamped socket timeouts) and
                # the caller gets DeadlineExceeded now.
                while pending:
                    done, pending = futures_mod.wait(
                        pending,
                        timeout=deadline.remaining(),
                        return_when=futures_mod.FIRST_COMPLETED,
                    )
                    if not done:  # deadline expired mid-drain
                        if first_err is None:
                            first_err = DeadlineExceeded(
                                "worker fan-in: deadline exceeded"
                            )
                        break
                    for f in done:
                        try:
                            out, failed, gerr = f.result()
                        except (
                            Exception,
                            futures_mod.CancelledError,
                        ) as e:
                            # CancelledError (close() mid-search) is a
                            # BaseException: it must not abort the drain
                            if first_err is None:
                                first_err = e
                        else:
                            responses.extend(out)
                            if failed:
                                # this group exhausted its replicas for
                                # these datasets: candidate for partial
                                # results, not an immediate failure
                                unavailable.extend(failed)
                                if group_err is None:
                                    group_err = gerr
                            if short_circuit_ok and any(
                                r.exists for r in out
                            ):
                                hit_seen = True
                    if hit_seen:
                        break
            if hit_seen:
                if pending:
                    # abandon the rest of the scatter: queued futures
                    # are cancelled outright, in-flight ones finish on
                    # the pool and are ignored — for a boolean query
                    # their answers cannot change the aggregate. The
                    # counter only ticks when a drain was actually cut
                    # short.
                    for f in pending:
                        f.cancel()
                    short_circuited = True
                    with self._sc_lock:
                        self._short_circuits += 1
                    annotate(short_circuit=True)
            elif first_err is not None:
                # a local-engine error, deadline expiry, or cancelled
                # drain is a real failure — partial results only cover
                # unreachable replicas
                raise first_err
            elif unavailable:
                unavailable = sorted(set(unavailable))
                self._nudge_rediscovery()
                if not getattr(
                    getattr(self.config, "resilience", None),
                    "partial_results",
                    True,
                ):
                    raise group_err or WorkerError(
                        "no reachable replica for dataset(s): "
                        + ", ".join(unavailable)
                    )
                # graceful degradation: answer with the datasets that
                # responded and mark the unreachable ones — the API
                # layer stamps meta.unavailableDatasets + a warning
                # instead of turning one dead fleet corner into a 502
                with self._sc_lock:
                    self._partials += 1
                annotate(unavailable_datasets=tuple(unavailable))
                plan_stage(
                    "fallback",
                    decision="partial",
                    reason="no_replica",
                    datasets=len(unavailable),
                )
                publish_event(
                    "dispatch.partial", datasets=list(unavailable)
                )
                log.warning(
                    "partial results: no reachable replica for %s (%s)",
                    unavailable,
                    group_err,
                )
            responses.sort(key=lambda r: (r.dataset_id, r.vcf_location))
            sp.note(
                workers=len(tasks),
                responses=len(responses),
                short_circuit=short_circuited,
                unavailable=len(unavailable),
            )
        return responses


# -- multi-host compute -------------------------------------------------------


def init_multihost(
    coordinator_address: str, num_processes: int, process_id: int
) -> None:
    """jax.distributed bring-up for one jit program spanning hosts (the
    pod-scale analogue of the reference's 'serverless means arbitrary
    scalability' premise): after this, ``jax.devices()`` spans all hosts
    and ``mesh.make_mesh`` / ``sharded_query`` shard across DCN+ICI."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def main(argv: list[str] | None = None) -> None:
    """``python -m sbeacon_tpu.parallel.dispatch`` — run one worker host:
    load this host's index shards and serve the typed-payload protocol."""
    import argparse

    from ..config import BeaconConfig
    from ..engine import VariantEngine
    from ..ingest import IngestService

    p = argparse.ArgumentParser(description="beacon query worker host")
    # loopback by default: workers serve all genomic data unauthenticated
    # unless --token/BEACON_WORKER_TOKEN is set, so exposure beyond the
    # host must be an explicit choice (--host 0.0.0.0 on a private net)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=5100)
    p.add_argument("--data-root", default=None)
    p.add_argument(
        "--token",
        default=None,
        help="shared bearer token required on /search, /datasets and "
        "/scan (default: BEACON_WORKER_TOKEN env)",
    )
    p.add_argument(
        "--open-scan",
        action="store_true",
        help="serve /scan without a token (DANGEROUS: /scan reads "
        "arbitrary client-supplied locations; only on airtight private "
        "networks)",
    )
    args = p.parse_args(argv)

    config = BeaconConfig.from_env(args.data_root)
    from ..config import enable_persistent_compile_cache
    from ..harness.faults import install_from_env

    enable_persistent_compile_cache(config.storage.root)
    # worker-side chaos: BEACON_FAULT_PLAN arms seeded fault injection
    install_from_env()
    token = args.token if args.token is not None else config.auth.worker_token
    engine = VariantEngine(config)
    service = IngestService(config, engine=engine)
    n = service.load_all()
    # pre-compile every dispatchable program (first requests must not
    # pay cold compiles; near-free on restart with the persistent cache)
    n_warm = engine.warmup()
    worker = WorkerServer(
        engine,
        host=args.host,
        port=args.port,
        token=token,
        open_scan=args.open_scan,
        reload_fn=service.load_all,
    )
    print(
        f"worker serving on {args.host}:{args.port} ({n} shards, "
        f"datasets: {', '.join(engine.datasets()) or 'none'}, "
        f"{n_warm} kernel programs warmed)"
    )
    try:
        worker.server.serve_forever()
    finally:
        worker.server.server_close()


if __name__ == "__main__":  # pragma: no cover
    main()
